"""Continuous-batching serving engine: paged KV block pool, prefix
reuse by block-table aliasing, chunked prefill, and ONE compiled decode
(or speculative-verify) step for many concurrent requests.

The training path sits at the HBM roof (PERF.md r5); the unclaimed
serving throughput is workload shape — one request per batch underfills
the lanes and every new prompt length recompiles. This engine
reproduces Orca-style iteration-level scheduling (Yu et al., OSDI '22)
in JAX/XLA idiom: static shapes everywhere, slots instead of dynamic
allocation. On top of that base (PR 2), admission reuses and bounds
prefill work (PR 4), and the KV cache itself is paged (PR 7):

  * Paged KV block pool — the per-layer cache is a pool of fixed
    `kv_block_tokens`-token blocks ([NB, Bt, H, Dh]); each slot owns a
    block-table row mapping logical depth to physical blocks
    (PagedAttention, Kwon et al., SOSP '23; the reference's
    PoolAllocator.h/MemoryHandle pooled-allocator lineage). Admission
    RESERVES the request's worst case (ceil((T0+max_new)/Bt) blocks)
    so decode can never deadlock, but blocks are ALLOCATED on demand
    as the sequence grows, and retirement frees the allocated blocks
    plus the reserved-but-unreached tail — HBM residency and admission
    capacity scale with tokens actually resident, not
    MAX_SLOTS x max_len (the slab this replaces).
  * Prefix reuse = table aliasing — completed prompt prefixes publish
    their PHYSICAL block ids into the trie pool (prefix_cache.py,
    RadixAttention-style); a hit writes those ids into the new slot's
    table (ref-counted, zero-copy — no dynamic_update_slice copies).
    When the suffix must recompute a token inside a shared block (the
    maximal-reuse case: the whole prompt is cached but the last
    token's logits must be computed), the block is COPY-ON-WRITE
    privatised first, so a shared block is never written through.
  * Chunked prefill — the uncached suffix runs through
    models/transformer.paged_prefill_chunk in chunks of
    `prefill_chunk_tokens`, interleaved with batched decode steps
    (Sarathi-Serve, Agrawal et al., OSDI '24). Chunks pad to pow-2
    buckets, so distinct compiled prefill shapes stay O(log max_len).
  * One jitted decode step — advances all MAX_SLOTS slots at once with
    per-slot positions, temperatures, and sampling keys; cache buffers
    are donated. Traced exactly once per engine lifetime. The eight
    host side-band arrays (now including the block tables and budget
    limits) are device-resident between steps; the steady decode loop
    re-uploads a band only when a scheduler event dirties it (block
    tables change only every `kv_block_tokens` decodes, at the
    on-demand append).
  * Self-drafting speculative decoding — with `spec_draft_len` = K,
    each decode phase proposes K-1 draft tokens per slot by prompt
    lookup (the last bigram's previous continuation in
    prompt+generated context — "self-drafting": no draft model) and
    verifies the K-token window in ONE batched compiled step
    (models/transformer.paged_verify_step, traced exactly once). The
    acceptance rule emits the model's own tokens — greedy outputs are
    IDENTICAL to the plain decode path whatever the drafts were;
    drafts only change how many tokens one step emits. Sampled
    requests keep the fold_in(key, token_index) schedule (position i
    uses index counts+i), so sampling is spec-invariant too.
  * Iteration-level scheduling — ServingEngine.step() retires a slot
    the moment its request emits EOS or exhausts its budget and refills
    it from the FCFS queue on the SAME step; a saturated block pool
    QUEUES admissions (backpressure) instead of raising, and the next
    retirement's freed blocks admit them. A pending slot advances at
    most ONE chunk per step (chunks always interleave with decodes —
    the Sarathi policy); `max_prefills_per_step` additionally caps the
    TOTAL chunks across slots per step.
  * Request SLO (ISSUE 8) — `submit(deadline_at=)` carries an absolute
    latency budget enforced at every hop (pre-admission, prefill
    chunk, decode): past it the request finishes with the terminal
    verdict 'expired' (partial tokens kept) and the scheduler spends
    nothing further on it. `submit(resume_tokens=)` is token-level
    resume: tokens an earlier incarnation already emitted become
    prefill context (aliasing whatever the prefix pool holds), the
    sampling-key schedule continues at the resume index, and only the
    remainder is decoded — the fleet's hedged failover rides this to
    turn "restart from token zero" into "keep decoding". `cancel(rid)`
    claws back work the fleet hedged elsewhere (demotion).

Correctness bar (tested): greedy engine output per request is
token-identical to sequential models/transformer.generate() at every
slot count and admission order, for every cache path — cold miss,
aliased hit, copy-on-write, post-eviction re-admit — and with
speculative decoding on or off (spec changes WHEN tokens are produced,
never WHICH). Identity is at the TOKEN level: padded/chunked prefill
drifts from the unpadded oracle in the last ~2 float bits — reduction
order under masked padding, present since PR 2 — which never moves an
argmax in practice and is pinned by the fixed-seed drills. Sampled
requests use a per-request fold_in(key, token_index) schedule —
deterministic per request and independent of slot assignment and
spec_draft_len, but not the same key schedule as
generate(temperature>0).
"""

from __future__ import annotations

import collections
import os
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..distributed import fault_injection as _fi
from ..fluid.core.kernels_sequence import bucket_pow2
from ..models import transformer as tlm
from .adapters import AdapterPool
from .integrity import (_FP_RTOL, BlockFingerprints, IntegrityError,
                        ServingSentinel)
from .kv_blocks import KVBlockAllocator
from .kv_store import make_block_record, payload_crc
from .metrics import ServingMetrics
from .prefix_cache import PrefixCache, chain_keys
from .quantization import dequantize_params, quantize_params

__all__ = ["ServingEngine", "ServingHandle", "EngineFailed",
           "IntegrityError"]

_BANDS = ("tok", "pos", "alive", "temps", "counts", "base_keys",
          "tables", "limits", "aidx", "eos")

# bands the compiled decode window ADVANCES on device (ISSUE 19): a
# host-side event that dirties any of these between dispatch and sync
# (admission, retirement, cancel, expiry, spec acceptance) means the
# device copies no longer carry host truth — the async chain must
# break and re-upload. Everything else in _BANDS is host-truth only
# (the device never writes it), so uploading those mid-flight is safe.
_DEVICE_ADVANCED = frozenset(("tok", "pos", "alive", "counts"))


class EngineFailed(RuntimeError):
    """The engine (or the fleet replica driving it) died with requests
    pending. Raised by `ServingHandle.result()` instead of blocking
    forever, and by `ServingEngine.step()` on every call after the
    failure (the compiled steps donate their cache buffers, so a step
    that died mid-dispatch leaves the cache unusable — the latch keeps
    a half-donated cache from being stepped again). `replica` names the
    failing replica when the engine serves inside a fleet."""

    def __init__(self, msg: str, replica=None):
        super().__init__(msg)
        self.replica = replica


class ServingHandle(object):
    """Per-request future: filled in by the engine as steps run.
    `result()` drives the owning engine until this request completes
    (single-threaded engines have no background loop to wait on).

    Token-level resume (ISSUE 8): a handle submitted with
    `resume_tokens` carries tokens ALREADY emitted by an earlier
    incarnation of the same request (journaled by the fleet). The
    engine prefills prompt + resume as context — aliasing whatever
    prefix the pool holds — and decodes only the remainder: decode
    steps are never re-spent on journaled tokens, and the sampling key
    schedule continues at token index `resume_len`, so outputs stay
    token-identical to an uninterrupted run. `tokens` holds only the
    NEWLY generated tokens; `result()` returns the full sequence."""

    def __init__(self, engine, rid, prompt, max_new_tokens, temperature,
                 eos_id, seed, publish_len, deadline_at=None,
                 resume_tokens=None, adapter=None, handoff=None):
        self._engine = engine
        self.rid = rid
        self.prompt = prompt  # np.int32 [T0] — the ORIGINAL prompt
        self.resume_tokens = np.asarray(
            resume_tokens if resume_tokens is not None else [], np.int32)
        self.resume_len = int(self.resume_tokens.shape[0])
        # prefill context: prompt plus everything already emitted
        self.full_prompt = (
            np.concatenate([prompt, self.resume_tokens])
            if self.resume_len else prompt)
        # budget REMAINING: max_new_tokens is the request's original
        # total; the resumed tokens are already spent
        self.total_new_tokens = int(max_new_tokens)
        self.max_new_tokens = int(max_new_tokens) - self.resume_len
        self.temperature = float(temperature)
        self.eos_id = eos_id
        self.seed = seed
        # publish boundary: how many leading prompt tokens may be
        # published back to the prefix pool (None = whole prompt)
        self.publish_len = publish_len
        # absolute time.monotonic() budget (None = no deadline): the
        # engine expires the request at the next queue hop past it
        self.deadline_at = deadline_at
        # LoRA-style adapter name (ISSUE 12; None = the base model /
        # zero adapter) — resolved to a pool slot at admission
        self.adapter = adapter
        # durable-KV handoff package (ISSUE 16): the finished prefix's
        # serialized block records shipped by the fleet at migration/
        # failover. Consumed at admission — each record is token- and
        # fingerprint-verified before it enters the pool; outcome lands
        # in handoff_imported/handoff_fallback for the journal's done
        # side-band (the J011 fence)
        self.handoff = handoff
        self.handoff_imported = 0       # tokens imported clean
        self.handoff_fallback = False   # any re-prefill shortfall
        self.handoff_outcome = None     # set once the package is judged
        self.tokens: List[int] = []  # generated tokens (may include eos)
        self.done = False
        # 'eos' | 'budget' | 'expired' | 'cancelled'
        self.finish_reason: Optional[str] = None
        # set by ServingEngine.abort() when the engine dies with this
        # request pending: result() raises it instead of spinning on a
        # dead engine forever (ISSUE 6 satellite)
        self.error: Optional[BaseException] = None
        self.submit_t = time.monotonic()
        self.queue_wait_s: Optional[float] = None
        self.ttft_s: Optional[float] = None

    def result(self) -> np.ndarray:
        """Block (by stepping the engine) until done; returns the full
        sequence — prompt, then resumed tokens (if any), then this
        incarnation's generated tokens. An 'expired' verdict still
        returns (the partial sequence): at the engine level the
        deadline outcome is `finish_reason`, not an exception — the
        fleet layer turns it into `DeadlineExceeded` for its callers.
        Raises `EngineFailed` (naming the failing replica when the
        engine serves in a fleet) if the engine died with this request
        pending — including when a BACKGROUND thread owned the engine
        and crashed: the failure is propagated into the handle, never
        an indefinite block."""
        while not self.done:
            if self.error is not None:
                raise self.error
            if not self._engine.step():
                raise RuntimeError(
                    "engine made no progress but request %r is not done"
                    % self.rid
                )
        return np.concatenate(
            [self.full_prompt, np.asarray(self.tokens, np.int32)]
        )


class ServingEngine(object):
    """Continuous-batching engine over a transformer LM's paged decode
    primitives. Knobs: `max_slots` (concurrent requests in the batched
    decode), `max_len` (per-request position cap, bounded by the
    positional table), `min_bucket` (smallest prefill pad length),
    `max_prefills_per_step` (total prefill chunks per step across
    slots; each pending slot advances at most one chunk per step
    regardless, so None = all pending slots advance, 1 = only the FCFS
    head — latency-biased for in-flight decodes),
    `prefill_chunk_tokens` (max tokens per prefill chunk;
    None = whole suffix in one chunk), `kv_block_tokens` (KV pool
    block granularity — allocation, prefix caching, and copy-on-write
    all happen in whole blocks), `kv_pool_blocks` (physical blocks in
    the pool = the engine's KV HBM budget / (Bt tokens x layers);
    default max_slots x ceil(max_len/Bt), the slab-parity worst case),
    `spec_draft_len` (speculative window size K: the pending token
    plus K-1 self-drafted tokens verified per step; None/<2 = off),
    and `prefix_cache_tokens` (token budget of the shared prefix trie;
    None/0 disables reuse). `prefix_block_tokens` is the pre-paging
    name for the block granularity and still accepted: trie blocks ARE
    pool blocks now, so the two sizes cannot differ. `weights_version`
    tags the engine — and every token it emits — with the weight
    version its params came from (the fleet's live-rollout version
    fence; a weight swap is a new engine, never an in-place mutation).
    `paged_kernel` picks how the compiled steps attend over the block
    pool (ISSUE 13): "fused" = the Pallas kernels that walk the block
    table inside the kernel (parallel/paged_attention.py — no
    per-layer gathered view; the default on accelerator backends),
    "gather" = the XLA `_paged_view` form (the CPU-backend default,
    where fused would run interpreted); `PADDLE_TPU_PAGED_KERNEL`
    overrides when the arg is None. Greedy outputs are token-identical
    either way (tests/test_paged_kernel.py pins it per primitive and
    end-to-end).

    `kv_quant` (ISSUE 14) picks the KV pool's STORAGE dtype:
    "none" (the default — cache structure and traces byte-identical
    to the pre-quant engine), "int8", or "fp8" (float8_e4m3fn). At
    block granularity: each physical block carries a per-head f32
    absmax scale (side-bands on the cache pytree, keyed by physical
    block id), committed when the block is first filled — so prefix
    ALIASING shares the scale with the payload for free, COW copies
    both in one compiled op, and eviction/reuse recommits on the next
    fill. Writes quantize at the scatter inside the one compiled
    step; reads dequantize inside the fused Pallas kernels (scales as
    scalar-prefetch operands — no HBM-materialised dequantized view)
    or on the gather view on CPU. int8/fp8 holds ~4x the resident
    blocks per HBM byte at a fixed byte budget; `bench.py
    serving_quant` pins the greedy-agreement quality gate. NOT
    token-identical to f32 — a quantized engine is a different model
    by design, which is why a fleet refuses mixed kv_quant replicas.
    `weight_quant` ("int8" | None) additionally stores the params as
    per-tensor int8 + f32 scales (serving/quantization.py), dequant
    folded into the compiled steps — the decode HBM roofline's weight
    term drops ~4x independently of the KV side.

    Serving integrity (ISSUE 15): `integrity_traps` (default True)
    folds a per-slot non-finite trap — logits + softmax-denominator
    reduction (`transformer.logits_trap`) — into the SAME compiled
    steps (no new traces; decode still compiles exactly once); a
    tripped slot raises `IntegrityError` INSTEAD of emitting a token,
    and the fleet routes that into quarantine + taint-aware resume.
    `kv_fingerprints` (default False) adds per-physical-block
    folded-f32 checksums: committed when a block closes (publish into
    the prefix trie), spot-verified when an aliased block is re-opened
    by a different request (which is also where failover resume
    re-attaches), dropped when the block frees — a flipped block
    cannot silently serve prefix-cache hits.
    `integrity_spike_factor` (default None = off) additionally watches
    the step's max-|logit| with the shared EWMA/hysteresis
    TripDetector core (utils/detector.py — the training sentinel's),
    catching wrong-but-finite magnitude excursions.
    """

    def __init__(self, params, cfg, max_slots=8, max_len=None,
                 min_bucket=8, max_prefills_per_step=None, donate=True,
                 prefill_chunk_tokens=None, prefix_cache_tokens=None,
                 prefix_block_tokens=None, kv_block_tokens=None,
                 kv_pool_blocks=None, spec_draft_len=None,
                 replica_id=None, fault_injector=None,
                 scheduler_hook=None, weights_version=None,
                 adapter_registry=None, adapter_slots=8,
                 adapter_rank=None, paged_kernel=None,
                 kv_quant="none", weight_quant=None,
                 integrity_traps=True, kv_fingerprints=False,
                 integrity_spike_factor=None, kv_store=None,
                 kv_store_warm=False, decode_window=None,
                 async_dispatch=False):
        self._params = params
        self._cfg = cfg
        # deterministic-exploration seam (ISSUE 9): the fleet threads
        # its SchedulerHook through so a controlled scheduler can park
        # a replica at engine-step granularity too; None costs one
        # attribute test per step
        self._sched_hook = scheduler_hook
        if getattr(cfg, "moe_experts", 0):
            # reference_moe's capacity cutoff couples rows: padded
            # chunk rows would compete with real rows for expert slots
            # and silently change real outputs (prefill_chunk
            # docstring) — refuse loudly instead
            raise ValueError(
                "ServingEngine serves dense models only; MoE configs "
                "(moe_experts > 0) are not bit-stable under "
                "padded/chunked prefill")
        S = int(max_slots)
        if S < 1:
            raise ValueError("max_slots must be >= 1")
        self.max_slots = S
        # the positional table bounds every position (same clamp as
        # generate: a gather past it would silently clamp, not error)
        L = int(max_len or cfg.max_len)
        L = min(L, int(params["pos"].shape[0]))
        self.max_len = L
        self.min_bucket = int(min_bucket)
        if max_prefills_per_step is not None and max_prefills_per_step < 1:
            raise ValueError("max_prefills_per_step must be >= 1 or None")
        self.max_prefills_per_step = max_prefills_per_step
        if prefill_chunk_tokens is not None and prefill_chunk_tokens < 1:
            raise ValueError("prefill_chunk_tokens must be >= 1 or None")
        self.prefill_chunk_tokens = prefill_chunk_tokens
        if (kv_block_tokens is not None and prefix_block_tokens is not None
                and int(kv_block_tokens) != int(prefix_block_tokens)):
            raise ValueError(
                "trie blocks ARE pool blocks: kv_block_tokens (%d) and "
                "prefix_block_tokens (%d) cannot differ"
                % (int(kv_block_tokens), int(prefix_block_tokens)))
        if kv_block_tokens is None:
            kv_block_tokens = prefix_block_tokens
        Bt = 16 if kv_block_tokens is None else int(kv_block_tokens)
        if Bt < 1:  # an explicit 0 must be loud, not a silent default
            raise ValueError("kv_block_tokens must be >= 1")
        self.kv_block_tokens = Bt
        self.blocks_per_slot = -(-L // Bt)  # ceil: table row width
        NB = (S * self.blocks_per_slot if kv_pool_blocks is None
              else int(kv_pool_blocks))
        if NB < 1:
            raise ValueError("kv_pool_blocks must be >= 1")
        # a pool smaller than one slot's max_len worst case is legal —
        # submit() rejects the individual requests that can never fit
        self.num_kv_blocks = NB
        if spec_draft_len is not None and int(spec_draft_len) < 0:
            raise ValueError("spec_draft_len must be >= 0 or None")
        # K < 2 means no drafts to verify — the plain decode step
        self.spec_draft_len = (
            int(spec_draft_len) if spec_draft_len and int(spec_draft_len) >= 2
            else None)
        # megabatch decode window (ISSUE 19): K decode iterations
        # folded into the ONE compiled step (a lax.scan over the plain
        # decode body) so the host scheduler runs once per K tokens
        # instead of once per token. K=1 without async dispatch keeps
        # the exact pre-window step (bit-identical path, same trace).
        # `async_dispatch` enqueues window N+1 off window N's device
        # outputs BEFORE syncing N's tokens, hiding host work under
        # device compute; emission then runs one window behind.
        dw = 1 if decode_window is None else int(decode_window)
        if dw < 1:
            raise ValueError("decode_window must be >= 1 or None")
        self.decode_window = dw
        self.async_dispatch = bool(async_dispatch)
        if self.spec_draft_len is not None \
                and (dw > 1 or self.async_dispatch):
            # spec decode is itself a multi-token window with HOST-side
            # acceptance after every verify — composing it with a
            # device-side decode window (or deferring its sync) would
            # need acceptance folded into the scan. Loud refusal
            # instead of a silently wrong schedule (ISSUE 19 allows
            # either composition or refusal; this is the refusal).
            raise ValueError(
                "spec_draft_len composes with neither decode_window>1 "
                "nor async_dispatch: speculative acceptance is a host "
                "decision after every verify step — run spec with "
                "decode_window=1 and async_dispatch=False")
        # paged-attention kernel selector (ISSUE 13): "fused" runs the
        # Pallas kernels that attend THROUGH the block table
        # (parallel/paged_attention.py — no per-layer gathered view);
        # "gather" keeps the XLA `_paged_view` form. Fixed for the
        # engine's lifetime (it is baked into the compiled steps);
        # resolution: explicit arg > PADDLE_TPU_PAGED_KERNEL > backend
        # default. The oracle suite (tests/test_paged_kernel.py) is
        # green, so the default IS flipped to "fused" — on accelerator
        # backends, where the kernel compiles to Mosaic. The CPU
        # backend keeps "gather": there the fused path runs the
        # identical kernel INTERPRETED (resolve_interpret), ~4x slower
        # per step and ~1.5x per compile — correct but the wrong
        # default for a CI backend; the paged-kernel suite and the
        # serving_paged_kernel bench force "fused" explicitly on CPU.
        pk = paged_kernel or os.environ.get("PADDLE_TPU_PAGED_KERNEL") \
            or ("gather" if jax.default_backend() == "cpu" else "fused")
        if pk not in ("fused", "gather"):
            raise ValueError(
                "paged_kernel must be 'fused' or 'gather' (got %r)"
                % (pk,))
        self.paged_kernel = pk
        # per-block KV quantization (ISSUE 14): the pool's storage
        # dtype, fixed for the engine's lifetime (baked into the cache
        # pytree AND the compiled steps). 'none' keeps the exact
        # pre-quant cache structure and traces, so the default engine
        # stays token-identical to the PR 13 tree.
        tlm._kv_quant_check(kv_quant)
        if kv_quant != "none":
            tlm.kv_storage_dtype(kv_quant)  # loud fp8-support gate
        self.kv_quant = kv_quant
        # per-tensor int8 weights (ISSUE 14): quantized ONCE below;
        # dequant is the first op of every compiled step
        if weight_quant not in (None, "int8"):
            raise ValueError(
                "weight_quant must be None or 'int8' (got %r)"
                % (weight_quant,))
        self.weight_quant = weight_quant
        # serving integrity (ISSUE 15): in-step numeric traps (per-slot
        # non-finite flag + max-|logit| scalar folded into the one
        # compiled step — no new traces; a tripped slot becomes an
        # IntegrityError instead of an emitted token), optional
        # per-block KV fingerprints (committed at publish, spot-
        # verified on aliased re-open — which is also where failover
        # resume re-attaches), and an opt-in EWMA magnitude spike
        # detector sharing the training sentinel's TripDetector core
        self.integrity_traps = bool(integrity_traps)
        if integrity_spike_factor is not None \
                and float(integrity_spike_factor) <= 1.0:
            raise ValueError(
                "integrity_spike_factor must be > 1 or None")
        if integrity_spike_factor is not None \
                and not self.integrity_traps:
            # the spike detector observes the max-|logit| scalar the
            # TRAP reduction computes — without traps it would be
            # silently dead, which is worse than a loud refusal
            raise ValueError(
                "integrity_spike_factor needs integrity_traps=True "
                "(the spike detector observes the trap reduction's "
                "magnitude scalar)")
        self._sentinel = ServingSentinel(
            spike_factor=integrity_spike_factor)  # guarded-by: scheduler
        if kv_fingerprints and not prefix_cache_tokens:
            # fingerprints commit at trie PUBLISH and verify at
            # aliased re-open — without a prefix cache neither point
            # exists, and the protection would be silently dead (all
            # counters zero forever while the operator believes
            # flipped blocks are covered): refuse loudly instead
            raise ValueError(
                "kv_fingerprints needs the prefix cache (pass "
                "prefix_cache_tokens=): fingerprints commit at trie "
                "publish and verify at aliased re-open — with no "
                "cache neither audit point ever runs")
        self._fp: Optional[BlockFingerprints] = (
            BlockFingerprints() if kv_fingerprints else None)  # guarded-by: scheduler
        self._fp_fn = None  # lazy-jitted fingerprint reduction
        self.metrics = ServingMetrics(S)
        self.metrics.paged_kernel = pk
        self.metrics.kv_quant = kv_quant
        self.metrics.weight_quant = weight_quant
        self.metrics.block_fp = self._fp
        self.metrics.kv_blocks_total = NB
        # live-rollout version fence (ISSUE 11): the weight version
        # these params came from — fixed for the engine's lifetime (a
        # weight swap is a NEW engine under a fresh incarnation, never
        # an in-place mutation), so every token this engine emits is
        # attributable to exactly one version
        self.weights_version = (
            None if weights_version is None else int(weights_version))
        self.metrics.weights_version = self.weights_version
        # one block's HBM cost, honest about the storage dtype (the
        # README sizing rule's block_bytes, surfaced through the
        # allocator's stats) — tlm.kv_block_bytes is the ONE formula,
        # shared with bench.py's byte-budget sizing and
        # bench_offline's roofline
        block_bytes = tlm.kv_block_bytes(
            cfg.layers, cfg.heads, cfg.dim // cfg.heads, Bt, kv_quant,
            act_itemsize=jnp.dtype(cfg.dtype).itemsize)
        self.kv_block_bytes = block_bytes
        self._alloc = KVBlockAllocator(NB, Bt,
                                       block_bytes=block_bytes)  # guarded-by: scheduler
        self.prefix_cache: Optional[PrefixCache] = None
        if prefix_cache_tokens:
            self.prefix_cache = PrefixCache(
                int(prefix_cache_tokens), block_tokens=Bt,
                # _decref_block, not the raw allocator decref: a block
                # the eviction actually FREES must drop its committed
                # fingerprint too, or a recycled id would be judged
                # against its previous tenant's checksum (ISSUE 15)
                on_evict=self._decref_block,
            )
            self.metrics.prefix_cache = self.prefix_cache

        # paged LoRA adapter pool (ISSUE 12): a per-engine device pool
        # of stacked A/B deltas gathered by the per-slot adapter-index
        # band inside the ONE compiled decode/verify/chunk step — N
        # tenants with N adapters retrace nothing; slot 0 is the zero
        # adapter (requests without an adapter are exact no-ops)
        self._adapter_pool: Optional[AdapterPool] = None  # guarded-by: scheduler
        if adapter_registry is not None:
            self._adapter_pool = AdapterPool(
                cfg, adapter_registry, adapter_slots,
                rank=adapter_rank)
            self.metrics.adapter_pool = self._adapter_pool

        self._cache = tlm.init_paged_kv_cache(cfg, NB, Bt,
                                              kv_quant=kv_quant)
        if weight_quant is not None:
            # quantize ONCE; the f32 tree the caller handed in is
            # theirs (fleet CRC walks / rollout see full precision) —
            # the engine's resident copy is int8 + per-tensor scales
            self._params = quantize_params(self._params)
        self._deq = (dequantize_params if weight_quant is not None
                     else None)
        # host-side truth of the per-slot side-bands; device copies are
        # kept across steps and re-uploaded only when dirtied. All
        # scheduler state below is confined to the thread driving
        # step()/submit() (the engine has no background loop). A future
        # background method must declare its `# thread: <domain>` —
        # lock_lint then flags its mutations of scheduler state
        # (undeclared methods are assumed to run on the owning domain).
        self._tok = np.zeros(S, np.int32)     # guarded-by: scheduler
        self._pos = np.zeros(S, np.int32)     # guarded-by: scheduler
        self._alive = np.zeros(S, bool)       # guarded-by: scheduler
        self._temps = np.zeros(S, np.float32)  # guarded-by: scheduler
        self._counts = np.zeros(S, np.int32)  # guarded-by: scheduler
        self._base_keys = np.zeros((S, 2), np.uint32)  # guarded-by: scheduler
        # per-slot block table (logical depth -> physical block id; -1
        # = not yet allocated) and position limit (T0 + max_new: verify
        # rows at or past it park their writes)
        self._tables = np.full((S, self.blocks_per_slot), -1,
                               np.int32)      # guarded-by: scheduler
        self._limits = np.zeros(S, np.int32)  # guarded-by: scheduler
        # per-slot adapter-index band (ISSUE 12): which adapter-pool
        # slot each request's q/v deltas gather from (0 = zero adapter)
        self._aidx = np.zeros(S, np.int32)    # guarded-by: scheduler
        # per-slot EOS id band (ISSUE 19): -1 = no EOS configured. The
        # compiled decode window retires slots in-loop, so the EOS rule
        # must live on device too (K=1 sync keeps judging on host).
        self._eos = np.full(S, -1, np.int32)  # guarded-by: scheduler
        self._n_alloc = np.zeros(S, np.int32)  # table entries >= 0  # guarded-by: scheduler
        self._reserved_tail = np.zeros(S, np.int32)  # guarded-by: scheduler
        self._dev: Dict[str, Any] = {}        # guarded-by: scheduler
        self._dirty = set(_BANDS)             # guarded-by: scheduler
        self._slot_req: List[Optional[ServingHandle]] = [None] * S  # guarded-by: scheduler
        # per-slot chunked-prefill cursors + FCFS order of pending slots
        self._prefill_state: Dict[int, dict] = {}  # guarded-by: scheduler
        self._prefill_q: collections.deque = collections.deque()  # guarded-by: scheduler
        # per-slot self-drafting index (spec decode): the context token
        # list, a bigram -> end-of-last-occurrence map maintained
        # incrementally per emitted token, and the tail bigram's
        # PREVIOUS occurrence — O(1) per step instead of rescanning the
        # whole context every decode
        self._spec_ctx: Dict[int, dict] = {}  # guarded-by: scheduler

        self._queue: collections.deque = collections.deque()  # guarded-by: scheduler
        self._next_rid = 0                    # guarded-by: scheduler
        # any request ever carried a deadline -> the per-step expiry
        # sweep runs; stays False (zero hot-path cost) otherwise
        self._deadlines = False               # guarded-by: scheduler
        self._donate = bool(donate)
        self._chunk_fns: Dict[int, Any] = {}
        # exactly ONE decode trace per engine lifetime, whatever K: the
        # window engine never builds (so never traces) the plain step,
        # and vice versa — both carry the trace name "decode_step"
        self._use_window = dw > 1 or self.async_dispatch
        self._decode_fn = (None if self._use_window
                           else self._make_decode())
        self._window_fn = (self._make_decode_window()
                           if self._use_window else None)
        # the one in-flight dispatched-not-yet-synced window record
        # (async dispatch); sync mode never leaves one pending
        self._inflight: Optional[dict] = None  # guarded-by: scheduler
        self._verify_fn = (
            self._make_verify() if self.spec_draft_len else None)
        self._cow_fn = None
        # failure latch (abort() docstring) + fleet attribution
        self.replica_id = replica_id
        self._failed: Optional[EngineFailed] = None  # guarded-by: scheduler
        # fault-injection tick source for step(): an explicit injector
        # (fleet drills give each replica its own), or — resolved
        # lazily on the first step — the process-wide default_injector
        # when PADDLE_FAULT is set, else an inert one (same contract as
        # the trainer CLI's per-batch tick; see fault_injection.py)
        self._injector = fault_injector       # guarded-by: scheduler
        # durable KV tier (ISSUE 16): a fleet-shared KVBlockStore the
        # engine WRITES closed blocks into at publish (self-describing
        # records: quantized codes + scale side-bands + the PR 15
        # fingerprint as the transfer checksum) and READS at admission
        # (handoff import) / construction (warm start). The store is
        # internally locked; the engine only ever touches it from the
        # scheduler thread.
        if kv_store is not None and int(kv_store.block_tokens) != Bt:
            raise ValueError(
                "kv_store block geometry mismatch: store has "
                "block_tokens=%d, engine has %d — records would never "
                "align with the trie chain keys"
                % (int(kv_store.block_tokens), Bt))
        if kv_store is not None and self.prefix_cache is None:
            # spill happens at trie PUBLISH and warm start targets the
            # trie — without a prefix cache neither path exists and the
            # store would be silently dead (same refusal shape as
            # kv_fingerprints above)
            raise ValueError(
                "kv_store needs the prefix cache (pass "
                "prefix_cache_tokens=): blocks spill at trie publish "
                "and warm-start restores into the trie")
        self._kv_store = kv_store             # thread: shared (store locks itself)
        self.metrics.kv_store = kv_store
        if kv_store is not None and kv_store_warm:
            # warm the trie from the store BEFORE traffic: a restarted
            # or autoscaled replica serves its first shared-prefix hit
            # without re-decoding the prefix
            self.warm_from_store()

    # ------------------------------------------------------------------
    # compiled steps
    # ------------------------------------------------------------------
    def _make_decode(self):
        cfg, metrics = self._cfg, self.metrics
        Lv = self.blocks_per_slot * self.kv_block_tokens
        kernel = self.paged_kernel  # baked into the one compiled step
        kv_quant = self.kv_quant    # ditto: storage dtype is traced in
        deq = self._deq
        traps = self.integrity_traps  # baked in: trap reduction or not

        def _decode(params, cache, tables, tok, pos, alive, temps,
                    counts, base_keys, adapters=None, aidx=None):
            metrics.count_trace("decode_step")  # trace-time side effect
            if deq is not None:  # int8 weights upcast INSIDE the step
                params = deq(params)
            # dead slots park their write past the table span: the
            # block lookup resolves them to the out-of-range sentinel
            # block and the scatter DROPS the row, so a retired slot
            # can never dirty a block a future request will claim
            write_pos = jnp.where(alive, pos, jnp.int32(Lv))
            logits, cache = tlm.paged_decode_step(
                params, tok, write_pos, tables, cache, cfg,
                adapters=adapters, adapter_idx=aidx, kernel=kernel,
                kv_quant=kv_quant,
            )
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            keys = jax.vmap(jax.random.fold_in)(base_keys, counts)
            safe_t = jnp.where(temps > 0, temps, 1.0)
            sampled = jax.vmap(
                lambda k, l, t: jax.random.categorical(
                    k, l.astype(jnp.float32) / t
                )
            )(keys, logits, safe_t).astype(jnp.int32)
            nxt = jnp.where(temps > 0, sampled, greedy)
            # ISSUE 15 in-step numeric traps: per-slot non-finite flag
            # + max-|logit| scalar, FOLDED into this same trace (a few
            # reductions — decode stays compiled exactly once). Off =
            # constant zeros, no reduction in the graph.
            if traps:
                trap = tlm.logits_trap(logits) & alive
                scale = tlm.logit_amax(logits, alive)
            else:
                trap = jnp.zeros_like(alive)
                scale = jnp.float32(0.0)
            # advance the device-resident bands in-step: the steady
            # decode loop re-uploads nothing (satellite: h2d dispatch
            # off the hot path). Dead rows advance by 0, matching the
            # untouched host mirrors.
            live = alive.astype(jnp.int32)
            return cache, nxt, pos + live, counts + live, trap, scale

        kw = {"donate_argnums": (1,)} if self._donate else {}
        return jax.jit(_decode, **kw)

    def _make_decode_window(self):
        """ONE compiled K-token decode window (ISSUE 19): a lax.scan
        over K iterations of exactly the plain decode body — paged
        scatter write (PR 13 kernels, PR 14 quant commit-at-open rides
        the same scatter), greedy/sampled next token on the SAME
        `fold_in(base_key, count)` schedule (counts advance per live
        iteration, so sampled outputs are window-invariant), then the
        device-side retirement rule (`tlm.decode_window_retire`): a
        slot hitting EOS or budget mid-window emits that final token
        and parks — its remaining scatter writes resolve to the
        out-of-range sentinel block and its emitted lane carries -1
        padding the host discards. PR 15 traps are accumulated PER
        ITERATION ([K, S] stack), so a trip in iteration j poisons
        only tokens >= j: the host checks row j before emitting row j.
        Traced exactly once per engine lifetime under the same
        "decode_step" trace name as the plain step it replaces."""
        cfg, metrics = self._cfg, self.metrics
        K = self.decode_window
        Lv = self.blocks_per_slot * self.kv_block_tokens
        kernel = self.paged_kernel  # baked into the one compiled step
        kv_quant = self.kv_quant
        deq = self._deq
        traps = self.integrity_traps

        def _window(params, cache, tables, tok, pos, alive, temps,
                    counts, base_keys, limits, eos, adapters=None,
                    aidx=None):
            metrics.count_trace("decode_step")  # trace-time side effect
            if deq is not None:  # int8 weights upcast ONCE per window
                params = deq(params)

            def _iter(carry, _):
                cache, tok, pos, alive, counts = carry
                write_pos = jnp.where(alive, pos, jnp.int32(Lv))
                logits, cache = tlm.paged_decode_step(
                    params, tok, write_pos, tables, cache, cfg,
                    adapters=adapters, adapter_idx=aidx, kernel=kernel,
                    kv_quant=kv_quant,
                )
                greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                keys = jax.vmap(jax.random.fold_in)(base_keys, counts)
                safe_t = jnp.where(temps > 0, temps, 1.0)
                sampled = jax.vmap(
                    lambda k, l, t: jax.random.categorical(
                        k, l.astype(jnp.float32) / t
                    )
                )(keys, logits, safe_t).astype(jnp.int32)
                nxt = jnp.where(temps > 0, sampled, greedy)
                if traps:
                    trap = tlm.logits_trap(logits) & alive
                    scale = tlm.logit_amax(logits, alive)
                else:
                    trap = jnp.zeros_like(alive)
                    scale = jnp.float32(0.0)
                # dead lanes emit -1 padding; a live lane emits its
                # token even on its retirement iteration (EOS/budget
                # tokens ARE emitted, exactly like the host _emit rule)
                emitted = jnp.where(alive, nxt, jnp.int32(-1))
                live = alive.astype(jnp.int32)
                nalive, npos = tlm.decode_window_retire(
                    alive, nxt, pos, limits, eos)
                ntok = jnp.where(alive, nxt, tok)
                return ((cache, ntok, npos, nalive, counts + live),
                        (emitted, trap, scale))

            carry, stacks = jax.lax.scan(
                _iter, (cache, tok, pos, alive, counts), None, length=K)
            cache, tok, pos, alive, counts = carry
            toks, trapw, scalew = stacks  # [K, S], [K, S], [K]
            return cache, tok, pos, alive, counts, toks, trapw, scalew

        kw = {"donate_argnums": (1,)} if self._donate else {}
        return jax.jit(_window, **kw)

    def _make_verify(self):
        """ONE compiled speculative-verify step: writes every slot's
        K-token window into its paged cache, returns the model's
        candidate token after each window prefix. Host-side acceptance
        turns candidates into emitted tokens; device-side this is a
        fixed [S, K] shape traced exactly once per engine lifetime."""
        cfg, metrics = self._cfg, self.metrics
        K = self.spec_draft_len
        Lv = self.blocks_per_slot * self.kv_block_tokens
        kernel = self.paged_kernel  # baked into the one compiled step
        kv_quant = self.kv_quant
        deq = self._deq
        traps = self.integrity_traps

        def _verify(params, cache, tables, window, pos, alive, limits,
                    temps, counts, base_keys, adapters=None, aidx=None):
            metrics.count_trace("spec_verify")  # trace-time side effect
            if deq is not None:
                params = deq(params)
            rows = pos[:, None] + jnp.arange(K)[None, :]  # [S, K]
            # dead slots and rows past the request's token budget park
            ok = alive[:, None] & (rows < limits[:, None])
            wpos = jnp.where(ok, rows, jnp.int32(Lv))
            logits, cache = tlm.paged_verify_step(
                params, cache, window, pos, wpos, tables, cfg,
                adapters=adapters, adapter_idx=aidx, kernel=kernel,
                kv_quant=kv_quant,
            )
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            # per-position sampling keys: position i of a slot whose
            # request has emitted `counts` tokens samples token index
            # counts + i — the SAME fold_in schedule the plain decode
            # path uses, so sampled outputs are spec-invariant
            idx = counts[:, None] + jnp.arange(K)[None, :]
            keys = jax.vmap(
                jax.vmap(jax.random.fold_in, in_axes=(None, 0)),
                in_axes=(0, 0),
            )(base_keys, idx)
            safe_t = jnp.where(temps > 0, temps, 1.0)
            sampled = jax.vmap(
                jax.vmap(
                    lambda k, l, t: jax.random.categorical(
                        k, l.astype(jnp.float32) / t
                    ),
                    in_axes=(0, 0, None),
                ),
                in_axes=(0, 0, 0),
            )(keys, logits, safe_t).astype(jnp.int32)
            cand = jnp.where((temps > 0)[:, None], sampled, greedy)
            # ISSUE 15 traps over the whole [S, K] window, reduced to
            # per-slot (any corrupt row in a slot's window trips it)
            if traps:
                trap = tlm.logits_trap(logits).any(axis=-1) & alive
                scale = tlm.logit_amax(logits, alive)
            else:
                trap = jnp.zeros_like(alive)
                scale = jnp.float32(0.0)
            return cache, cand, trap, scale

        kw = {"donate_argnums": (1,)} if self._donate else {}
        return jax.jit(_verify, **kw)

    def _chunk_fn(self, Cb):
        """One compiled prefill-chunk step per pow-2 bucket: extends a
        slot's cached prefix by a [Cb]-padded chunk and returns the
        would-be first generated token (meaningful only when the chunk
        completes the prompt)."""
        fn = self._chunk_fns.get(Cb)
        if fn is not None:
            return fn
        cfg, metrics = self._cfg, self.metrics
        kernel = self.paged_kernel  # baked into the per-bucket step
        kv_quant = self.kv_quant
        deq = self._deq
        traps = self.integrity_traps

        def _chunk(params, cache, padded, start, table_row, true_len,
                   temp, key, adapters=None, aidx=None):
            metrics.count_trace("prefill_T%d" % Cb)
            if deq is not None:
                params = deq(params)
            logits, cache = tlm.paged_prefill_chunk(
                params, cache, padded, start, table_row, cfg,
                true_len=true_len, adapters=adapters, adapter_idx=aidx,
                kernel=kernel, kv_quant=kv_quant,
            )
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            sampled = jax.random.categorical(
                key,
                logits.astype(jnp.float32)
                / jnp.where(temp > 0, temp, 1.0),
            ).astype(jnp.int32)
            first = jnp.where(temp > 0, sampled, greedy)
            # ISSUE 15 trap on the chunk's last-token logits. A NaN
            # written MID-chunk propagates: attention over a NaN K/V
            # row yields NaN logits at the final chunk, which is the
            # only chunk the host reads back anyway (mid-prompt chunks
            # stay dispatch-only so prefill keeps overlapping decode)
            if traps:
                trap = tlm.logits_trap(logits)
                scale = tlm.logit_amax(logits)
            else:
                trap = jnp.bool_(False)
                scale = jnp.float32(0.0)
            return cache, first, trap, scale

        kw = {"donate_argnums": (1,)} if self._donate else {}
        fn = jax.jit(_chunk, **kw)
        self._chunk_fns[Cb] = fn
        return fn

    def _make_cow(self):  # band-verb: cow
        """Copy-on-write: privatise one shared block before the suffix
        writes into it. ONE compiled shape total (fixed block size) —
        the only device copy left in the reuse path; plain aliasing
        moves zero bytes. On a quantized pool each layer dict also
        carries the k_scale/v_scale side-bands, row-indexed by the
        same physical block id — copying every band privatises
        payload AND scale in the same compiled op, so the private
        block dequantizes bit-identically to the shared one it
        forked from."""
        metrics = self.metrics

        def _cow(cache, dst, src):
            metrics.count_trace("cow_copy")
            return [
                {band: buf.at[dst].set(buf[src])
                 for band, buf in kv.items()}
                for kv in cache
            ]

        kw = {"donate_argnums": (0,)} if self._donate else {}
        return jax.jit(_cow, **kw)

    # ------------------------------------------------------------------
    # device-resident side-bands
    # ------------------------------------------------------------------
    def _band(self, name):
        if name in self._dirty:
            self._dev[name] = jnp.asarray(getattr(self, "_" + name))
            self._dirty.discard(name)
            self.metrics.band_uploads += 1
        return self._dev[name]

    def _mark_dirty(self, *names):
        self._dirty.update(names or _BANDS)

    def _adapter_args(self, aidx) -> dict:
        """Extra kwargs for the compiled steps when the adapter pool
        is on: the stacked pool arrays + the adapter-index side-band
        (`aidx` — the [S] device band for decode/verify, a scalar for
        a prefill chunk). Empty when adapters are off, so the traced
        graphs stay byte-identical to the pre-adapter engine."""
        if self._adapter_pool is None:
            return {}
        return {"adapters": self._adapter_pool.device_arrays(),
                "aidx": aidx}

    # ------------------------------------------------------------------
    # integrity (ISSUE 15)
    # ------------------------------------------------------------------
    def _trip(self, kind: str, detail: str):
        """Raise the integrity event: step()'s except path latches the
        engine and the fleet's _on_crash routes an IntegrityError into
        quarantine + taint-aware resume instead of plain failover."""
        raise IntegrityError(
            "integrity trip%s: %s" % (
                "" if self.replica_id is None
                else " (replica %s)" % self.replica_id,
                detail),
            kind=kind, replica=self.replica_id)

    def _check_integrity(self, trap, scale, where: str, slots=None):
        """Judge one compiled step's trap flag(s) + magnitude scalar.
        A tripped slot becomes an integrity event INSTEAD of an
        emitted token — the caller checks BEFORE its emit loop, so no
        token from a poisoned step ever reaches a handle (or, through
        the fleet, the journal)."""
        trap = np.atleast_1d(np.asarray(trap))
        verdict = self._sentinel.observe(bool(trap.any()), float(scale))
        if verdict == "ok":
            return
        if verdict == "trap":
            bad = (slots if slots is not None
                   else [int(s) for s in np.nonzero(trap)[0]])
            rids = [self._slot_req[s].rid for s in bad
                    if self._slot_req[s] is not None]
            self._trip("trap",
                       "non-finite logits in %s step (slots %s, rids "
                       "%s)" % (where, bad, rids))
        self._trip("spike",
                   "logit magnitude spike in %s step (max-|logit| "
                   "%.3g vs EWMA %.3g x factor %g)"
                   % (where, float(scale),
                      self._sentinel.detector.ewma or 0.0,
                      self._sentinel.detector.spike_factor))

    def _fp_of(self, bid: int) -> float:
        """Recompute one physical block's fingerprint on device. The
        reduction is jitted ONCE (trace name "block_fp") — never
        donated: the cache must survive the read."""
        if self._fp_fn is None:
            metrics = self.metrics

            def _fp(cache, b):
                metrics.count_trace("block_fp")
                return tlm.paged_block_fingerprint(cache, b)

            self._fp_fn = jax.jit(_fp)
        return float(self._fp_fn(self._cache, jnp.int32(int(bid))))

    def _decref_block(self, bid) -> bool:
        """Drop one pool reference; a block actually FREED also drops
        its committed fingerprint (a recycled id must never be judged
        against the previous tenant's checksum). The ONE decref every
        engine-side release path uses (slot retirement, trie
        eviction)."""
        freed = self._alloc.decref(bid)
        if freed and self._fp is not None:
            self._fp.drop(int(bid))
        return freed

    def _flip_resident_block(self):
        """Consume a flip@ fault (ISSUE 15 drill): corrupt ONE resident
        physical block's K payload in place with finite garbage — the
        silent-data-corruption shape the numeric traps CANNOT see (no
        NaN) and only a fingerprint spot-check catches. Deterministic
        victim: the lowest in-use physical id. With nothing resident
        the fault re-arms for the next step, so flip@N on a
        still-empty pool lands on the first real block."""
        bid = next((b for b in range(self.num_kv_blocks)
                    if self._alloc.refcount(b) > 0), None)
        if bid is None:
            self._injector.rearm_flip()
            return
        kv = self._cache[0]
        buf = kv["k"]
        row = buf[bid]
        if buf.dtype == jnp.int8:
            garb = jnp.clip(row.astype(jnp.int32) + 37,
                            -127, 127).astype(jnp.int8)
        else:
            garb = (row.astype(jnp.float32) * -1.0
                    + 1.7).astype(buf.dtype)
        kv["k"] = buf.at[bid].set(garb)

    # ------------------------------------------------------------------
    # durable KV tier (ISSUE 16)
    # ------------------------------------------------------------------
    def _serialize_block(self, bid: int):  # band-verb: serialize
        """Flatten one physical block across every layer and band into
        (payload bytes, meta rows). Meta rows are ("li.band", dtype,
        shape-per-block) in the SAME sorted-band order
        `paged_block_fingerprint` folds, so a record is self-describing
        on a replica that never saw this pool: codes AND quant-scale
        side-bands travel together, and the fingerprint is recomputable
        from the payload alone."""
        parts = []
        meta = []
        b = int(bid)
        for li, kv in enumerate(self._cache):
            for band in sorted(kv):
                arr = np.asarray(kv[band][b])
                meta.append(("%d.%s" % (li, band), str(arr.dtype),
                             tuple(int(x) for x in arr.shape)))
                parts.append(arr.tobytes())
        return b"".join(parts), meta

    def _upload_block_record(self, rec, bid: int) -> bool:  # band-verb: import
        """Write one store record's payload into physical block `bid`
        (in-place band update, the `_flip_resident_block` idiom).
        Validates EVERY meta row against this engine's cache geometry
        before touching the device — False (and an untouched cache)
        on any layer/band/dtype/shape mismatch, so a foreign-geometry
        record can never half-write a block."""
        payload = rec["payload"]
        off = 0
        planned = []
        for name, dtype, shape in rec["meta"]:
            li_s, _, band = str(name).partition(".")
            try:
                li = int(li_s)
            except ValueError:
                return False
            if li < 0 or li >= len(self._cache) \
                    or band not in self._cache[li]:
                return False
            buf = self._cache[li][band]
            shape = tuple(int(x) for x in shape)
            if shape != tuple(buf.shape[1:]) or str(buf.dtype) != dtype:
                return False
            n = int(np.prod(shape, dtype=np.int64)) \
                * np.dtype(dtype).itemsize
            chunk = payload[off:off + n]
            if len(chunk) != n:
                return False
            off += n
            planned.append(
                (li, band, np.frombuffer(chunk, dtype).reshape(shape)))
        if off != len(payload):
            return False
        b = int(bid)
        for li, band, vals in planned:
            kv = self._cache[li]
            kv[band] = kv[band].at[b].set(jnp.asarray(vals))
        return True

    def _record_fp_ok(self, rec, fp_d) -> bool:
        """The handoff/warm transfer checksum: the RECOMPUTED on-device
        fingerprint of the uploaded block vs the record's committed one
        (same tolerance as the aliased re-open spot-check)."""
        exp = float(rec["fp"])
        return abs(float(fp_d) - exp) <= _FP_RTOL * max(1.0, abs(exp))

    def warm_from_store(self) -> int:  # band-verb: import
        """Restore the durable store's chains into THIS engine's prefix
        trie (restart / autoscale warm start): parent-before-child over
        the store snapshot, each block crc- and fingerprint-verified on
        upload, grafted under the trie with the fresh block's single
        pool ref TRANSFERRED to the trie (on_evict drops it). Corrupt
        entries are skipped and quarantined — with their whole subtree,
        a child's context is its ancestors' payloads — never served.
        Stops (rather than evicting warmed chains or starving traffic)
        at the trie token budget or pool exhaustion. Returns blocks
        restored."""
        store = self._kv_store
        pc = self.prefix_cache
        if store is None or pc is None:
            return 0
        Bt = self.kv_block_tokens
        n_warm = 0
        chain: Dict[int, list] = {}  # key -> tokens through this block
        skipped = set()
        for rec in store.iter_chains():
            key = rec["key"]
            par = rec["parent"]
            if par in skipped:
                skipped.add(key)  # corrupt ancestor: subtree is dead
                continue
            if par != 0 and par not in chain:
                continue  # unrooted (hole upstream): nothing to graft
            toks = (chain[par] if par else []) \
                + [int(t) for t in rec["tokens"]]
            depth = len(toks) // Bt
            m = pc.match(np.asarray(toks, np.int32), record=False)
            have = m.length
            m.release()
            if have >= depth * Bt:
                chain[key] = toks  # already resident (or just warmed)
                continue
            if pc.size_tokens + Bt > pc.token_budget:
                break  # budget: deeper warms would evict earlier ones
            if len(rec["payload"]) != rec["nbytes"] \
                    or payload_crc(rec["payload"]) != rec["crc"]:
                store.quarantine(key)
                self.metrics.store_quarantined += 1
                skipped.add(key)
                continue
            bid = self._alloc.try_alloc()
            if bid is None:
                break  # pool pressure: serve traffic over warmth
            ok = self._upload_block_record(rec, bid)
            fp_d = self._fp_of(bid) if ok else None
            if not ok or not self._record_fp_ok(rec, fp_d):
                self._decref_block(bid)
                store.quarantine(key)
                self.metrics.store_quarantined += 1
                skipped.add(key)
                continue
            if self._fp is not None:
                self._fp.commit(bid, fp_d)
            # ancestors are resident (the chain[] gate above), so only
            # this deepest block is novel to the publish
            pc.publish(np.asarray(toks, np.int32), depth,
                       lambda _d, b=bid: b)
            n_warm += 1
            self.metrics.store_warm_blocks += 1
            chain[key] = toks
        return n_warm

    # ------------------------------------------------------------------
    # block bookkeeping
    # ------------------------------------------------------------------
    def _blocks_for(self, tokens: int) -> int:
        return -(-int(tokens) // self.kv_block_tokens)

    def _ensure_blocks(self, s: int, lo: int, hi: int):
        """Materialise (from this slot's reservation) every block
        covering positions [lo, hi) that the table has not allocated
        yet — the on-demand append that keeps residency at tokens
        actually written."""
        if hi <= lo:
            return
        Bt = self.kv_block_tokens
        for b in range(lo // Bt, (hi - 1) // Bt + 1):
            if self._tables[s, b] < 0:
                self._tables[s, b] = self._alloc.alloc_reserved()
                self._reserved_tail[s] -= 1
                self._n_alloc[s] += 1
                self._mark_dirty("tables")

    def _reclaim_for(self, need_new: int):
        """Evict idle trie chains until `need_new` blocks are
        available — but ONLY when eviction can actually bridge the gap
        (the freeable gain is trie payloads nobody holds whose pool
        refcount is 1: eviction of a slot-aliased or match-held block
        frees nothing). A failed admission attempt must leave the trie
        INTACT: a block-starved request retries every scheduler step,
        and unconditional reclaim would drain every shareable chain
        before anything admits (review hardening)."""
        pc = self.prefix_cache
        if pc is None or self._alloc.available >= need_new:
            return
        gain = sum(1 for bid in pc.idle_payloads()
                   if self._alloc.refcount(int(bid)) == 1)
        if self._alloc.available + gain < need_new:
            return  # hopeless right now: stay queued, trie untouched
        while self._alloc.available < need_new:
            # shareability yields to admitting the next request
            if pc.reclaim(need_new - self._alloc.available) == 0:
                break

    def _free_slot_blocks(self, s: int):
        """Retirement: drop this slot's reference on every allocated
        block (a block shared with the prefix trie or another slot
        survives) and release the reserved-but-unreached tail — the
        capacity an early-EOS request never grew into."""
        freed = 0
        for b in range(self.blocks_per_slot):
            bid = int(self._tables[s, b])
            if bid >= 0 and self._decref_block(bid):
                freed += 1
        tail = int(self._reserved_tail[s])
        if tail:
            self._alloc.release_reservation(tail)
        self.metrics.kv_blocks_freed_at_retire += freed
        self.metrics.kv_tail_blocks_freed += tail
        self._tables[s, :] = -1
        self._n_alloc[s] = 0
        self._reserved_tail[s] = 0
        self._limits[s] = 0
        self._mark_dirty("tables", "limits")

    # ------------------------------------------------------------------
    # scheduler
    # ------------------------------------------------------------------
    def submit(self, prompt, max_new_tokens, temperature=0.0, eos_id=None,
               seed=0, publish_len=None, deadline_at=None,
               resume_tokens=None, adapter=None,
               handoff=None) -> ServingHandle:
        """Enqueue one request (FCFS). Returns a handle whose `.tokens`
        fills in as the engine steps; `handle.result()` drives the
        engine to completion of this request. Structurally impossible
        requests (past the positional table, or needing more blocks
        than the whole pool) raise; a merely SATURATED pool queues —
        the block-budget check happens at admission and retirements
        free capacity (backpressure, ISSUE 7 satellite). `publish_len`
        is the publish-boundary tag: at most this many leading prompt
        tokens are published to the prefix pool once prefill completes
        (None = the whole prompt; pass the shared-header length to keep
        request-unique tails out of the pool). `deadline_at` is an
        absolute time.monotonic() budget: past it the request is
        terminally 'expired' at the next queue hop (admission, prefill
        chunk, or decode) instead of consuming further steps.
        `resume_tokens` are tokens an earlier incarnation of this
        request already emitted (token-level resume, ISSUE 8): they
        become prefill context — prefix-aliased where the pool allows —
        and only `max_new_tokens - len(resume_tokens)` tokens are
        decoded, on the ORIGINAL request's sampling-key schedule.
        `handoff` is a durable-KV block package (ISSUE 16): the source
        replica's closed prompt blocks as kv_store records, imported at
        admission after per-block fingerprint verification — the clean
        path re-prefills ZERO closed-block tokens; any mismatch falls
        back to re-prefill (counted, never wrong)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        T0 = prompt.shape[0]
        if T0 < 1:
            raise ValueError("empty prompt")
        E = 0 if resume_tokens is None else len(resume_tokens)
        if int(max_new_tokens) - E < 1:
            raise ValueError(
                "max_new_tokens must leave >= 1 token past the resumed "
                "prefix (%d - %d resumed < 1)" % (int(max_new_tokens), E))
        if T0 + int(max_new_tokens) > self.max_len:
            raise ValueError(
                "request needs T0+max_new <= max_len (%d + %d > %d)"
                % (T0, int(max_new_tokens), self.max_len)
            )
        if self._blocks_for(T0 + int(max_new_tokens)) > self.num_kv_blocks:
            raise ValueError(
                "request worst case (%d blocks) exceeds the whole KV "
                "pool (%d blocks of %d tokens)"
                % (self._blocks_for(T0 + int(max_new_tokens)),
                   self.num_kv_blocks, self.kv_block_tokens)
            )
        if publish_len is not None and publish_len < 0:
            raise ValueError("publish_len must be >= 0 or None")
        if adapter is not None:
            # resolve-or-refuse NOW: an unknown adapter (or an engine
            # with no pool) must fail the caller synchronously, never
            # crash the scheduler at admission time
            if self._adapter_pool is None:
                raise ValueError(
                    "request names adapter %r but the engine has no "
                    "adapter pool (pass adapter_registry=)" % (adapter,))
            if not self._adapter_pool.registry.has(adapter):
                raise ValueError("unknown adapter %r (registered: %r)"
                                 % (adapter,
                                    self._adapter_pool.registry.names()))
        h = ServingHandle(self, self._next_rid, prompt, max_new_tokens,
                          temperature, eos_id, seed, publish_len,
                          deadline_at=deadline_at,
                          resume_tokens=resume_tokens, adapter=adapter,
                          handoff=handoff)
        self._next_rid += 1
        if deadline_at is not None:
            self._deadlines = True
        if E:
            self.metrics.resumed_requests += 1
            self.metrics.resume_tokens_reused += E
        self._queue.append(h)
        return h

    def _free_slot(self) -> Optional[int]:
        for s in range(self.max_slots):
            if self._slot_req[s] is None:
                return s
        return None

    def _bucket(self, T0: int) -> int:
        return min(bucket_pow2(T0, floor=self.min_bucket), self.max_len)

    def _retire(self, s: int, reason: str):  # band-verb: retire
        h = self._slot_req[s]
        h.done = True
        h.finish_reason = reason
        self._slot_req[s] = None
        self._alive[s] = False
        self._spec_ctx.pop(s, None)
        if self._adapter_pool is not None:
            # drop the request's adapter pin (the residency ref keeps
            # it warm); the band resets to the zero adapter so a freed
            # pool slot is never reachable through a stale index
            self._adapter_pool.release(int(self._aidx[s]))
            self._aidx[s] = 0
            self._mark_dirty("aidx")
        self._free_slot_blocks(s)
        self.metrics.kv_blocks_in_use = self._alloc.blocks_in_use
        self._mark_dirty("alive")

    def _emit(self, s: int, token: int) -> bool:
        """Append one generated token to slot s's request; retire on EOS
        or budget (EOS on the budget-exhausting step reports 'eos').
        Returns True if the slot was retired."""
        h = self._slot_req[s]
        if self._injector is not None \
                and getattr(self._injector, "garbled", False):
            # garble@ drill (ISSUE 15): wrong-but-FINITE output — every
            # emitted token is shifted to a different valid vocab id.
            # Sticky by design (a faulty core keeps computing wrong);
            # the numeric traps never fire, only a known-answer canary
            # mismatch can catch it. Applied at the emission bus, so
            # real requests AND canaries on this engine garble alike.
            token = (int(token) + 1) % int(self._cfg.vocab)
        h.tokens.append(int(token))
        st = self._spec_ctx.get(s)
        if st is not None:  # keep the drafting index current in O(1)
            ctx = st["ctx"]
            ctx.append(int(token))
            pair = (ctx[-2], ctx[-1])
            st["from"] = st["map"].get(pair)
            st["map"][pair] = len(ctx)
        self._counts[s] += 1
        self.metrics.tokens_out += 1
        if h.eos_id is not None and int(token) == int(h.eos_id):
            self._retire(s, "eos")
            return True
        if len(h.tokens) >= h.max_new_tokens:
            self._retire(s, "budget")
            return True
        return False

    def _admit(self, h: ServingHandle, s: int) -> bool:  # band-verb: alias
        """Try to assign a free slot: match the longest cached prefix
        chain, ALIAS its physical blocks into the slot's table
        (ref-counted, zero-copy), copy-on-write any aliased block the
        suffix must write into, and reserve the worst-case remainder
        from the pool. Returns False — leaving the request QUEUED and
        the engine state untouched — when the pool cannot cover the
        reservation even after reclaiming idle trie blocks. No model
        compute happens here — chunks run in step()'s prefill phase.
        A resumed request's context is prompt + already-emitted tokens
        (full_prompt): the pool match below is how "restart from
        scratch" becomes "alias the finished part, keep decoding"."""
        T0 = h.full_prompt.shape[0]
        Bt = self.kv_block_tokens
        need_total = self._blocks_for(T0 + h.max_new_tokens)
        aslot = 0
        pool = self._adapter_pool
        pc = self.prefix_cache
        if h.adapter is not None:
            # the trie is keyed by TOKENS alone, but an adapted model
            # writes adapter-specific K/V: aliasing another tenant's
            # blocks (or publishing ours) would serve tenant A's cache
            # rows to tenant B — adapter-carrying requests skip the
            # shared prefix pool entirely (_publish applies the same
            # rule on the way out)
            pc = None
        # a pure PROBE: a block-starved request retries every step, and
        # retries must not inflate hit/miss stats or restamp LRU order
        # — record_hit/record_miss fire once the admission resolves
        m = pc.match(h.full_prompt, record=False) if pc is not None else None
        if m is not None and m.length == 0:
            m.release()
            m = None
        cursor = n_alias = n_cow = 0
        need_new = need_total
        if m is not None:
            matched = m.length
            # the last prompt token must be COMPUTED — its logits seed
            # the first generated token — so the suffix cursor stops at
            # T0-1 even when the whole prompt is cached…
            cursor = min(matched, T0 - 1)
            n_alias = matched // Bt
            # …and any aliased block overlapping [cursor, T0) (only the
            # last one can: cursor >= (n_alias-1)*Bt) is copy-on-write
            # privatised below, never written through
            n_cow = n_alias - min(n_alias, cursor // Bt)
            need_new = need_total - (n_alias - n_cow)
            self._reclaim_for(need_new)
            if self._alloc.available < need_new:
                # the held match PINS the very chain reclaim would have
                # to evict (a fully-cached prompt whose worst case
                # fills the pool would deadlock here forever) — drop
                # the alias plan and fall through to a cold-miss
                # admission, where those blocks are reclaim's fair game
                m.release()
                m = None
                cursor = n_alias = n_cow = 0
                need_new = need_total
        if m is None:
            self._reclaim_for(need_new)
            if not self._alloc.reserve(need_new):
                return False  # saturated: stay queued (backpressure)
            if pool is not None:
                # pin the request's adapter AFTER the block
                # reservation: a block-starved request retries every
                # scheduler step, and acquiring first would inflate
                # adapter hit counts and restamp the pool LRU per
                # retry (the prefix-probe discipline, applied to
                # adapters). A pool whose every slot is held by live
                # requests leaves this request QUEUED — unwind the
                # block reservation and retry next step
                aslot = pool.acquire(h.adapter)
                if aslot is None:
                    self._alloc.release_reservation(need_new)
                    return False
            if pc is not None:
                pc.record_miss()
        else:
            try:
                # the match is ref-held until the aliases take their
                # own pool refs: reclaim/eviction cannot free a block
                # mid-alias
                if not self._alloc.reserve(need_new):
                    return False  # unreachable single-threaded; defensive
                if pool is not None:
                    # h.adapter is None on this branch (adapter
                    # requests never match the trie): the zero-slot
                    # pin, which always succeeds
                    aslot = pool.acquire(None)
                if self._fp is not None and n_alias:
                    # ISSUE 15 fingerprint spot-check — the aliased
                    # re-open audit point: a DIFFERENT request is about
                    # to attend through these physical blocks (and a
                    # failover/migration RESUME re-attaches to the pool
                    # through this very match), so a silently flipped
                    # block must be caught HERE, before it serves a
                    # single prefix-cache hit. Placed AFTER the
                    # reservation so a block-starved request's per-step
                    # admission retries never pay the device reduction
                    # (the pure-probe discipline); on a mismatch the
                    # trip latches the engine, so the half-taken
                    # reservation dies with it. All dispatches are
                    # issued before the first host sync, so an N-block
                    # chain costs ~one round-trip, not N (a fixed-shape
                    # batched reduction would save the dispatches too —
                    # the PERF.md honest-overhead row tracks it)
                    if self._fp_fn is None:
                        self._fp_of(int(m.payloads[0]))  # trace once
                    pend = [(int(m.payloads[d]),
                             self._fp_fn(self._cache,
                                         jnp.int32(int(m.payloads[d]))))
                            for d in range(n_alias)]
                    for bid, fp_d in pend:
                        if not self._fp.check(bid, float(fp_d)):
                            self._trip(
                                "fingerprint",
                                "KV block %d fingerprint mismatch on "
                                "aliased re-open (committed %r)"
                                % (bid, self._fp.expected(bid)))
                pc.record_hit(m)  # the probe resolves to a real use
                keep = n_alias - n_cow
                for d in range(keep):
                    bid = int(m.payloads[d])
                    self._alloc.incref(bid)
                    self._tables[s, d] = bid
                for d in range(keep, n_alias):
                    nb = self._alloc.alloc_reserved()
                    if self._cow_fn is None:
                        self._cow_fn = self._make_cow()
                    self._cache = self._cow_fn(
                        self._cache, jnp.int32(nb),
                        jnp.int32(int(m.payloads[d])))
                    self._tables[s, d] = nb
                    self.metrics.cow_blocks += 1
            finally:
                m.release()
        # ISSUE 16 handoff import: the migration/failover package ships
        # the source replica's CLOSED prompt blocks as self-describing
        # store records — upload each into a freshly materialised block
        # (consuming this slot's reservation, exactly like a prefill
        # allocation would) after token/crc checks, then verify the
        # RECOMPUTED on-device fingerprint against the record's: the
        # PR 15 fingerprint IS the transfer checksum. Any failure stops
        # the import at the last good block (a child's KV attends
        # through its ancestors — importing past a hole would be
        # wrong); the prefill cursor then covers the shortfall, so the
        # fallback is re-prefill: counted, never wrong.
        n_imp = 0
        imp_fail = False
        package = h.handoff
        store = self._kv_store
        if package:
            for d in range(n_alias,
                           min(len(package), self.blocks_per_slot)):
                rec = package[d]
                blk = tuple(int(t)
                            for t in h.full_prompt[d * Bt:(d + 1) * Bt])
                if (rec.get("kv_quant", "none") != self.kv_quant
                        or tuple(rec["tokens"]) != blk
                        or len(rec["payload"]) != rec["nbytes"]
                        or payload_crc(rec["payload"]) != rec["crc"]):
                    imp_fail = True
                    break
                bid = self._alloc.alloc_reserved()
                ok = self._upload_block_record(rec, bid)
                fp_d = self._fp_of(bid) if ok else None
                if not ok or not self._record_fp_ok(rec, fp_d):
                    # the freed block does NOT restore the reservation
                    # alloc_reserved consumed — re-reserve it (the just-
                    # freed block guarantees success) so the slot's
                    # reserved-tail accounting stays balanced
                    self._decref_block(bid)
                    self._alloc.reserve(1)
                    if store is not None and ok:
                        store.quarantine(rec["key"])
                        self.metrics.store_quarantined += 1
                    imp_fail = True
                    break
                if self._fp is not None:
                    self._fp.commit(bid, fp_d)
                self._tables[s, d] = bid
                n_imp += 1
            if n_imp:
                cursor = min((n_alias + n_imp) * Bt, T0 - 1)
                self.metrics.handoff_blocks_imported += n_imp
                self.metrics.handoff_tokens_imported += n_imp * Bt
                h.handoff_imported = n_imp * Bt
        # the zero-recompute audit: closed-block prompt tokens the
        # source had finished vs where this admission's prefill cursor
        # actually starts. The final prompt token (T0-1) always
        # computes — its logits seed the first generated token — so
        # the contract excludes it. A resumed admission with NO package
        # charges every closed block it re-prefills (handoff absent or
        # disabled: the counted degradation path).
        expected = 0
        if package:
            expected = min(len(package) * Bt, T0 - 1)
        elif h.resume_len > 0:
            expected = min((T0 // Bt) * Bt, T0 - 1)
        recomputed = max(0, expected - cursor)
        self.metrics.tokens_recomputed_at_migration += recomputed
        if package:
            if recomputed > 0 or imp_fail:
                self.metrics.handoff_fallbacks += 1
                h.handoff_fallback = True
            else:
                # clean: imported, or already resident via the warmed
                # trie (n_imp == 0 with full alias coverage) — either
                # way zero tokens re-prefilled
                self.metrics.handoff_imports += 1
            # every judged package reports an outcome — the journal's
            # done record must account for the assign's handoff
            # side-band (J011), silence is never an answer
            h.handoff_outcome = {"imported": h.handoff_imported,
                                 "fallback": h.handoff_fallback}
            h.handoff = None  # release the payload bytes
        self._n_alloc[s] = n_alias + n_imp
        self._reserved_tail[s] = need_new - n_cow - n_imp
        if pc is not None:
            self.metrics.prefix_hit_tokens.append(cursor if n_alias else 0)
        h.queue_wait_s = time.monotonic() - h.submit_t
        self.metrics.queue_wait_s.append(h.queue_wait_s)
        self.metrics.kv_blocks_in_use = self._alloc.blocks_in_use
        self._slot_req[s] = h
        self._limits[s] = T0 + h.max_new_tokens
        self._aidx[s] = aslot
        self._mark_dirty("tables", "limits", "aidx")
        # the first-token sampling key is per-request, not per-chunk:
        # computed once here, consumed on the prompt's final chunk. A
        # resumed request's first NEW token is overall token index
        # resume_len — the fold_in schedule continues where the dead
        # incarnation stopped, so sampled outputs stay resume-invariant
        self._prefill_state[s] = {
            "handle": h, "cursor": cursor,
            "key": jax.random.fold_in(
                jax.random.PRNGKey(h.seed), h.resume_len),
        }
        self._prefill_q.append(s)
        return True

    def _publish(self, s: int, h: ServingHandle):  # band-verb: serialize
        """Publish the finished prompt's prefix blocks (up to the
        request's publish boundary) back to the pool — zero-copy: the
        trie takes a ref on the slot's PHYSICAL block ids. Novel blocks
        only; a chain the trie already holds gains nothing."""
        pc = self.prefix_cache
        if pc is None or h.adapter is not None:
            # adapter-specific K/V must never enter the shared trie
            # (the _admit cross-tenant poisoning rule, outbound half)
            return
        T0 = h.full_prompt.shape[0]
        bound = T0 if h.publish_len is None else min(h.publish_len, T0)
        Bt = pc.block_tokens
        n_blocks = bound // Bt
        if n_blocks < 1:
            return
        store = self._kv_store
        # chain keys for the store records: one fold per publish call,
        # shared with the trie summary and the router (fold_key) — the
        # store is keyed by the SAME chain identity the trie uses
        keys = (chain_keys(h.full_prompt[:n_blocks * Bt], Bt)
                if store is not None else None)

        def _take(d):
            bid = int(self._tables[s, d])
            self._alloc.incref(bid)
            fp = None
            if self._fp is not None or store is not None:
                fp = self._fp_of(bid)
            if self._fp is not None:
                # ISSUE 15: publish is where a block CLOSES — it is
                # full (only whole prompt blocks publish; the slot's
                # later decode writes land past them) and any future
                # write goes through COW to a private copy. Commit the
                # fingerprint now; aliased re-opens verify against it.
                self._fp.commit(bid, fp)
            if store is not None:
                # ISSUE 16 write-through: a closing block leaves the
                # replica as a self-describing record, the committed
                # fingerprint riding along as the transfer checksum.
                # Novel blocks only (publish skips trie-held chains):
                # a chain the store evicted since its first spill is
                # NOT re-spilled — accepted staleness, the fallback
                # path covers it.
                payload, meta = self._serialize_block(bid)
                store.put(make_block_record(
                    keys[d], keys[d - 1] if d else 0,
                    tuple(int(t)
                          for t in h.full_prompt[d * Bt:(d + 1) * Bt]),
                    fp, payload, meta, kv_quant=self.kv_quant))
                self.metrics.store_spilled_blocks += 1
            return bid

        pc.publish(h.full_prompt, n_blocks, _take)

    def _run_chunk(self, s: int) -> bool:  # band-verb: resume
        """Advance slot s's prefill by one chunk; on the final chunk,
        publish the prefix, activate the slot, and emit the first
        token. Returns True when the prefill completed."""
        st = self._prefill_state[s]
        h = st["handle"]
        T0 = h.full_prompt.shape[0]
        cursor = st["cursor"]
        c = T0 - cursor
        if self.prefill_chunk_tokens is not None:
            c = min(c, self.prefill_chunk_tokens)
        self._ensure_blocks(s, cursor, cursor + c)
        Cb = self._bucket(c)
        padded = np.zeros(Cb, np.int32)
        padded[:c] = h.full_prompt[cursor:cursor + c]
        fn = self._chunk_fn(Cb)
        t0 = time.monotonic()
        self._cache, first, trap_d, scale_d = fn(
            self._params, self._cache, jnp.asarray(padded),
            jnp.int32(cursor), jnp.asarray(self._tables[s]),
            jnp.int32(c), jnp.float32(h.temperature), st["key"],
            **self._adapter_args(jnp.int32(int(self._aidx[s]))),
        )
        st["cursor"] = cursor + c
        self.metrics.prefill_chunks += 1
        self.metrics.prefill_tokens_computed += c
        self.metrics.kv_blocks_in_use = self._alloc.blocks_in_use
        if st["cursor"] < T0:
            # mid-prompt chunk: dispatch only, nothing to read back —
            # the batched decode below overlaps with it
            self.metrics.span("prefill_T%d" % Cb, time.monotonic() - t0)
            return False
        first = int(np.asarray(first))  # blocks: first token is real
        if self.integrity_traps:
            # the trap rides the same readback sync (mid-prompt chunks
            # stay dispatch-only: a mid-chunk NaN propagates through
            # the cache into THIS final chunk's logits)
            self._check_integrity(trap_d, np.asarray(scale_d),
                                  "prefill chunk", slots=[s])
        now = time.monotonic()
        h.ttft_s = now - h.submit_t
        self.metrics.ttft_s.append(h.ttft_s)
        self.metrics.span("prefill_T%d" % Cb, now - t0)
        self.metrics.observe_device_interval(t0, now)
        self.metrics.prefills += 1
        self._publish(s, h)
        del self._prefill_state[s]

        self._tok[s] = first
        self._pos[s] = T0
        self._alive[s] = True
        self._temps[s] = h.temperature
        # a resumed request continues the ORIGINAL fold_in schedule:
        # its next sampled token is overall index resume_len
        self._counts[s] = h.resume_len
        self._base_keys[s] = np.asarray(jax.random.PRNGKey(h.seed))
        # device-side EOS judgment for the decode window (-1 = none);
        # the _mark_dirty() below re-uploads it with everything else
        self._eos[s] = -1 if h.eos_id is None else int(h.eos_id)
        if self.spec_draft_len is not None:
            # seed the drafting index from the context once (O(T0));
            # _emit keeps it current per token from here on
            ctx = [int(t) for t in h.full_prompt]
            bmap = {}
            for i in range(len(ctx) - 1):
                bmap[(ctx[i], ctx[i + 1])] = i + 2
            self._spec_ctx[s] = {"ctx": ctx, "map": bmap, "from": None}
        self._mark_dirty()  # all bands: slot s changed everywhere
        self._emit(s, first)  # may retire immediately (max_new==1 / eos)
        return True

    def _drop_slot(self, s: int, reason: str):
        """Terminate slot s's request without emitting: clear any
        pending prefill cursor, then retire (frees blocks + the
        reserved tail). The deadline/cancel path — the slot's work is
        abandoned, not completed."""
        if s in self._prefill_state:
            del self._prefill_state[s]
            self._prefill_q.remove(s)
        self._retire(s, reason)

    def _expire_sweep(self) -> bool:
        """Enforce per-request deadlines at every queue hop (ISSUE 8):
        queued requests expire before admission, prefilling slots
        before their next chunk, decoding slots before the next batched
        step — the scheduler stops spending compute on a request the
        moment it cannot be answered in budget. Expiry is a VERDICT
        (finish_reason 'expired', done=True), never a silent hang."""
        if not self._deadlines:
            return False
        now = time.monotonic()
        changed = False
        seen = False  # any deadline still pending after this sweep?
        keep: collections.deque = collections.deque()
        while self._queue:
            h = self._queue.popleft()
            if h.deadline_at is not None and now >= h.deadline_at:
                h.done = True
                h.finish_reason = "expired"
                self.metrics.expired += 1
                changed = True
            else:
                seen = seen or h.deadline_at is not None
                keep.append(h)
        self._queue = keep
        for s in range(self.max_slots):
            h = self._slot_req[s]
            if h is not None and h.deadline_at is not None:
                if now >= h.deadline_at:
                    self._drop_slot(s, "expired")
                    self.metrics.expired += 1
                    changed = True
                else:
                    seen = True
        if not seen:
            # nothing left carries a deadline: drop the latch (the
            # next deadline submit re-arms it) so a long-lived engine
            # does not pay the sweep forever for one SLO request
            self._deadlines = False
        return changed

    def cancel(self, rid) -> bool:
        """Terminate one request (by this ENGINE's rid) wherever it is
        — queued, prefilling, or decoding — freeing its slot and
        blocks; the handle finishes with reason 'cancelled' and its
        partial tokens. The fleet uses this to claw work back from a
        demoted (gray-slow) replica after hedging it to a survivor;
        the demoted engine must stop spending steps on it. Returns
        False if the rid is unknown or already finished."""
        for h in self._queue:
            if h.rid == rid and not h.done:
                self._queue.remove(h)
                h.done = True
                h.finish_reason = "cancelled"
                self.metrics.cancelled += 1
                return True
        for s in range(self.max_slots):
            h = self._slot_req[s]
            if h is not None and h.rid == rid:
                self._drop_slot(s, "cancelled")
                self.metrics.cancelled += 1
                return True
        return False

    def abort(self, exc: BaseException):
        """Latch the engine as failed and propagate `exc` into every
        pending handle (queued, prefilling, or decoding): their
        `result()` raises instead of blocking forever. Called
        internally when a step dies, and externally by whatever thread
        drives the engine (a fleet replica loop) when IT dies between
        steps. Idempotent; the first failure wins."""
        if self._failed is None:
            if isinstance(exc, EngineFailed):
                self._failed = exc
            else:
                self._failed = EngineFailed(
                    "engine%s failed: %r" % (
                        "" if self.replica_id is None
                        else " (replica %s)" % self.replica_id,
                        exc),
                    replica=self.replica_id)
                self._failed.__cause__ = exc
        for h in list(self._queue) + list(self._slot_req):
            if h is not None and not h.done and h.error is None:
                h.error = self._failed

    def step(self) -> bool:
        """One scheduler iteration: expire anything past its deadline
        (queued, prefilling, or decoding — a verdict before another
        token of work is spent on it), admit queued requests into free
        slots (prefix aliasing + block reservation; a block-starved
        pool leaves them queued), advance pending prefills by up to
        `max_prefills_per_step` chunks (FCFS), then ONE batched decode
        — or, with `spec_draft_len` set, ONE batched speculative
        verify emitting up to K tokens per slot — advancing every live
        slot; retirements free blocks and slots for the next step's
        admissions. Returns False when there was nothing to do (queue
        empty, no pending prefill, no live slots).

        Each call ticks the fault injector (PADDLE_FAULT, or the
        engine's own `fault_injector`) BEFORE doing work, so
        `kill@N`/`exc@N`/`delay@N:dur` specs land mid-decode — the
        fleet kill drills' step boundary. Any failure (injected or
        real) aborts every pending handle and latches the engine: the
        compiled steps donate their cache buffers, so a step that died
        mid-dispatch must never run again on the half-donated cache."""
        if self._sched_hook is not None:
            self._sched_hook.yield_point(
                "engine:%s:step" % (self.replica_id or ""))
        if self._failed is not None:
            raise self._failed
        inj = self._injector
        if inj is None:
            inj = self._injector = (
                _fi.default_injector()
                if os.environ.get(_fi.ENV_VAR) else _fi.FaultInjector("")
            )
        t0 = time.monotonic()
        try:
            if inj.active:
                inj.tick()
                if inj.take_flip():
                    # flip@ drill (ISSUE 15): silent KV corruption —
                    # finite garbage into one resident block, invisible
                    # to the numeric traps, caught only by the
                    # fingerprint spot-check at aliased re-open
                    self._flip_resident_block()
            out = self._step_inner()
        except Exception as exc:
            self.abort(exc)
            raise
        # step-latency EWMA INCLUDES the injector tick: an injected
        # gray stall (slow@) is exactly what the fleet's health score
        # must see here. Normalized PER TOKEN (ISSUE 19 satellite): a
        # K-token window legitimately takes ~K x longer per step and
        # must not read as a gray stall or shift the fleet's live-
        # median demotion threshold. The STATIC window size, not the
        # emitted count — a low-occupancy window still does K
        # iterations of device work, and dividing by fewer emitted
        # tokens would make an idle replica read slow (false demotion).
        self.metrics.observe_step(time.monotonic() - t0,
                                  tokens=self.decode_window)
        return out

    def _step_inner(self) -> bool:
        progressed = self._expire_sweep()
        while self._queue:
            s = self._free_slot()
            if s is None:
                break
            if not self._admit(self._queue[0], s):
                break  # block-starved: FCFS head waits, so do followers
            self._queue.popleft()
            progressed = True

        cap = self.max_prefills_per_step
        chunks = 0
        for s in list(self._prefill_q):
            if cap is not None and chunks >= cap:
                break
            if self._run_chunk(s):
                self._prefill_q.remove(s)
            chunks += 1
            progressed = True

        if self._use_window:
            # window engines must reach _window_phase even with no
            # host-live slot: a pending async window may still hold
            # the tokens that retire the last requests
            if not self._window_phase():
                return progressed
        elif not self._alive.any():
            return progressed
        elif self.spec_draft_len is not None:
            self._spec_step()
        else:
            self._decode_once()

        frag = 0
        for s in np.nonzero(self._alive)[0]:
            frag += int(self._n_alloc[s]) * self.kv_block_tokens \
                - int(self._pos[s])
        for s in self._prefill_q:
            frag += int(self._n_alloc[s]) * self.kv_block_tokens \
                - int(self._prefill_state[s]["cursor"])
        self.metrics.kv_frag_tokens = frag
        self.metrics.kv_blocks_in_use = self._alloc.blocks_in_use
        return True

    def _decode_once(self):  # band-verb: sync
        """The plain (non-speculative) batched decode: one token per
        live slot, bands advanced on device so a steady loop uploads
        nothing (tables change only at a block-boundary append)."""
        live = np.nonzero(self._alive)[0]
        for s in live:
            p = int(self._pos[s])
            self._ensure_blocks(s, p, p + 1)
        t0 = time.monotonic()
        self._cache, nxt_d, pos_d, counts_d, trap_d, scale_d = \
            self._decode_fn(
                self._params, self._cache, self._band("tables"),
                self._band("tok"), self._band("pos"),
                self._band("alive"), self._band("temps"),
                self._band("counts"), self._band("base_keys"),
                **self._adapter_args(self._band("aidx")),
            )
        nxt = np.asarray(nxt_d)  # blocks; tokens are real
        if self.integrity_traps:
            # a tripped slot becomes an integrity event INSTEAD of an
            # emitted token: checked before the emit loop below, so no
            # token from a poisoned step reaches a handle
            self._check_integrity(trap_d, np.asarray(scale_d), "decode")
        # the decode step advanced tok/pos/counts on device; adopt its
        # outputs so an admission-free step re-uploads nothing. (Dead
        # rows: device tok holds this step's don't-care sample, host
        # keeps the stale final token — both are masked and parked, and
        # an admission re-dirties every band anyway.)
        self._dev["tok"], self._dev["pos"], self._dev["counts"] = (
            nxt_d, pos_d, counts_d)
        self._dirty.difference_update(("tok", "pos", "counts"))
        t1 = time.monotonic()
        self.metrics.span("decode_step", t1 - t0)
        self.metrics.observe_device_interval(t0, t1)
        self.metrics.decode_steps += 1
        self.metrics.occupancy.append(
            float(self._alive.sum()) / self.max_slots
        )

        self._pos[live] += 1  # the token just cached sat at pos
        for s in live:
            self._tok[s] = nxt[s]
            self._emit(s, nxt[s])

    # ------------------------------------------------------------------
    # megabatch decode window (ISSUE 19)
    # ------------------------------------------------------------------
    def _can_chain(self) -> bool:
        """Window N+1 may chain off window N's un-synced device
        outputs only while the device-advanced bands still carry
        device truth: any host event since dispatch (admission,
        retirement, cancel, expiry) dirtied one of them and the chain
        must break — sync first, re-upload host truth, then dispatch."""
        return not (self._dirty & _DEVICE_ADVANCED)

    def _window_phase(self) -> bool:
        """The window engine's decode phase: sync the pending window
        (if any), keep the async pipeline one window deep, or run one
        dispatch+sync in-line (sync mode). Returns False only when
        there is genuinely nothing to do — no live slot AND no pending
        window (a pending window may still hold the tokens that retire
        the final requests, so it must sync even with zero host-live
        slots)."""
        rec, self._inflight = self._inflight, None
        if rec is None and not self._alive.any():
            return False
        if rec is not None:
            chained = None
            if self.async_dispatch and self._alive.any() \
                    and self._can_chain():
                # enqueue window N+1 off window N's device outputs
                # BEFORE syncing N: the emit/schedule work below runs
                # under N+1's device compute (the whole point)
                chained = self._dispatch_window(prev=rec)
            self._sync_window(rec)
            self._inflight = chained
            if chained is None and self.async_dispatch \
                    and self._alive.any():
                # chain broken by a host event: host truth is current
                # again post-sync — refill the pipeline this step
                self._inflight = self._dispatch_window()
            return True
        w = self._dispatch_window()
        if self.async_dispatch:
            self._inflight = w  # one-step-behind emission: sync next step
        else:
            self._sync_window(w)
        return True

    def _dispatch_window(self, prev=None):
        """Enqueue one compiled K-token window. `prev` chains this
        dispatch off the given un-synced window's output bands (host
        mirrors are one window stale then — the block horizon covers
        2K positions so the device never writes past the table)."""
        K = self.decode_window
        live = np.nonzero(self._alive)[0]
        horizon = 2 * K if prev is not None else K
        for s in live:
            p = int(self._pos[s])
            # positions < limits-1 are the only ones ever written (the
            # budget rule parks a slot after its write at limits-2)
            self._ensure_blocks(
                s, p, min(p + horizon, int(self._limits[s]) - 1))
        t0 = time.monotonic()
        if prev is None:
            tok_d, pos_d = self._band("tok"), self._band("pos")
            alive_d, counts_d = self._band("alive"), self._band("counts")
        else:
            tok_d, pos_d, alive_d, counts_d = prev["bands"]
        out = self._window_fn(
            self._params, self._cache, self._band("tables"), tok_d,
            pos_d, alive_d, self._band("temps"), counts_d,
            self._band("base_keys"), self._band("limits"),
            self._band("eos"),
            **self._adapter_args(self._band("aidx")),
        )
        self._cache = out[0]
        self.metrics.decode_steps += 1
        self.metrics.occupancy.append(
            float(self._alive.sum()) / self.max_slots
        )
        return {"bands": out[1:5], "toks": out[5], "traps": out[6],
                "scales": out[7], "t0": t0,
                "slots": [(int(s), self._slot_req[int(s)])
                          for s in live]}

    def _sync_window(self, rec):  # band-verb: sync
        """Sync one dispatched window and emit its tokens in iteration
        order. Lane discipline: -1 lanes are parking padding (the slot
        retired in an earlier iteration) and are discarded; a slot
        whose handle changed since dispatch (expired, cancelled,
        re-tenanted) has its remaining lanes discarded too — an
        expired request keeps its pre-window tokens and nothing more.
        Integrity rows are judged BEFORE their tokens emit, so a trap
        tripping in iteration j poisons only tokens >= j (ISSUE 19
        tentpole rule); all-parked rows are skipped so the spike EWMA
        never ingests masked zeros."""
        K = self.decode_window
        toks = np.asarray(rec["toks"])  # [K, S] — THE sync point
        t1 = time.monotonic()
        self.metrics.span("decode_step", t1 - rec["t0"])
        self.metrics.observe_device_interval(rec["t0"], t1)
        if self.integrity_traps:
            traps_w = np.asarray(rec["traps"])
            scales_w = np.asarray(rec["scales"])
        for j in range(K):
            row = toks[j]
            if self.integrity_traps and (row >= 0).any():
                self._check_integrity(traps_w[j], scales_w[j],
                                      "decode window")
            for s, h in rec["slots"]:
                if self._slot_req[s] is not h or not self._alive[s]:
                    continue  # expired/cancelled/re-tenanted: discard
                t = int(row[s])
                if t < 0:
                    continue  # parked lane
                self._pos[s] += 1  # the token just synced sat at pos
                self._tok[s] = t
                self._emit(s, t)
        # adopt the window's outputs as device truth (steady loop
        # re-uploads nothing) — but only when the host mirrors agree:
        # a host-side divergence (fault drills shifting emitted
        # tokens' EOS judgment, a mid-flight expiry) re-uploads host
        # truth instead of silently trusting the device schedule
        ntok, npos, nalive, ncounts = rec["bands"]
        if (np.array_equal(self._pos, np.asarray(npos))
                and np.array_equal(self._alive, np.asarray(nalive))
                and np.array_equal(self._counts, np.asarray(ncounts))
                and np.array_equal(self._tok, np.asarray(ntok))):
            self._dev["tok"], self._dev["pos"] = ntok, npos
            self._dev["alive"], self._dev["counts"] = nalive, ncounts
            self._dirty.difference_update(_DEVICE_ADVANCED)
        else:
            self._mark_dirty("tok", "pos", "alive", "counts")

    def _draft_window(self, s: int) -> np.ndarray:
        """Self-drafting by prompt lookup: continue the context's last
        bigram from its most recent earlier occurrence (Leviathan et
        al.'s speculative schedule with the request's own text as the
        draft model — free drafts, no second network). Unfilled draft
        rows are -1: never accepted (candidates are valid vocab ids),
        so a draft-less window degrades to plain one-token decode."""
        K = self.spec_draft_len
        w = np.full(K, -1, np.int32)
        w[0] = self._tok[s]  # the pending (unwritten) token leads
        st = self._spec_ctx.get(s)
        if st is not None and st["from"] is not None:
            # tokens following the tail bigram's previous occurrence
            cont = st["ctx"][st["from"]:st["from"] + K - 1]
            w[1:1 + len(cont)] = cont
        return w

    def _spec_step(self):
        """One speculative decode phase: build every live slot's
        K-token window (pending token + K-1 drafts), verify in ONE
        compiled batched step, then emit the model's own candidates up
        to the first draft mismatch (plus the bonus token) — greedy
        emission is exactly the plain path's, only batched in time.
        Host-side acceptance re-uploads the tok/pos/counts bands next
        step (the documented spec trade: ~3 small h2d per multi-token
        step instead of zero per single-token step)."""
        K = self.spec_draft_len
        live = np.nonzero(self._alive)[0]
        window = np.zeros((self.max_slots, K), np.int32)
        for s in live:
            lo = int(self._pos[s])
            self._ensure_blocks(s, lo, min(lo + K, int(self._limits[s])))
            window[s] = self._draft_window(s)
        t0 = time.monotonic()
        self._cache, cand_d, trap_d, scale_d = self._verify_fn(
            self._params, self._cache, self._band("tables"),
            jnp.asarray(window), self._band("pos"), self._band("alive"),
            self._band("limits"), self._band("temps"),
            self._band("counts"), self._band("base_keys"),
            **self._adapter_args(self._band("aidx")),
        )
        cand = np.asarray(cand_d)  # blocks; candidates are real
        if self.integrity_traps:
            self._check_integrity(trap_d, np.asarray(scale_d),
                                  "spec verify")
        t1 = time.monotonic()
        self.metrics.span("spec_verify", t1 - t0)
        self.metrics.observe_device_interval(t0, t1)
        self.metrics.decode_steps += 1
        self.metrics.occupancy.append(
            float(self._alive.sum()) / self.max_slots
        )
        for s in live:
            h = self._slot_req[s]
            m = 0  # accepted drafts: longest window prefix the model agrees with
            while m < K - 1 and window[s, m + 1] == cand[s, m]:
                m += 1
            budget_left = h.max_new_tokens - len(h.tokens)
            n = min(m + 1, budget_left)
            self.metrics.spec_windows += 1
            # count only drafts actually PROPOSED (-1 rows are empty
            # lanes, not rejections) AND within the request's remaining
            # budget (a final window's over-budget lanes can never be
            # accepted): accept_rate stays an honest measure of draft
            # quality
            lanes = window[s, 1:max(1, budget_left)]
            self.metrics.spec_drafted += int((lanes >= 0).sum())
            adv = 0
            for j in range(n):
                adv += 1
                self._tok[s] = cand[s, j]
                if self._emit(s, cand[s, j]):
                    break  # EOS/budget: later accepted drafts discarded
            self._pos[s] += adv  # one cache write per emitted token
            self.metrics.spec_accepted += max(0, adv - 1)
        # acceptance is a host decision: these bands re-upload next step
        self._mark_dirty("tok", "pos", "counts")

    def run(self) -> Dict[int, np.ndarray]:
        """Drive the engine until the queue drains and every slot
        retires; returns {request_id: full sequence} for every request
        completed during this call."""
        finished: Dict[int, np.ndarray] = {}
        # a retired handle never lingers in _slot_req, so everything
        # in-flight or queued right now is exactly this call's work
        pending = list(self._queue) + [
            h for h in self._slot_req if h is not None
        ]
        while self.step():
            pass
        for h in pending:
            if h.done:
                # full_prompt: a resumed request's sequence includes
                # the tokens the earlier incarnation already emitted
                finished[h.rid] = np.concatenate(
                    [h.full_prompt, np.asarray(h.tokens, np.int32)]
                )
        return finished

    # ------------------------------------------------------------------
    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def live_slots(self) -> int:
        return int(self._alive.sum())

    @property
    def prefilling_slots(self) -> int:
        return len(self._prefill_q)

    @property
    def kv_blocks_in_use(self) -> int:
        return self._alloc.blocks_in_use

    @property
    def kv_blocks_free(self) -> int:
        return self._alloc.free_blocks
