"""Open-loop load generator for the serving front door (ISSUE 18):
fixed-rate Poisson arrivals over real sockets, swept to the capacity
knee.

Open loop, not closed loop — the sizing rule that matters (Schroeder
et al., "Open Versus Closed: A Cautionary Tale", NSDI'06): a
closed-loop driver waits for a completion before sending the next
request, so when the server saturates the DRIVER slows down with it
and the measured latency stays politely bounded — collapse is
structurally invisible. An open-loop driver sends at the scheduled
arrival times no matter what is outstanding, which is how real
traffic behaves; past the knee the backlog grows without bound and
p99 TTFT inflects while goodput flattens at capacity. Only the open
loop can find that knee, and the knee — not the closed-loop
throughput — is the number an operator can size against.

Determinism: arrivals, tenant choices, prompts, and budgets all come
from one seeded RandomState, so a sweep is reproducible request-for-
request; only wall-clock timings vary run to run.

Every timing below is host wall-clock around socket I/O — CPU-honest
shape measurements (shed rates, divergence, relative knee position),
not chip throughput claims (PERF.md's on-chip-pending discipline)."""

import threading
import time
from typing import Dict, List, Optional

import numpy as np

from .wire import WireClient

# TTFT histogram bucket edges (seconds) for SLO scoring of chaos runs:
# a kill drill shows up as mass migrating to the tail buckets, which a
# bare mean would average away
SLO_BUCKETS_S = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0)


class LoadReport(dict):
    """One open-loop run's report: a dict (JSON-able, bench-row
    friendly) with attribute sugar for the common keys."""

    def __getattr__(self, k):
        try:
            return self[k]
        except KeyError:
            raise AttributeError(k)


def _pct(xs: List[float], q: float) -> Optional[float]:
    if not xs:
        return None
    return float(np.percentile(np.asarray(xs, np.float64), q))


def _histogram(xs: List[float]) -> Dict[str, int]:
    """TTFT values -> SLO bucket counts ("<=0.05s", ..., ">5.0s")."""
    out: Dict[str, int] = {}
    for edge in SLO_BUCKETS_S:
        out["<=%gs" % edge] = 0
    out[">%gs" % SLO_BUCKETS_S[-1]] = 0
    for x in xs:
        for edge in SLO_BUCKETS_S:
            if x <= edge:
                out["<=%gs" % edge] += 1
                break
        else:
            out[">%gs" % SLO_BUCKETS_S[-1]] += 1
    return out


class _Recorder(object):
    """Per-connection frame collector. One reader thread per
    connection drains server frames into per-request records; the
    dispatcher never blocks on it (open loop)."""

    def __init__(self, client: WireClient):
        self.client = client
        self.lock = threading.Lock()
        self.records: Dict[str, dict] = {}   # guarded-by: lock
        self.thread = threading.Thread(
            target=self._loop, name="loadgen-reader", daemon=True)
        self.thread.start()

    def expect(self, req_id: str, t_send: float, tenant: str,
               streamed: bool):
        with self.lock:
            self.records[req_id] = {
                "tenant": tenant, "streamed": streamed,
                "t_send": t_send, "t_first": None, "token_t": [],
                "chunks": [], "done": None, "error": None,
                "t_done": None, "rid": None,
            }

    def _loop(self):  # thread: loadgen-reader
        while True:
            try:
                f = self.client.recv()
            except Exception:
                return
            if f is None:
                return
            now = time.monotonic()
            rid = f.get("id")
            with self.lock:
                rec = self.records.get(rid)
                if rec is None:
                    continue
                op = f.get("op")
                if op == "accepted":
                    rec["rid"] = f.get("rid")
                elif op == "tokens":
                    if rec["t_first"] is None:
                        rec["t_first"] = now
                    rec["token_t"].append(now)
                    rec["chunks"].append(list(f["tokens"]))
                elif op == "done":
                    if rec["t_first"] is None:
                        rec["t_first"] = now
                    rec["done"] = list(f["tokens"])
                    rec["t_done"] = now
                elif op == "error":
                    rec["error"] = f.get("code", "INTERNAL")
                    rec["t_done"] = now

    def unresolved(self) -> List[str]:
        with self.lock:
            return [k for k, r in self.records.items()
                    if r["done"] is None and r["error"] is None]


def run_open_loop(address, tenants, rate_rps: float,
                  duration_s: float, seed: int = 0,
                  prompt_len: int = 4, max_new_tokens: int = 8,
                  vocab: int = 97, deadline_s: Optional[float] = None,
                  stream: bool = True,
                  settle_s: float = 30.0,
                  chaos_after_s: Optional[float] = None,
                  chaos_fn=None) -> LoadReport:
    """One fixed-rate open-loop run against a front door at
    `address`. `tenants` is a list of dicts: {"name", "token",
    "weight"} (token None for a single-tenant fleet; weights are
    arrival-mix probabilities, uniform when omitted). `chaos_fn` is
    called once (from the dispatch thread) when `chaos_after_s` of
    load has elapsed — the hook the chaos variant uses to
    kill/slow a replica mid-load. `settle_s` bounds the post-dispatch
    wait for outstanding verdicts; anything still unresolved then is
    counted `wire_unresolved` (and is a finding, not a shrug)."""
    rng = np.random.RandomState(int(seed))
    n = max(1, int(round(float(rate_rps) * float(duration_s))))
    arrivals = np.cumsum(rng.exponential(1.0 / float(rate_rps), n))
    weights = np.asarray(
        [float(t.get("weight", 1.0)) for t in tenants], np.float64)
    weights = weights / weights.sum()
    t_ix = rng.choice(len(tenants), size=n, p=weights)
    prompts = rng.randint(1, int(vocab), size=(n, int(prompt_len)))

    recs: List[_Recorder] = []
    for t in tenants:
        client = WireClient(address, token=t.get("token"))
        recs.append(_Recorder(client))

    # -- dispatch (open loop: send at the SCHEDULED time, regardless
    # of what is outstanding — never gated on completions)
    t0 = time.monotonic()
    chaos_done = chaos_after_s is None
    sent = 0
    for k in range(n):
        target = t0 + float(arrivals[k])
        while True:
            now = time.monotonic()
            if not chaos_done and now - t0 >= float(chaos_after_s):
                chaos_done = True
                if chaos_fn is not None:
                    chaos_fn()
            if now >= target:
                break
            time.sleep(min(0.002, target - now))
        ti = int(t_ix[k])
        rec = recs[ti]
        req_id = "t%d-%d" % (ti, k)
        rec.expect(req_id, time.monotonic(),
                   tenants[ti]["name"], stream)
        kw = {}
        if deadline_s is not None:
            kw["deadline_s"] = float(deadline_s)
        if stream:
            kw["stream"] = True
        try:
            rec.client.generate(req_id, [int(x) for x in prompts[k]],
                                int(max_new_tokens), seed=int(k),
                                **kw)
            sent += 1
        except Exception:
            with rec.lock:
                rec.records[req_id]["error"] = "SEND_FAILED"
    if not chaos_done and chaos_fn is not None:
        chaos_fn()

    # -- settle: wait for every outstanding verdict (bounded)
    deadline = time.monotonic() + float(settle_s)
    while time.monotonic() < deadline:
        if not any(r.unresolved() for r in recs):
            break
        time.sleep(0.01)
    elapsed = time.monotonic() - t0
    for r in recs:
        r.client.close()
        r.thread.join(timeout=5.0)

    # -- score
    ttft: List[float] = []
    itl: List[float] = []
    per_tenant: Dict[str, dict] = {
        t["name"]: {"sent": 0, "completed": 0, "shed": {},
                    "unresolved": 0} for t in tenants}
    completed = 0
    divergent = 0
    rids_seen: Dict[int, int] = {}
    for r in recs:
        with r.lock:
            items = list(r.records.items())
        for _req_id, rec in items:
            pt = per_tenant[rec["tenant"]]
            pt["sent"] += 1
            if rec["rid"] is not None:
                rids_seen[rec["rid"]] = rids_seen.get(
                    rec["rid"], 0) + 1
            if rec["done"] is not None:
                completed += 1
                pt["completed"] += 1
                ttft.append(rec["t_first"] - rec["t_send"])
                ts = rec["token_t"]
                itl.extend(b - a for a, b in zip(ts, ts[1:]))
                if rec["streamed"]:
                    got = [t for c in rec["chunks"] for t in c]
                    if got != rec["done"]:
                        divergent += 1
            elif rec["error"] is not None:
                pt["shed"][rec["error"]] = \
                    pt["shed"].get(rec["error"], 0) + 1
            else:
                pt["unresolved"] += 1
    shed_total: Dict[str, int] = {}
    unresolved = 0
    for pt in per_tenant.values():
        unresolved += pt["unresolved"]
        for code, cnt in pt["shed"].items():
            shed_total[code] = shed_total.get(code, 0) + cnt
    return LoadReport(
        rate_rps=float(rate_rps), duration_s=float(duration_s),
        seed=int(seed), requests=n, sent=sent, completed=completed,
        offered_rps=round(n / elapsed, 3) if elapsed else None,
        goodput_rps=round(completed / elapsed, 3) if elapsed else None,
        ttft_p50_s=_pct(ttft, 50), ttft_p99_s=_pct(ttft, 99),
        ttft_p999_s=_pct(ttft, 99.9),
        itl_p50_s=_pct(itl, 50), itl_p99_s=_pct(itl, 99),
        itl_p999_s=_pct(itl, 99.9),
        shed=shed_total, per_tenant=per_tenant,
        stream_divergent=divergent,
        wire_unresolved=unresolved,
        duplicate_rids=sum(c - 1 for c in rids_seen.values() if c > 1),
        slo_histogram=_histogram(ttft),
    )


def sweep(address, tenants, rates, duration_s: float,
          seed: int = 0, **kw) -> List[LoadReport]:
    """Rate sweep: one open-loop run per rate (same seed base, so the
    arrival PATTERN scales with the rate deterministically)."""
    return [run_open_loop(address, tenants, r, duration_s,
                          seed=seed + i, **kw)
            for i, r in enumerate(rates)]


def find_knee(reports: List[LoadReport]) -> dict:
    """Locate the capacity knee in a rate sweep: the first rate where
    goodput stops tracking the offered rate (flattens at capacity —
    sheds absorb the excess) while p99 TTFT inflects versus the
    lowest-rate baseline. Returns {"knee_rate_rps", "reason"} with
    None when the sweep never saturated (all rates under capacity —
    sweep higher)."""
    if not reports:
        return {"knee_rate_rps": None, "reason": "empty sweep"}
    base_p99 = reports[0].get("ttft_p99_s") or 0.0
    for rep in reports:
        offered = rep.get("offered_rps") or 0.0
        goodput = rep.get("goodput_rps") or 0.0
        p99 = rep.get("ttft_p99_s")
        sheds = sum(rep.get("shed", {}).values())
        flat = offered > 0 and goodput < 0.8 * offered
        inflected = (p99 is not None and base_p99 > 0
                     and p99 > 2.0 * base_p99)
        if flat and (inflected or sheds > 0):
            return {"knee_rate_rps": rep["rate_rps"],
                    "reason": "goodput %.3f rps vs offered %.3f rps "
                              "(%d shed), p99 TTFT %s vs baseline "
                              "%.4fs" % (goodput, offered, sheds,
                                         ("%.4fs" % p99)
                                         if p99 is not None else "n/a",
                                         base_p99)}
    return {"knee_rate_rps": None,
            "reason": "no rate saturated: goodput tracked offered "
                      "load at every step (sweep higher)"}
