"""Durable KV tier (ISSUE 16): checksummed block handoff + a
crash-survivable tiered prefix store beneath the fleet.

The fleet survives replica death (PR 6), gray failure (PR 8), and
silent corruption (PR 15), but its KV state does not: migration and
failover RE-PREFILL the finished prefix on the target, and every
replica's prefix trie is private, RAM-bound, and dies with the
process. This module is the missing memory tier — the reference's
`PoolAllocator.h`/`MemoryHandle` pooled-allocator story at fleet
scale, with the pserver push/pull + etcd durability discipline recast
as KV movement between inference replicas:

  block SERIALIZATION    a closed KV block leaves a replica as a
                         self-describing record: the raw storage bytes
                         of every (layer, band) slice — quantized
                         codes AND their per-(block, head) scale
                         side-bands — plus the block's token tuple,
                         its chain key (`prefix_cache.fold_key`, the
                         ONE key definition routing already uses), and
                         the PR 15 device fingerprint, which IS the
                         transfer checksum. A host-side crc32 of the
                         payload bytes guards the record AT REST
                         (bit-rot on disk / in host RAM); the device
                         fingerprint guards it END TO END (recomputed
                         on the importing device after upload).
  replica HANDOFF        at migration/failover the fleet fetches the
                         finished prefix's chain from the store and
                         attaches it to the re-route; the target
                         imports the blocks straight into its pool
                         after fingerprint verification, so
                         `tokens_recomputed_at_migration == 0` on the
                         clean path. Re-prefill is DEMOTED to the
                         fallback taken on mismatch/absence — counted,
                         never wrong.
  tiered PREFIX STORE    closed blocks spill here write-through at
                         publish; the store holds them in host RAM
                         and (with `dir=`) an append-only
                         `store.jsonl` with the journal's atomic-
                         commit discipline: torn FINAL line tolerated,
                         compaction via tmp + fsync + os.replace.
                         Eviction is leaf-first LRU under a byte
                         budget (evicting a leaf never orphans a
                         longer chain — the trie's own rule). A
                         restarted or freshly-autoscaled replica warms
                         its trie FROM the store instead of from
                         traffic.
  QUARANTINE             a record whose payload fails its crc (or
                         whose fingerprint fails on-device) is
                         skipped, dropped, and quarantined — never
                         served, sticky across restarts.

One deliberate divergence from the journal's corruption rule: a
mid-file garbage line in `store.jsonl` is SKIPPED and counted
(`corrupt_dropped`), not an audit failure — the store is a CACHE of
recomputable state, not the truth; losing an entry costs a re-prefill,
serving a corrupt one would cost correctness. The journal, which IS
the truth, keeps its hard J008 line.

Threading: ONE store is shared by every replica in a fleet (source
threads spill, target threads import, the fleet routes against the
summary), so unlike the engine's thread-confined side-bands the store
carries its own lock — the same discipline as `RequestJournal`.
"""

from __future__ import annotations

import base64
import json
import os
import threading
import zlib
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from .prefix_cache import fold_key

__all__ = ["KVBlockStore", "make_block_record", "payload_crc"]


def payload_crc(payload: bytes) -> int:
    """The ONE at-rest checksum definition: crc32 over the raw payload
    bytes. Host-side only — the end-to-end check is the device
    fingerprint carried in the record."""
    return zlib.crc32(payload) & 0xFFFFFFFF


def make_block_record(key: int, parent: int, tokens, fp: float,  # band-verb: serialize
                      payload: bytes, meta, kv_quant: str = "none") -> dict:
    """Build one self-describing block record. `meta` lists the
    payload's concatenated slices as (name, dtype, shape) with name
    "<layer>.<band>" — enough for any pool of the same config to
    reassemble the block without the exporter's engine. `fp` is the
    committed device fingerprint (integrity.BlockFingerprints), the
    transfer checksum the importer re-verifies on its own device."""
    return {
        "key": int(key),
        "parent": int(parent),
        "tokens": tuple(int(t) for t in tokens),
        "fp": float(fp),
        "crc": payload_crc(payload),
        "nbytes": len(payload),
        "kv_quant": str(kv_quant),
        "meta": [(str(n), str(d), tuple(int(x) for x in s))
                 for n, d, s in meta],
        "payload": bytes(payload),
    }


def _encode(rec: dict) -> dict:  # band-verb: serialize
    """Record -> JSON-serialisable dict (payload base64)."""
    out = dict(rec)
    out["tokens"] = [int(t) for t in rec["tokens"]]
    out["meta"] = [[n, d, list(s)] for n, d, s in rec["meta"]]
    out["payload"] = base64.b64encode(rec["payload"]).decode("ascii")
    return out


def _decode(obj: dict) -> dict:  # band-verb: import
    """JSON dict -> record (inverse of _encode). Raises on any
    malformed field — the caller treats a raise as a corrupt line."""
    return {
        "key": int(obj["key"]),
        "parent": int(obj["parent"]),
        "tokens": tuple(int(t) for t in obj["tokens"]),
        "fp": float(obj["fp"]),
        "crc": int(obj["crc"]),
        "nbytes": int(obj["nbytes"]),
        "kv_quant": str(obj["kv_quant"]),
        "meta": [(str(n), str(d), tuple(int(x) for x in s))
                 for n, d, s in obj["meta"]],
        "payload": base64.b64decode(obj["payload"]),
    }


class KVBlockStore(object):
    """Fleet-shared tiered store of closed KV block records, keyed by
    chain key (`prefix_cache.fold_key` over whole leading blocks).
    Host-RAM resident, optionally durable under `dir`; leaf-first LRU
    eviction under `byte_budget`; crc-verified on every get with
    sticky quarantine on mismatch."""

    def __init__(self, byte_budget: Optional[int] = None,
                 dir: Optional[str] = None, block_tokens: int = 16,
                 fault_injector=None):
        if int(block_tokens) < 1:
            raise ValueError("block_tokens must be >= 1")
        if byte_budget is not None and int(byte_budget) < 1:
            raise ValueError("byte_budget must be >= 1 (or None)")
        # thread: any (fleet + every replica thread) — all state below
        # is guarded by _lock unless noted
        self._lock = threading.Lock()
        self.byte_budget = None if byte_budget is None else int(byte_budget)
        self.block_tokens = int(block_tokens)
        self._records: Dict[int, dict] = {}     # guarded-by: _lock
        # key -> number of PRESENT children (leaf == 0): leaf-first
        # eviction's O(1) test
        self._children: Dict[int, int] = {}     # guarded-by: _lock
        self._stamp: Dict[int, int] = {}        # guarded-by: _lock
        self._clock = 0                         # guarded-by: _lock
        self._bytes = 0                         # guarded-by: _lock
        self._quarantined: Set[int] = set()     # guarded-by: _lock
        self._injector = fault_injector         # guarded-by: _lock
        # O(1) counters (ServingMetrics discipline)
        self.puts = 0                           # guarded-by: _lock
        self.hits = 0                           # guarded-by: _lock
        self.misses = 0                         # guarded-by: _lock
        self.evictions = 0                      # guarded-by: _lock
        self.quarantines = 0                    # guarded-by: _lock
        self.corrupt_dropped = 0                # guarded-by: _lock
        self.compactions = 0                    # guarded-by: _lock
        # routing-summary revision cache: rebuilt only when _rev moves
        self._rev = 0                           # guarded-by: _lock
        self._summary_rev = -1                  # guarded-by: _lock
        self._summary: frozenset = frozenset()  # guarded-by: _lock
        self._file = None                       # guarded-by: _lock
        self._file_records = 0                  # guarded-by: _lock
        self._path = None
        if dir is not None:
            os.makedirs(dir, exist_ok=True)
            self._path = os.path.join(dir, "store.jsonl")
            self._load_locked()
            self._file = open(self._path, "a")
            if self._file_records == 0:
                self._append_locked({"kind": "meta",
                                     "block_tokens": self.block_tokens})

    # -- durability -----------------------------------------------------
    def _append_locked(self, obj: dict):
        if self._file is None:
            return
        self._file.write(json.dumps(obj) + "\n")
        self._file.flush()
        self._file_records += 1

    def _load_locked(self):
        """Replay `store.jsonl`: torn FINAL line tolerated (the crash
        the tier exists to survive); mid-file garbage or an ill-formed
        record is SKIPPED and counted — cache, not truth."""
        if not os.path.exists(self._path):
            return
        lines = open(self._path).read().splitlines()
        # a torn tail is only the LAST non-empty line; anything broken
        # earlier is mid-file damage — also survivable, also counted
        for line in lines:
            line = line.strip()
            if not line:
                continue
            self._file_records += 1
            try:
                obj = json.loads(line)
                kind = obj["kind"]
                if kind == "meta":
                    if int(obj["block_tokens"]) != self.block_tokens:
                        raise ValueError(
                            "store at %r was written with block_tokens"
                            "=%r, this store wants %r — one store, one "
                            "block geometry" % (self._path,
                                                obj["block_tokens"],
                                                self.block_tokens))
                elif kind == "put":
                    rec = _decode(obj)
                    self._admit_locked(rec, persist=False)
                elif kind == "evict":
                    self._drop_locked(int(obj["key"]))
                elif kind == "quarantine":
                    key = int(obj["key"])
                    self._drop_locked(key)
                    self._quarantined.add(key)
                else:
                    self.corrupt_dropped += 1
            except ValueError as exc:
                if "block geometry" in str(exc):
                    raise
                self.corrupt_dropped += 1
            except (KeyError, TypeError):
                self.corrupt_dropped += 1
        self._rev += 1

    def _maybe_compact_locked(self):
        """Rewrite the file to live records only (tmp + fsync +
        os.replace — the journal's atomic-commit discipline) once dead
        lines dominate."""
        if self._file is None:
            return
        live = len(self._records) + len(self._quarantined) + 1
        if self._file_records < max(16, 2 * live):
            return
        tmp = self._path + ".tmp"
        with open(tmp, "w") as f:
            f.write(json.dumps({"kind": "meta",
                                "block_tokens": self.block_tokens})
                    + "\n")
            n = 1
            for rec in self._iter_chains_locked():
                f.write(json.dumps({"kind": "put", **_encode(rec)})
                        + "\n")
                n += 1
            for key in sorted(self._quarantined):
                f.write(json.dumps({"kind": "quarantine",
                                    "key": int(key)}) + "\n")
                n += 1
            f.flush()
            os.fsync(f.fileno())
        self._file.close()
        os.replace(tmp, self._path)
        self._file = open(self._path, "a")
        self._file_records = n
        self.compactions += 1

    # -- internals ------------------------------------------------------
    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _admit_locked(self, rec: dict, persist: bool) -> bool:
        key = rec["key"]
        if key in self._quarantined:
            return False
        if key in self._records:
            self._stamp[key] = self._tick()
            return True
        self._records[key] = rec
        self._children.setdefault(key, 0)
        if rec["parent"]:
            self._children[rec["parent"]] = (
                self._children.get(rec["parent"], 0) + 1)
        self._bytes += rec["nbytes"]
        self._stamp[key] = self._tick()
        self.puts += 1
        self._rev += 1
        if persist:
            self._append_locked({"kind": "put", **_encode(rec)})
        self._evict_to_budget_locked()
        return key in self._records

    def _drop_locked(self, key: int):  # band-verb: retire
        rec = self._records.pop(key, None)
        if rec is None:
            return
        self._bytes -= rec["nbytes"]
        self._stamp.pop(key, None)
        self._children.pop(key, None)
        if rec["parent"] and rec["parent"] in self._children:
            self._children[rec["parent"]] -= 1
        self._rev += 1

    def _evict_to_budget_locked(self):
        if self.byte_budget is None:
            return
        while self._bytes > self.byte_budget and self._records:
            victim = None
            for key in self._records:
                if self._children.get(key, 0) > 0:
                    continue  # not a leaf: evicting would orphan a chain
                if victim is None or self._stamp[key] < self._stamp[victim]:
                    victim = key
            if victim is None:
                return  # cycle-free by construction; defensive only
            self._drop_locked(victim)
            self.evictions += 1
            self._append_locked({"kind": "evict", "key": int(victim)})
        self._maybe_compact_locked()

    def _quarantine_locked(self, key: int):
        if key in self._quarantined:
            return
        self._drop_locked(key)
        self._quarantined.add(key)
        self.quarantines += 1
        self._rev += 1
        self._append_locked({"kind": "quarantine", "key": int(key)})
        self._maybe_compact_locked()

    def _get_locked(self, key: int) -> Optional[dict]:
        if key in self._quarantined:
            self.misses += 1
            return None
        rec = self._records.get(key)
        if rec is None:
            self.misses += 1
            return None
        if (len(rec["payload"]) != rec["nbytes"]
                or payload_crc(rec["payload"]) != rec["crc"]):
            # at-rest corruption: skip, quarantine, never serve
            self._quarantine_locked(key)
            self.misses += 1
            return None
        self._stamp[key] = self._tick()
        self.hits += 1
        return rec

    def _iter_chains_locked(self) -> List[dict]:
        """Live records, every parent before any of its children (the
        order a warm start can replay: ancestors publish first)."""
        out: List[dict] = []
        present = self._records
        # roots: parent absent from the store (0, evicted, or foreign)
        frontier = sorted(k for k, r in present.items()
                          if r["parent"] not in present)
        kids: Dict[int, List[int]] = {}
        for k, r in present.items():
            if r["parent"] in present:
                kids.setdefault(r["parent"], []).append(k)
        while frontier:
            key = frontier.pop(0)
            out.append(present[key])
            frontier.extend(sorted(kids.get(key, ())))
        return out

    # -- public API -----------------------------------------------------
    def put(self, record: dict) -> bool:
        """Admit one closed-block record (idempotent per key; a
        quarantined key is refused — its lineage is suspect). Applies
        any armed `store_corrupt@N`/`store_trunc@N` fault to the
        record AT REST (RAM and file both) so the read path's crc
        check is what catches it. May evict leaf-first to stay under
        the byte budget; returns False when the record was refused or
        immediately evicted."""
        with self._lock:
            rec = dict(record)
            if self._injector is not None:
                fault = self._injector.store_tick()
                if fault == "corrupt" and rec["payload"]:
                    pay = bytearray(rec["payload"])
                    pay[0] ^= 0x5A
                    rec["payload"] = bytes(pay)
                elif fault == "trunc":
                    rec["payload"] = rec["payload"][:-4] \
                        if len(rec["payload"]) > 4 else b""
            if self.byte_budget is not None \
                    and rec["nbytes"] > self.byte_budget:
                return False
            return self._admit_locked(rec, persist=True)

    def get(self, key: int) -> Optional[dict]:
        """Fetch one record, crc-verified: a mismatch (bit-rot, an
        injected store fault) quarantines the key and returns None —
        the caller falls back to re-prefill, counted, never wrong."""
        with self._lock:
            return self._get_locked(int(key))

    def chain_fetch(self, tokens, block_tokens: Optional[int] = None  # band-verb: alias
                    ) -> List[dict]:
        """Records covering the leading whole blocks of `tokens`, in
        chain order, stopping at the first miss/quarantined/corrupt
        entry (an interior hole makes the tail unusable — blocks
        import in order or not at all). Each record's token tuple is
        re-checked against the probe (crc-collision guard: chain keys
        only STEER, bytes decide)."""
        Bt = int(block_tokens or self.block_tokens)
        tokens = [int(t) for t in np.asarray(tokens).reshape(-1)]
        out: List[dict] = []
        acc = 0
        with self._lock:
            for d in range(len(tokens) // Bt):
                block = tuple(tokens[d * Bt:(d + 1) * Bt])
                acc = fold_key(acc, block)
                rec = self._get_locked(acc)
                if rec is None or rec["tokens"] != block:
                    break
                out.append(rec)
        return out

    def quarantine(self, key: int):
        """Mark a key as never-servable (sticky, persisted). Called by
        the store itself on crc mismatch and by importers whose
        ON-DEVICE fingerprint check failed — the record read clean
        from disk but its content lies."""
        with self._lock:
            self._quarantine_locked(int(key))

    def evict(self, key: int) -> bool:
        """Drop one record (drills / explicit cold-path management).
        Unlike budget eviction this accepts interior keys — the chain's
        tail simply becomes unreachable to `chain_fetch`."""
        with self._lock:
            if int(key) not in self._records:
                return False
            self._drop_locked(int(key))
            self.evictions += 1
            self._append_locked({"kind": "evict", "key": int(key)})
            # an unbudgeted-but-durable store still accumulates dead
            # lines through explicit evicts — rotate here too
            self._maybe_compact_locked()
            return True

    def summary(self) -> frozenset:
        """Chain keys of every servable record — the router's
        store-awareness: what ANY replica can cheaply restore.
        Revision-cached; same key definition as
        `PrefixCache.summary()`."""
        with self._lock:
            if self._summary_rev != self._rev:
                self._summary = frozenset(self._records)
                self._summary_rev = self._rev
            return self._summary

    def iter_chains(self) -> List[dict]:
        """Snapshot of live records, parents before children — the
        warm-start replay order."""
        with self._lock:
            return list(self._iter_chains_locked())

    def close(self):
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def stats(self) -> dict:
        with self._lock:
            return {
                "records": len(self._records),
                "bytes": self._bytes,
                "byte_budget": self.byte_budget,
                "block_tokens": self.block_tokens,
                "puts": self.puts,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "quarantined": len(self._quarantined),
                "quarantines": self.quarantines,
                "corrupt_dropped": self.corrupt_dropped,
                "compactions": self.compactions,
                "durable": self._path is not None,
            }
