"""The serving front door (ISSUE 18 tentpole): a threaded TCP server
speaking `wire.py`'s newline-delimited JSON protocol over a
`ServingFleet` — the reference's pserver RPC / go-master service
surface recast for inference serving.

Design rules, in fleet order of precedence:

- **Auth -> tenant**: a connection's `hello` token maps to a tenant
  name (the `auth` table); every `generate` on that connection is
  admitted through PR 12's quota/WFQ machinery as that tenant. A
  multi-tenant fleet refuses unauthenticated generates
  (UNAUTHORIZED) — quotas with an anonymous bypass are decoration.
- **Typed rejections only**: every fleet verdict crosses the wire as
  a stable `ERROR_CODES` code via `wire.error_code_for`; a stack
  trace never does.
- **Streaming rides the journal**: a `"stream": true` generate pumps
  `FleetHandle.stream_chunks()` — chunks are the journal's
  batched-flush progress records, so what the client sees is exactly
  what failover would resume from, and the concatenation is
  bit-identical to `done.tokens` across kill drills.
- **Disconnect == cancel**: a dropped connection cancels every
  request it owns (`ServingFleet.cancel`), journaling a `cancelled`
  terminal and freeing the abandoned stream's KV blocks at the
  holder's next handshake.
- **Drain, never drop**: `drain()` stops accepting, refuses new
  generates with SERVER_DRAINING, and waits for in-flight requests
  to reach their fleet verdicts — the wire-side half of the fleet's
  own drain discipline.

Threads: one acceptor, one reader per connection, one pump per
in-flight request. Pumps and the reader share the connection's write
lock; connection state is guarded by the connection's own lock —
never the fleet's."""

import socket
import threading
import time
from typing import Dict, Optional

from . import wire
from .fleet import ServingFleet, _SLO_UNSET

# generate-frame keys forwarded to ServingFleet.submit verbatim (when
# present) — anything else in the frame is refused as BAD_REQUEST, so
# a typo'd knob fails loudly instead of silently serving defaults
_GENERATE_KEYS = ("op", "id", "prompt", "max_new_tokens",
                  "temperature", "eos_id", "seed", "publish_len",
                  "deadline_s", "stream", "slo", "adapter")


class _Conn(object):
    """One accepted connection: socket + its reader thread's state.
    `handles` maps the client's request id -> live FleetHandle;
    mutations happen under `lock` (a leaf lock — never held while
    calling into the fleet)."""

    def __init__(self, cid: str, sock: socket.socket):
        self.id = cid
        self.sock = sock
        self.rfile = sock.makefile("rb")
        self.wlock = threading.Lock()   # serializes frame writes
        self.lock = threading.Lock()
        self.handles: Dict[str, object] = {}  # guarded-by: lock
        self.tenant: Optional[str] = None     # guarded-by: lock
        self.closed = False                   # guarded-by: lock

    def send(self, frame: dict) -> bool:
        """Best-effort frame write: a dead client is handled by the
        reader's EOF (which cancels its requests) — pumps must not
        crash on it."""
        try:
            wire.send_frame(self.sock, frame, lock=self.wlock)
            return True
        except (OSError, ValueError, wire.WireError):
            return False

    def close_socket(self):
        with self.lock:
            if self.closed:
                return
            self.closed = True
        # shutdown FIRST: the reader thread parked in readline() holds
        # the BufferedReader lock rfile.close() needs — shutdown EOFs
        # the read and releases it (the close-vs-read deadlock when
        # close()/_abandon runs from another thread)
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        for closer in (self.sock.close, self.rfile.close):
            try:
                closer()
            except (OSError, ValueError):
                pass


class FrontDoor(object):
    """TCP front door over one `ServingFleet`. `auth` maps auth token
    -> tenant name (required for a multi-tenant fleet; optional
    labelling for a single-tenant one). `port=0` picks a free port —
    read `.address` after `start()`."""

    def __init__(self, fleet: ServingFleet, host: str = "127.0.0.1",
                 port: int = 0, auth: Optional[Dict[str, str]] = None,
                 backlog: int = 64,
                 request_wait_s: Optional[float] = None):
        self.fleet = fleet
        self.host = host
        self.port = port
        self.auth = dict(auth) if auth else None
        self.backlog = backlog
        # server-side patience per request (None = wait for the fleet
        # verdict): bounds how long a pump blocks on a stream chunk /
        # result before answering a typed TIMEOUT
        self.request_wait_s = request_wait_s
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._conns: Dict[str, _Conn] = {}    # guarded-by: _lock
        self._next_conn = 0                   # guarded-by: _lock
        self._draining = False                # guarded-by: _lock
        self._closed = False                  # guarded-by: _lock
        # wire-level O(1) counters (the fleet's stats discipline)
        self.conns_accepted = 0               # guarded-by: _lock
        self.frames_bad = 0                   # guarded-by: _lock
        self.requests_in = 0                  # guarded-by: _lock
        self.streams_in = 0                   # guarded-by: _lock
        self.cancels_in = 0                   # guarded-by: _lock
        self.disconnect_cancels = 0           # guarded-by: _lock
        self.drain_refused = 0                # guarded-by: _lock
        self.errors_out: Dict[str, int] = {}  # guarded-by: _lock

    # -- lifecycle ----------------------------------------------------

    def start(self) -> "FrontDoor":
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((self.host, self.port))
        ls.listen(self.backlog)
        self._listener = ls
        self.port = ls.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="frontdoor-accept",
            daemon=True)
        self._accept_thread.start()
        return self

    @property
    def address(self):
        return (self.host, self.port)

    def drain(self, timeout: float = 30.0) -> bool:
        """Stop accepting, refuse new generates (SERVER_DRAINING),
        wait for every in-flight request's fleet verdict. Live
        connections stay open until their requests finish — the
        wire-side half of the fleet drain discipline. Returns False
        if requests were still open at the deadline."""
        with self._lock:
            self._draining = True
            ls, self._listener = self._listener, None
        if ls is not None:
            # shutdown FIRST: close() alone does not wake a thread
            # blocked in accept() on Linux — shutdown makes the
            # pending accept return immediately
            try:
                ls.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                ls.close()
            except OSError:
                pass
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                busy = any(c.handles for c in self._conns.values())
            if not busy:
                return True
            time.sleep(0.005)
        with self._lock:
            return not any(c.handles for c in self._conns.values())

    def close(self, timeout: float = 10.0):
        """Drain, then drop every connection. Never closes the fleet —
        the caller owns it (it may outlive this front door)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self.drain(timeout=timeout)
        with self._lock:
            conns = list(self._conns.values())
        for c in conns:
            self._abandon(c)
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=timeout)

    def stats(self) -> dict:
        with self._lock:
            return {
                "conns_accepted": self.conns_accepted,
                "conns_open": len(self._conns),
                "requests_in": self.requests_in,
                "streams_in": self.streams_in,
                "cancels_in": self.cancels_in,
                "disconnect_cancels": self.disconnect_cancels,
                "drain_refused": self.drain_refused,
                "frames_bad": self.frames_bad,
                "errors_out": dict(self.errors_out),
                "draining": self._draining,
            }

    def _count_error(self, code: str):
        with self._lock:
            self.errors_out[code] = self.errors_out.get(code, 0) + 1

    # -- accept / read ------------------------------------------------

    def _accept_loop(self):  # thread: frontdoor-accept
        while True:
            with self._lock:
                ls = self._listener
            if ls is None:
                return
            try:
                sock, _addr = ls.accept()
            except OSError:
                return  # listener closed: drain/close
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                cid = "c%d" % self._next_conn
                self._next_conn += 1
                conn = _Conn(cid, sock)
                self._conns[cid] = conn
                self.conns_accepted += 1
            threading.Thread(
                target=self._reader_loop, args=(conn,),
                name="frontdoor-%s" % cid, daemon=True).start()

    def _reader_loop(self, conn: _Conn):  # thread: conn reader
        try:
            while True:
                try:
                    frame = wire.read_frame(conn.rfile)
                except wire.WireError as exc:
                    # a corrupt NDJSON stream cannot be resynchronized:
                    # answer typed, then treat it as a disconnect
                    with self._lock:
                        self.frames_bad += 1
                    conn.send(wire.error_frame(exc))
                    self._count_error(exc.code)
                    return
                if frame is None:
                    return  # clean EOF
                if not frame:
                    continue  # blank line keep-alive
                self._dispatch(conn, frame)
        except OSError:
            return  # socket died mid-read: same as EOF
        except ValueError:
            # close_socket() from another thread won the race and
            # closed rfile under our readline: same as EOF
            return
        finally:
            self._abandon(conn)

    def _dispatch(self, conn: _Conn, frame: dict):  # thread: conn reader
        op = frame.get("op")
        if op == "ping":
            conn.send({"op": "pong"})
        elif op == "hello":
            self._handle_hello(conn, frame)
        elif op == "generate":
            self._handle_generate(conn, frame)
        elif op == "cancel":
            self._handle_cancel(conn, frame)
        else:
            exc = ValueError("unknown op %r" % (op,))
            conn.send(wire.error_frame(exc, frame.get("id")))
            self._count_error("BAD_REQUEST")

    # -- ops ----------------------------------------------------------

    def _handle_hello(self, conn: _Conn, frame: dict):
        token = frame.get("token")
        tenant = None
        if self.auth is not None:
            if token not in self.auth:
                conn.send({"op": "error", "id": None,
                           "code": "UNAUTHORIZED",
                           "message": "unknown auth token"})
                self._count_error("UNAUTHORIZED")
                return
            tenant = self.auth[token]
        with conn.lock:
            conn.tenant = tenant
        conn.send({"op": "welcome", "proto": wire.PROTO_VERSION,
                   "tenant": tenant})

    def _handle_generate(self, conn: _Conn, frame: dict):
        req_id = frame.get("id")
        if not isinstance(req_id, str) or not req_id:
            conn.send(wire.error_frame(
                ValueError("generate needs a string id"), req_id))
            self._count_error("BAD_REQUEST")
            return
        with self._lock:
            draining = self._draining
            if draining:
                self.drain_refused += 1
        if draining:
            # refuse OUTSIDE _lock: it is non-reentrant and
            # _count_error needs it (and a socket write never belongs
            # under the server-wide lock anyway)
            conn.send({"op": "error", "id": req_id,
                       "code": "SERVER_DRAINING",
                       "message": "front door is draining"})
            self._count_error("SERVER_DRAINING")
            return
        with conn.lock:
            tenant = conn.tenant
            duplicate = req_id in conn.handles
        if duplicate:
            conn.send(wire.error_frame(
                ValueError("request id %r already in flight on this "
                           "connection" % req_id), req_id))
            self._count_error("BAD_REQUEST")
            return
        # a multi-tenant fleet admits nothing without a quota bucket
        # to charge: unauthenticated generates are refused before the
        # fleet ever sees them
        if self.fleet._tenants is not None and tenant is None:
            conn.send({"op": "error", "id": req_id,
                       "code": "UNAUTHORIZED",
                       "message": "multi-tenant fleet: hello with an "
                                  "auth token first"})
            self._count_error("UNAUTHORIZED")
            return
        unknown = [k for k in frame if k not in _GENERATE_KEYS]
        if unknown:
            conn.send(wire.error_frame(
                ValueError("unknown generate key(s) %r" % unknown),
                req_id))
            self._count_error("BAD_REQUEST")
            return
        streamed = bool(frame.get("stream", False))
        kw = {}
        for k in ("temperature", "eos_id", "seed", "publish_len",
                  "deadline_s", "adapter"):
            if frame.get(k) is not None:
                kw[k] = frame[k]
        if "slo" in frame:
            kw["slo"] = frame["slo"]  # explicit null = wildcard
        else:
            kw["slo"] = _SLO_UNSET    # absent = tenant/fleet default
        try:
            h = self.fleet.submit(
                frame.get("prompt", []),
                frame.get("max_new_tokens", 0),
                tenant=tenant if self.fleet._tenants is not None
                else None,
                stream=streamed, conn=conn.id, **kw)
        except Exception as exc:  # typed verdicts, never tracebacks
            ef = wire.error_frame(exc, req_id)
            conn.send(ef)
            self._count_error(ef["code"])
            return
        with conn.lock:
            if conn.closed:
                # the client vanished between read and submit: claw
                # the request back immediately, exactly like a
                # mid-stream disconnect would
                self.fleet.cancel(h.rid)
                return
            conn.handles[req_id] = h
        with self._lock:
            self.requests_in += 1
            if streamed:
                self.streams_in += 1
        conn.send({"op": "accepted", "id": req_id, "rid": h.rid})
        threading.Thread(
            target=self._pump, args=(conn, req_id, h, streamed),
            name="frontdoor-%s-%s" % (conn.id, req_id),
            daemon=True).start()

    def _handle_cancel(self, conn: _Conn, frame: dict):
        req_id = frame.get("id")
        with conn.lock:
            h = conn.handles.get(req_id)
        with self._lock:
            self.cancels_in += 1
        if h is not None:
            # the pump answers with the typed CANCELLED error once the
            # fleet verdict lands (or with done, if completion won the
            # race — the client must handle both orders)
            self.fleet.cancel(h.rid)

    def _pump(self, conn: _Conn, req_id: str, h, streamed: bool):
        # thread: request pump — owns every response frame for req_id
        # after `accepted`; exits by sending exactly one done/error
        try:
            if streamed:
                index = 0
                for chunk in h.stream_chunks(
                        timeout=self.request_wait_s):
                    conn.send({"op": "tokens", "id": req_id,
                               "index": index,
                               "tokens": [int(t) for t in chunk]})
                    index += len(chunk)
                # the generator closed without raising: h is done
                conn.send({"op": "done", "id": req_id,
                           "tokens": [int(t) for t in h.tokens],
                           "n": len(h.tokens), "replica": h.replica})
            else:
                h.result(timeout=self.request_wait_s)
                conn.send({"op": "done", "id": req_id,
                           "tokens": [int(t) for t in h.tokens],
                           "n": len(h.tokens), "replica": h.replica})
        except Exception as exc:
            ef = wire.error_frame(exc, req_id)
            conn.send(ef)
            self._count_error(ef["code"])
        finally:
            with conn.lock:
                conn.handles.pop(req_id, None)

    # -- disconnect ---------------------------------------------------

    def _abandon(self, conn: _Conn):
        """Client gone (EOF, reset, or close()): cancel every request
        this connection owns — the fleet journals `cancelled`
        terminals and frees the abandoned streams' KV blocks — then
        drop the connection."""
        with conn.lock:
            handles = list(conn.handles.values())
            conn.handles.clear()
        for h in handles:
            if self.fleet.cancel(h.rid):
                with self._lock:
                    self.disconnect_cancels += 1
        conn.close_socket()
        with self._lock:
            self._conns.pop(conn.id, None)
