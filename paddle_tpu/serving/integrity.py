"""Serving integrity sentinel (ISSUE 15): silent-corruption detection
for the inference path.

The fleet survives replicas that are DEAD (PR 6 heartbeats/failover)
and replicas that are SLOW (PR 8 gray-failure demotion). This module
closes the third gap — replicas that are alive, fast, and **wrong**:
non-finite logits, a bit-flipped KV block, a corrupted weight tile.
PR 10 solved the same problem shape for training (check_nan_inf
upgraded from raise-and-die to detect-and-recover, rollback to
known-good, exactly-once quarantine); this is the serving counterpart,
built from four mechanisms:

  in-step numeric TRAPS   the one compiled decode/verify/chunk step
                          additionally returns a per-slot non-finite
                          flag (`transformer.logits_trap`: logits +
                          softmax-denominator reduction — a few ops
                          folded into the existing step, NO new
                          traces) and a max-|logit| scalar the shared
                          `utils.detector.TripDetector` watches for
                          magnitude spikes (wrong-but-finite compute).
                          A tripped slot becomes an integrity event
                          INSTEAD of an emitted token.
  KV block FINGERPRINTS   a cheap folded-f32 checksum per physical
                          block (`transformer.paged_block_fingerprint`,
                          riding block-id addressing like PR 14's
                          quant scales), committed when a block closes
                          (publish into the prefix trie), spot-verified
                          when an aliased block is re-opened by a
                          different request — which is also exactly
                          where a failover RESUME re-attaches to the
                          pool — so a flipped block cannot silently
                          serve prefix-cache hits.
  known-answer CANARIES   the fleet extends PR 8's probe machinery from
                          demoted-only to periodic canary requests on
                          LIVE replicas, checked against a golden token
                          trace computed once per `weights_version`
                          (fleet.py `canary_interval_s`).
  QUARANTINE + TAINT      a tripped replica is killed under a fresh
                          incarnation (PR 11 supervisor backoff), and
                          its journaled progress since its last clean
                          canary is marked TAINTED (`RequestJournal.
                          integrity`): resubmission resumes from the
                          last verified token index and the taint
                          window is re-decoded on a healthy survivor —
                          the ONE sanctioned exception to PR 8's
                          zero-re-decode rule, journal-audited (J010)
                          so ONLY tainted tokens ever re-decode.

Threading: `BlockFingerprints` is engine state, confined to the
engine's scheduler thread like every other side-band; `ServingSentinel`
likewise. The fleet-side canary/taint state lives in fleet.py under
`_cond`.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..utils.detector import TripDetector

__all__ = ["IntegrityError", "BlockFingerprints", "ServingSentinel",
           "golden_trace", "fp_digest", "CANARY_PROMPT"]

# the fleet's default known-answer canary prompt: tiny, fixed, and in
# every model's vocab range (ids 1..3) — the GOLDEN trace is what makes
# it a known answer, the prompt only has to be deterministic
CANARY_PROMPT = (1, 2, 3)

# relative tolerance for fingerprint comparison: the recompute runs the
# SAME jitted reduction on the same backend, so a clean block matches
# essentially bitwise — the slack only forgives float noise far below
# any real corruption's displacement
_FP_RTOL = 1e-5


class IntegrityError(RuntimeError):
    """A serving replica produced evidence of silent corruption. Raised
    by the engine (numeric trap, fingerprint mismatch, magnitude spike)
    — crashing the replica thread into the fleet's quarantine path —
    and synthesized by the fleet on a canary mismatch. `kind` is one of
    "trap" | "fingerprint" | "spike" | "canary"; `replica` names the
    tripped replica when known."""

    def __init__(self, msg: str, kind: str = "trap", replica=None):
        super().__init__(msg)
        self.kind = kind
        self.replica = replica


class BlockFingerprints(object):
    """Host bookkeeping for per-physical-block checksums (engine state,
    thread-confined like the allocator). A fingerprint is COMMITTED
    when a block closes (published into the prefix trie — full, never
    written again: later writes land in later blocks, and a write into
    a shared block goes through COW to a private copy), VERIFIED when
    an aliased block is re-opened, and DROPPED when the block returns
    to the free list (a recycled id must never be judged against its
    previous tenant's checksum)."""

    def __init__(self):
        self._fp: Dict[int, float] = {}  # guarded-by: scheduler
        # O(1) counters (ServingMetrics discipline)
        self.committed = 0               # guarded-by: scheduler
        self.verified = 0                # guarded-by: scheduler
        self.mismatches = 0              # guarded-by: scheduler

    def commit(self, bid: int, fp: float):
        if bid not in self._fp:
            self.committed += 1
        self._fp[int(bid)] = float(fp)

    def expected(self, bid: int) -> Optional[float]:
        return self._fp.get(int(bid))

    def drop(self, bid: int):
        self._fp.pop(int(bid), None)

    def check(self, bid: int, got: float) -> bool:
        """Compare a recomputed fingerprint against the committed one;
        True = clean (or never committed — nothing to judge)."""
        exp = self._fp.get(int(bid))
        if exp is None:
            return True
        self.verified += 1
        ok = abs(float(got) - exp) <= _FP_RTOL * max(1.0, abs(exp))
        if not ok:
            self.mismatches += 1
        return ok

    def stats(self) -> dict:
        return {"blocks_fingerprinted": len(self._fp),
                "committed": self.committed,
                "verified": self.verified,
                "mismatches": self.mismatches}


class ServingSentinel(object):
    """Per-engine numeric sentinel: folds the compiled step's trap flag
    and max-|logit| scalar into verdicts, using the SAME
    TripDetector core as the training DivergenceDetector (ISSUE 15
    satellite — one hysteresis implementation, two health loops).

    observe(trap_any, scale) -> "ok" | "trap" | "spike"

    The trap flag is a hard verdict (non-finite logits are already in
    an emitted token's future); the scale feeds the EWMA spike
    detector when `spike_factor` is set (None = traps only — magnitude
    varies honestly across workloads, so the soft detector is opt-in,
    sized per deployment like the training sentinel's)."""

    def __init__(self, spike_factor: Optional[float] = None,
                 hysteresis: int = 2, warmup: int = 8):
        self.detector = (
            TripDetector(spike_factor=float(spike_factor),
                         hysteresis=hysteresis, warmup=warmup)
            if spike_factor is not None else None)  # guarded-by: scheduler
        self.trips = 0  # guarded-by: scheduler

    def observe(self, trap_any: bool, scale: float) -> str:
        if trap_any:
            self.trips += 1
            return "trap"
        if self.detector is not None and scale > 0.0:
            verdict = self.detector.observe(scale)
            if verdict != "ok":
                # "nonfinite" on the scale means the trap already fired
                # upstream in practice; fold both into the spike verdict
                self.trips += 1
                return "spike"
        return "ok"


def fp_digest(fps) -> str:
    """Fold a sequence of block fingerprints into one short hex digest
    (crc32 over each float's little-endian f64 bytes, chained). The
    ISSUE 16 handoff side-band: an assign record that ships imported
    blocks carries this digest so the journal audit can tie the done
    back to ONE specific verified transfer — cheap enough to compute
    inline, stable across platforms (explicit endianness)."""
    import struct
    import zlib

    acc = 0
    for fp in fps:
        acc = zlib.crc32(struct.pack("<d", float(fp)), acc)
    return "%08x" % (acc & 0xFFFFFFFF)


def golden_trace(params, cfg, prompt=CANARY_PROMPT, max_new_tokens=4):
    """The known-answer canary's golden GENERATED tokens for one weight
    set: greedy `transformer.generate` on the canary prompt, computed
    once per `weights_version` (fleet construction and every
    `roll_weights` commit). Greedy engine output is token-identical to
    `generate()` — the serving suite's tested bar — so a live replica
    whose canary disagrees is producing corrupt tokens, not noise.
    Returns a plain list of ints (the generated suffix only; the
    prompt is not part of the answer)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..models import transformer as tlm

    # device arrays: a rollout hands in params freshly LOADED from a
    # checkpoint (numpy leaves), and generate()'s scan body indexes
    # them with tracers — numpy leaves would TracerArrayConversionError
    params = jax.tree_util.tree_map(jnp.asarray, params)
    p = np.asarray(prompt, np.int32)[None, :]
    out = np.asarray(tlm.generate(params, p, cfg, int(max_new_tokens)))
    return [int(t) for t in out[0, p.shape[1]:]]
