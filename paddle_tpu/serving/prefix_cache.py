"""Prefix KV pool: a trie of cached prompt-prefix KV blocks shared
across requests (RadixAttention, Zheng et al. 2024, recast for the
slotted static-shape cache).

Heavy serving traffic repeats prompt headers — the same system prompt /
few-shot block leads dozens of concurrent requests — and the baseline
engine recomputed that header's K/V for every admission. The pool turns
the repeat into a device-side copy: completed prefixes are published
back as fixed-size token BLOCKS (one trie node per block, children
keyed by the child block's token tuple), and admission walks the trie
for the longest cached block-chain, `dynamic_update_slice`-copying each
block's K/V into the new slot instead of recomputing it. Fixed block
granularity is the static-shape analogue of the radix tree's
path-compressed edges: no node splitting, ONE compiled copy/extract
shape total (vs per-length shapes), and eviction is block-sized — the
same reasons vLLM's prefix cache hashes fixed blocks.

Pool discipline (the reference's pooled-allocator design,
PoolAllocator/MemoryHandle — PARITY.md PR 4):
  * token budget — the pool holds at most `token_budget` cached tokens;
    publishing past the budget evicts least-recently-used LEAF blocks
    (a leaf has no children, so evicting it never orphans a longer
    cached chain that extends through it).
  * ref-counted entries — `match()` acquires every matched node; an
    acquired node is skipped by eviction until `release()`, so a block
    serving a live device-copy can never be freed mid-admit. A matched
    chain is root-connected, so acquiring the chain pins every
    ancestor of every acquired node.
  * counters — hits/misses/evictions/tokens-saved, O(1) ints (the same
    no-unbounded-lists rule ServingMetrics follows).

Payloads are OPAQUE to the pool (the paged engine stores PHYSICAL
block ids); the trie, budget, LRU, and ref-count logic are pure host
bookkeeping and unit-testable without a device. Opacity is what makes
quantized pools (ISSUE 14) free here: a published block id names the
payload AND its per-(block, head) scale side-band — both live in the
cache pytree keyed by that id — so an aliasing hit shares the scale
with the payload and the trie never learns storage dtypes exist
(within one engine the pool has exactly one storage dtype; across a
fleet, uniformity is enforced at replica spawn).
"""

from __future__ import annotations

import heapq
import zlib
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

import numpy as np

__all__ = ["PrefixCache", "PrefixMatch", "chain_keys", "fold_key"]


def _fold(acc: int, block) -> int:
    """One chain-key step: crc32 of a block's tokens folded over the
    parent key. The SINGLE definition both sides of affinity routing
    use — `chain_keys` (prompt side) and `PrefixCache.summary` (trie
    side) must produce identical keys or every lookup silently
    misses."""
    return zlib.crc32(np.asarray(block, np.int64).tobytes(), acc)


# public alias: the durable KV store (kv_store.py) keys its records
# with the SAME fold as the trie summary and the router — three users,
# one definition, or lookups silently miss across the tier boundary
fold_key = _fold


def chain_keys(tokens, block_tokens: int) -> List[int]:
    """Chained-crc32 key per whole leading block of `tokens`: key[d]
    identifies the token prefix tokens[:(d+1)*block_tokens] (the crc of
    block d folded over key[d-1]). Two prefixes share key[d] iff they
    share the first d+1 blocks (modulo crc collision — harmless where
    this is used: fleet AFFINITY routing, which only steers load; the
    pool's trie match stays exact). Module-level so the fleet router can
    key a prompt without holding any pool."""
    tokens = np.asarray(tokens).reshape(-1)
    out: List[int] = []
    acc = 0
    for d in range(len(tokens) // int(block_tokens)):
        acc = _fold(acc, tokens[d * block_tokens:(d + 1) * block_tokens])
        out.append(acc)
    return out


class _Node(object):
    __slots__ = ("block", "payload", "children", "parent", "refs", "stamp")

    def __init__(self, block: Tuple[int, ...], payload: Any,
                 parent: Optional["_Node"]):
        self.block = block
        self.payload = payload
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.parent = parent
        self.refs = 0
        self.stamp = 0


class PrefixMatch(object):
    """Result of `PrefixCache.match()`: the longest cached block-chain
    for the probed tokens, ACQUIRED (ref-counted) until `release()`.
    `payloads` lists each matched block's payload in chain order;
    `length` is the matched token count (blocks * block_tokens)."""

    def __init__(self, cache: "PrefixCache", nodes: List[_Node]):
        self._cache = cache
        self._nodes = nodes
        self.length = len(nodes) * cache.block_tokens
        self._released = False

    @property
    def payloads(self) -> List[Any]:
        return [n.payload for n in self._nodes]

    def release(self):
        if self._released:
            return
        self._released = True
        for n in self._nodes:
            n.refs -= 1

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


class PrefixCache(object):
    """Trie-keyed pool of cached KV prefix blocks with LRU eviction
    under a token budget. Single-threaded (the serving engine's
    scheduler loop); all bookkeeping is O(blocks)."""

    def __init__(self, token_budget: int, block_tokens: int = 16,
                 on_evict: Optional[Callable[[Any], None]] = None):
        if int(block_tokens) < 1:
            raise ValueError("block_tokens must be >= 1")
        if int(token_budget) < 1:
            raise ValueError("token_budget must be >= 1")
        self.token_budget = int(token_budget)
        self.block_tokens = int(block_tokens)
        # called with each evicted node's payload BEFORE it is dropped.
        # The paged engine publishes physical block IDS as payloads and
        # uses this hook to decref them in the KV pool — eviction is how
        # a trie-held block's HBM returns to the allocator (ISSUE 7).
        self._on_evict = on_evict
        self._root = _Node((), None, None)
        self._nodes: Dict[_Node, None] = {}  # every non-root node
        self._clock = 0
        # O(1) counters (no per-request history)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.tokens_saved = 0
        self.inserted_blocks = 0
        self.size_tokens = 0

    # -- internals ------------------------------------------------------
    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _block_of(self, tokens, d: int) -> Tuple[int, ...]:
        B = self.block_tokens
        return tuple(int(t) for t in tokens[d * B:(d + 1) * B])

    # -- lookup ---------------------------------------------------------
    def match(self, tokens, record=True) -> PrefixMatch:  # band-verb: alias
        """Longest cached block-chain prefix of `tokens` (block
        granularity: a partial trailing block never matches). Acquires
        every matched node — call `release()` (or use as a context
        manager) once the copies are dispatched. With `record` (the
        default) counts one hit (length > 0) or miss per call and
        LRU-stamps the chain; `record=False` is a pure PROBE — the
        engine's admission may retry a block-starved request every
        scheduler step, and retries must not inflate hit/miss stats or
        perturb eviction order (call `record_hit`/`record_miss` once
        the admission actually resolves)."""
        tokens = np.asarray(tokens).reshape(-1)
        node, nodes = self._root, []
        for d in range(len(tokens) // self.block_tokens):
            child = node.children.get(self._block_of(tokens, d))
            if child is None:
                break
            nodes.append(child)
            node = child
        for n in nodes:
            n.refs += 1
        m = PrefixMatch(self, nodes)
        if record:
            if nodes:
                self.record_hit(m)
            else:
                self.record_miss()
        return m

    def record_hit(self, m: PrefixMatch):
        """Commit a probed match as an actual use: LRU-stamp the chain
        and count the hit + saved tokens ONCE (per admission, not per
        retry)."""
        stamp = self._tick()
        for n in m._nodes:
            n.stamp = stamp
        self.hits += 1
        self.tokens_saved += m.length

    def record_miss(self):
        self.misses += 1

    def idle_payloads(self) -> List[Any]:
        """Payloads of every node no in-flight match holds — the
        engine's reclaim-gain probe: before evicting shareable chains
        toward an admission, it checks these payloads' pool refcounts
        to see whether eviction can free enough blocks AT ALL."""
        return [n.payload for n in self._nodes if n.refs == 0]

    # -- publication ----------------------------------------------------
    def publish(self, tokens, n_blocks: int,
                make_payload: Callable[[int], Any]) -> int:
        """Insert the first `n_blocks` blocks of `tokens` into the trie.
        `make_payload(d)` is called ONLY for blocks not already cached
        (the extract cost is paid once per novel block, not per
        request). Returns the number of new blocks; may evict LRU
        leaves to stay under the token budget."""
        tokens = np.asarray(tokens).reshape(-1)
        if n_blocks * self.block_tokens > len(tokens):
            raise ValueError("publish needs n_blocks*block_tokens <= len")
        stamp = self._tick()
        node, new = self._root, 0
        for d in range(int(n_blocks)):
            blk = self._block_of(tokens, d)
            child = node.children.get(blk)
            if child is None:
                child = _Node(blk, make_payload(d), node)
                node.children[blk] = child
                self._nodes[child] = None
                self.size_tokens += self.block_tokens
                self.inserted_blocks += 1
                new += 1
            child.stamp = stamp
            node = child
        self._evict_to_budget()
        return new

    def _evict_to_budget(self):
        if self.size_tokens <= self.token_budget:
            return
        self._evict_lru(lambda n: self.size_tokens > self.token_budget)

    def reclaim(self, n_blocks: int) -> int:
        """Evict up to `n_blocks` LRU unreferenced leaf blocks
        REGARDLESS of the token budget, returning the count actually
        evicted. The paged engine calls this when an admission needs
        pool blocks the trie is idly holding: shareability is worth
        less than admitting the next request (vLLM's cached-block
        reclaim policy). Acquired chains stay pinned as ever."""
        if n_blocks <= 0:
            return 0
        return self._evict_lru(lambda n: n < n_blocks)

    def _evict_lru(self, more) -> int:  # band-verb: retire
        # one pass builds the LRU heap of currently-evictable leaves;
        # the cascade then costs O(log n) per eviction (evicting a leaf
        # may expose its parent as the next candidate) — admissions
        # wait on this loop, so no full rescan per victim. `more`
        # receives the running eviction count and says whether to keep
        # going (budget pressure or an explicit reclaim quota).
        heap = [
            (n.stamp, i, n) for i, n in enumerate(self._nodes)
            if not n.children and n.refs == 0
        ]
        heapq.heapify(heap)
        tick = len(heap)
        evicted = 0
        while more(evicted) and heap:
            stamp, _, victim = heapq.heappop(heap)
            if victim not in self._nodes or victim.children \
                    or victim.refs > 0 or victim.stamp != stamp:
                continue  # stale heap entry
            parent = victim.parent
            del parent.children[victim.block]
            del self._nodes[victim]
            if self._on_evict is not None:
                self._on_evict(victim.payload)
            victim.payload = None
            self.size_tokens -= self.block_tokens
            self.evictions += 1
            evicted += 1
            if parent is not self._root and not parent.children \
                    and parent.refs == 0:
                tick += 1
                heapq.heappush(heap, (parent.stamp, tick, parent))
        # heap drained with pinned entries left: honestly over budget
        return evicted

    # -- reporting ------------------------------------------------------
    def summary(self) -> Set[int]:
        """Host-only routing digest: the chain key (see `chain_keys`) of
        every cached block-chain prefix. A fleet front door matches a
        prompt's chain keys against each replica's summary to find the
        replica whose pool holds the longest prefix — without touching
        the trie from another thread (the summary is rebuilt by the
        replica's own thread and handed over as an immutable set).
        O(blocks) walk; the pool is budget-bounded so this stays small."""
        out: Set[int] = set()
        stack: List[Tuple[_Node, int]] = [(self._root, 0)]
        while stack:
            node, acc = stack.pop()
            for child in node.children.values():
                key = _fold(acc, child.block)
                out.add(key)
                stack.append((child, key))
        return out

    def __len__(self) -> int:
        return len(self._nodes)

    def stats(self) -> dict:
        total = self.hits + self.misses
        return {
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": round(self.hits / total, 4) if total else None,
            "evictions": self.evictions,
            "tokens_saved": self.tokens_saved,
            "inserted_blocks": self.inserted_blocks,
            "size_tokens": self.size_tokens,
            "token_budget": self.token_budget,
            "block_tokens": self.block_tokens,
            "blocks": len(self._nodes),
        }
