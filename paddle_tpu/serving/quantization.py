"""Weight quantization for the serving engine (ISSUE 14, tentpole
half 2): per-tensor int8 weights with f32 absmax scales, dequantized
INSIDE the one compiled decode/verify/chunk step.

The offline cost model calls decode HBM-bound: a decode step reads
every weight byte once per token batch, so per-tensor int8 weights cut
that stream ~4x independently of the KV side (LLM.int8-style absmax
scaling, Dettmers et al. 2022 — the whole-tensor variant, no outlier
split: the quality gate in `bench.py serving_quant` is the arbiter of
whether that simplification holds on a given model). The engine
quantizes its params ONCE at construction; each compiled step's first
op is the dequant `tree_map`, so XLA folds the upcast into the step
(fusing it into the consuming matmuls where profitable) and the
HBM-resident copy of every quantized tensor stays int8 for the
engine's lifetime. Nothing outside the engine changes: the fleet
hands replicas f32 params (checkpoint CRC walks, live rollout, and
the version fence all see full-precision trees), and each replica
quantizes privately.

`QuantTensor` is a registered pytree node, so a quantized params tree
flows through `jax.jit` like any other: the int8 codes and the scalar
scale are its leaves, and `dequantize_params` (called inside the
traced step) rebuilds a plain tree in the original dtype. 1D tensors
(layer norms, biases) and integer leaves stay unquantized — they are
noise in the byte stream and load-bearing in the numerics.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["QuantTensor", "quantize_params", "dequantize_params",
           "params_bytes"]

_INT8_MAX = 127.0


@jax.tree_util.register_pytree_node_class
class QuantTensor(object):
    """One per-tensor-quantized weight: int8 codes + f32 absmax scale
    (dequant = codes * scale, cast back to the original dtype). A
    pytree node, so jit flattens it to its two array leaves."""

    def __init__(self, codes, scale, out_dtype):
        self.codes = codes
        self.scale = scale
        self.out_dtype = jnp.dtype(out_dtype)

    def dequantize(self):
        return (self.codes.astype(jnp.float32)
                * self.scale).astype(self.out_dtype)

    @property
    def shape(self):
        return self.codes.shape

    @property
    def nbytes(self):
        return int(np.prod(self.codes.shape)) + 4  # int8 codes + scale

    def tree_flatten(self):
        return (self.codes, self.scale), self.out_dtype

    @classmethod
    def tree_unflatten(cls, out_dtype, children):
        return cls(children[0], children[1], out_dtype)


def _is_qt(x):
    return isinstance(x, QuantTensor)


def quantize_params(params, min_ndim: int = 2):
    """Per-tensor int8 absmax quantization of every float leaf with
    ndim >= `min_ndim`; everything else passes through untouched. An
    all-zero tensor keeps scale 0 and round-trips to exact zeros."""

    def q(leaf):
        if not hasattr(leaf, "ndim") or leaf.ndim < min_ndim \
                or not jnp.issubdtype(jnp.asarray(leaf).dtype,
                                      jnp.floating):
            return leaf
        x = jnp.asarray(leaf)
        f = x.astype(jnp.float32)
        amax = jnp.max(jnp.abs(f))
        s = amax / _INT8_MAX
        safe = jnp.where(s > 0, s, 1.0)
        codes = jnp.clip(jnp.round(f / safe),
                         -_INT8_MAX, _INT8_MAX).astype(jnp.int8)
        return QuantTensor(codes, s.astype(jnp.float32), x.dtype)

    return jax.tree_util.tree_map(q, params)


def dequantize_params(params):
    """Rebuild a plain params tree from a `quantize_params` tree — the
    first op of a weight-quantized compiled step (so the upcast is
    inside the jit, foldable into the consuming matmuls). Identity on
    trees with no QuantTensor nodes."""
    return jax.tree_util.tree_map(
        lambda l: l.dequantize() if _is_qt(l) else l,
        params, is_leaf=_is_qt)


def params_bytes(params) -> int:
    """HBM bytes of a params tree (quantized leaves count their int8
    codes + scale) — the weight term of the decode byte roofline."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params, is_leaf=_is_qt):
        if _is_qt(leaf):
            total += leaf.nbytes
        elif hasattr(leaf, "nbytes"):
            total += int(leaf.nbytes)
    return total
