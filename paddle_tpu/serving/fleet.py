"""Fault-tolerant serving fleet: N supervised `ServingEngine` replicas
behind one front door (ISSUE 6; ROADMAP item 3).

The reference's cloud layer exists so that *training* survives any
single process dying: the Go master leases tasks with timeouts and
fencing, etcd TTL keys detect dead trainers, and the cluster controller
respawns them (go/master/service.go, go/pserver/etcd_client.go). PR 1
rebuilt those primitives for trainers — coordinator heartbeats,
incarnation-fenced membership, lease generations, supervisor
restart/backoff. This module points the same control plane at
*inference*: one `ServingFleet` owns N engine replicas (in-process
threads here; a subprocess mode through `distributed/supervisor.py`
below for kill drills), and a crash mid-decode loses nothing.

Guarantees (the PR-1 drills' falsifiability bar, recast for serving):

  * No request lost — every `submit()` lands in a durable REQUEST
    JOURNAL before it is routed; when a replica dies (crash, hang past
    the heartbeat deadline, or drill kill), its queued + in-flight
    requests are recovered FROM THE JOURNAL and resubmitted to
    survivors. Outputs are token-identical to sequential `generate()`
    no matter which replica (or how many replicas, in sequence) ran
    the request: the engine's per-request sampling keys depend only on
    (seed, token index), never on slot or replica assignment.
  * No request answered twice — completions are deduplicated by
    request id, and a result reported by a replica that has been
    declared dead is REFUSED (incarnation fencing: the registered
    replica object + its incarnation are the liveness lease, exactly
    the zombie-holder rule the coordinator's task leases enforce). A
    stalled replica that wakes after failover cannot overwrite the
    survivor's answer.
  * Bounded admission — at most `max_pending` requests may be open
    (queued + in-flight) fleet-wide; past that `submit()` raises
    `FleetSaturated` instead of growing an unbounded queue. Explicit
    load-shed is the backpressure contract: the CALLER decides what to
    drop, the fleet never hides an hour of queue wait.
  * Prefix-affinity routing — each replica's engine publishes a
    host-side SUMMARY of its prefix pool (chained-crc block keys,
    `prefix_cache.chain_keys`); routing sends a prompt to the replica
    whose pool holds its longest cached prefix (ties: least loaded),
    so shared-header families keep hitting the replica whose blocks
    are hot and PR 4's prefill deletion becomes a fleet-wide number
    (RadixAttention-style reuse, now across replicas).
  * Drain/refill — `drain(i)` stops admitting to a replica, finishes
    its in-flight work (publishing prefixes back to its pool as every
    completed prefill does), then parks it; `refill(i)` brings a
    DRAINED replica back with its engine — and prefix pool — warm, or
    replaces a DEAD one with a fresh incarnation. Planned restarts
    lose neither requests nor the hot prefix working set.
  * SLO classes — `replica_slo` maps each replica to a class
    ("interactive"/"batch"), and `slo_classes` maps the class onto the
    engine's `max_prefills_per_step` (interactive = 1: flattest decode
    latency; batch = None: maximum prefill throughput). `submit(slo=)`
    routes within the class, falling back to any live replica before
    failing — SLO is a preference, survival is a guarantee.
  * Per-request deadlines (ISSUE 8) — `submit(deadline_s=)` journals
    the budget with the spec and enforces it at EVERY queue hop:
    dead-on-arrival requests raise `DeadlineExceeded` before the
    saturation shed, the routing hop expires inbox requests whose
    budget died waiting, and the engine expires queued / prefilling /
    decoding requests before spending another step on them. Expiry is
    a terminal journal verdict (`expired`) — no request is ever late
    without one, and the scheduler never burns decode steps on a
    request that cannot be answered in budget.
  * Gray-failure demotion + hedged failover with token-level resume
    (ISSUE 8) — fail-stop detection (heartbeats) cannot see a replica
    that is alive but too slow (Huang et al., "Gray Failure"; Dean &
    Barroso, "The Tail at Scale"). With `slow_replica_factor` set, the
    monitor scores every busy replica's step-latency EWMA against the
    live-fleet median and watches a decode-progress watermark (tokens
    per wall-second); a replica slow past the factor for
    `slow_min_duration_s` (hysteresis: one GC pause decays out of the
    EWMA and resets the clock) is DEMOTED — not killed: its open
    requests are hedged to survivors, it cancels the clawed-back work,
    stays warm, and is probed every `probe_interval_s` until healthy,
    then restored under the SAME incarnation with its prefix pool hot.
    Hedged (and failed-over) requests resume at the TOKEN level: every
    emitted token is journaled incrementally (batched, flush-deferred
    records), the survivor is submitted `prompt + tokens_already_
    emitted` with the original sampling-key schedule continued at the
    resume index, and the prefix pool aliases whatever prefix it
    holds — decode steps are never re-spent, outputs stay
    token-identical to an uninterrupted `generate()`. The journal's
    latest ASSIGNMENT is the lease: a demoted replica racing its
    hedged survivor has its completions and progress refused, exactly
    like a zombie lease-holder.
  * Prefill/decode disaggregation (ISSUE 11) — with `replica_tier`
    set, admissions route to PREFILL-tier replicas (engine tuned for
    prefill throughput, `max_prefills_per_step=None`) and MIGRATE at
    first token to a DECODE-tier replica: the fleet journals the
    prefill replica's progress, cancels its claim (same handshake —
    it never spends another step), and resubmits with
    `resume_tokens=` — PR 8's token-level resume used ON PURPOSE
    instead of on failure. The decode replica prefill-aliases the
    finished prefill (block aliasing against its own pool, fed by
    prefix-affinity routing), ZERO journaled tokens are re-decoded,
    and outputs stay token-identical to a single-replica run (the
    engine's sampling keys depend only on (seed, token index)).
  * Queue-driven autoscaling (ISSUE 11) — with `min_replicas <
    max_replicas`, the monitor's scale sweep spawns replicas when open
    requests outrun live capacity (`scale_up_open_per_replica`) or
    deadline headroom shrinks below `scale_up_headroom_s`, and retires
    them after `scale_down_idle_s` of sustained low load. Scale-up
    goes through the warm `refill()` machinery (a DRAINED replica
    resumes warm; otherwise a fresh incarnation spawns, gated by the
    supervisor's exponential restart backoff); scale-down is a
    graceful `drain()` → retire: queued requests re-route immediately,
    in-flight work is hedged to survivors FROM THE JOURNAL with
    token-level resume, and the replica's stats fold into the
    cumulative base so fleet totals stay monotonic. One cool-down gate
    (`scale_cooldown_s`) covers both directions — a burst cannot flap
    the fleet.
  * Live weight rollout (ISSUE 11) — `roll_weights(ckpt_step)`
    consumes a training checkpoint (default: the sentinel's promoted
    known-good step) and performs a rolling drain → swap → refill
    across the fleet. The candidate is CRC-verified with
    `resume_or_init`'s per-step walk machinery BEFORE any replica
    touches it — a failed verify aborts the rollout with the fleet
    untouched, every replica still serving the old version. Every
    response records the `weights_version` that produced it (assign
    and done journal records carry the version side-band; the journal
    DFA's J009 rejects a done whose version differs from its latest
    assignment's). In-flight requests either FINISH on the old
    version (policy "finish", the default: the drain waits) or
    migrate-resume onto the new one (policy "migrate": hedged from
    the journal like a demotion) — pinned by the `rollout_policy`
    knob, so a request's verdict version always matches its final
    assignment.
  * Serving integrity (ISSUE 15) — replicas that are alive, fast, and
    WRONG: the engines' in-step numeric traps and KV block
    fingerprints raise `IntegrityError` into the crash path, and
    `canary_interval_s` adds known-answer canary requests on LIVE
    replicas judged against a per-weights_version golden trace. Any
    trip QUARANTINES the replica (killed under a fresh incarnation
    through the supervisor backoff) and journals an `integrity`
    record tainting its progress since the last clean canary: the
    mirror truncates to the verified prefix, resubmission resumes
    from the last verified token index, and the taint window
    re-decodes on a healthy survivor — the one sanctioned exception
    to PR 8's zero-re-decode rule, audited by the journal DFA's J010.
    A done landing from inside a taint window is refused by the
    fence (the tripped incarnation is dead; `zombie_refused`).

Threading: all shared scheduler state lives on `ServingFleet` and is
guarded by ONE condition's lock (`_cond`); replica threads and the
monitor thread touch it only through fleet methods that take it.
Engines (and their prefix tries) are confined to their replica's
thread — the router sees pools only through the immutable summary sets
handed over under the lock.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from ..distributed.supervisor import restart_backoff_s as _backoff
from .engine import EngineFailed, ServingEngine
from .integrity import (CANARY_PROMPT, IntegrityError, fp_digest,
                        golden_trace)
from .kv_store import KVBlockStore
from .prefix_cache import chain_keys
from .tenancy import TenantQuotaExceeded, WFQueue

__all__ = [
    "ServingFleet", "FleetHandle", "FleetSaturated", "RequestJournal",
    "DeadlineExceeded", "FleetTimeout", "run_fleet_subprocess",
    "SchedulerHook", "RolloutAborted", "save_weights",
    "TenantQuotaExceeded", "IntegrityError",
]


class SchedulerHook(object):
    """Seam contract for deterministic schedule exploration (ISSUE 9).

    The fleet's protocol bugs live in interleavings — a handshake
    racing a demotion racing a close. This hook is the controlled-
    scheduler seam (CHESS-lite, Musuvathi et al.): the fleet calls it
    at every thread-handoff point, and a controlling implementation
    (`paddle_tpu.analysis.sched_explore.ControlledScheduler`) can park
    each thread there and enumerate who runs next. The default
    (`scheduler_hook=None`) costs one `is not None` test per point.

    Contract — every yield point is OUTSIDE all fleet locks, so a
    parked thread never blocks another thread's lock acquisition:

      thread_started(kind, name)  first call on a fleet-owned thread
                                  ("replica"/"monitor"), before any
                                  yield_point; `name` is unique per
                                  incarnation (e.g. "r0.i2", "mon")
      yield_point(point)          a handoff point was reached; may
                                  block until the scheduler grants the
                                  thread its turn. Points: "replica:
                                  <name>:sync" (before the scheduler
                                  handshake), "replica:<name>:step"
                                  (before an engine step),
                                  "monitor:sweep" (before a monitor
                                  pass), "journal:flush" (before the
                                  journal file write), "submit:commit"
                                  (between a submit's durable journal
                                  write and its routing critical
                                  section — the close()-race window),
                                  "engine:<replica_id>:step" (inside
                                  `ServingEngine.step`)
      thread_exiting()            last call on the thread (crash paths
                                  included), so a controller never
                                  waits on a dead thread
      thread_spawning(name)       NON-BLOCKING notice, called on the
                                  SPAWNING thread just before a new
                                  fleet thread starts (a scale-up, a
                                  rollout refill): `name` is the exact
                                  name the new thread will register
                                  under. Lets a controller account for
                                  the thread synchronously — without
                                  it, the gap between start() and the
                                  new thread's own registration would
                                  make recorded schedules racy. May be
                                  called while fleet locks are held,
                                  so it MUST NOT block

    A hook must tolerate calls from UNREGISTERED threads (the caller's
    own submit/close run on threads the fleet never started) — the
    no-op base ignores everything.
    """

    def thread_started(self, kind: str, name: str):
        pass

    def thread_spawning(self, name: str):
        pass

    def yield_point(self, point: str):
        pass

    def thread_exiting(self):
        pass


# Test-only protocol mutants (tests/test_protocol_analysis.py): each
# name re-opens a REAL post-merge review bug behind a flag so the
# schedule explorer / journal verifier can prove they catch it —
# CHESS-style regression seeding. Never set outside tests:
#   "superseded_report"  _accept skips the in-flight check that refuses
#                        a completion for work this replica no longer
#                        tracks (the PR-8 demote -> survivor-death ->
#                        route-back fence hole: the stale report's
#                        tokens double-prepend the resume prefix)
#   "double_reject"      _reject_locked skips its idempotence guard
#                        (the PR-6 close()-race double count: rejected
#                        increments twice, stats()['lost'] goes
#                        negative, the journal gets a second terminal)
_MUTANTS: Set[str] = set()

# replica lifecycle states
_LIVE, _DRAINING, _DRAINED, _DEAD = "live", "draining", "drained", "dead"
# gray-failure state (ISSUE 8): alive and heartbeating, but too slow —
# drained of work, probed, and restored (not killed) when healthy again
_DEMOTED = "demoted"
# elastic state (ISSUE 11): a slot with no running replica — either it
# never started (capacity held back for scale-up) or the autoscaler
# drained and retired it (stats folded, thread exited). Scale-up (or an
# operator refill()) brings it back as a fresh incarnation.
_RETIRED = "retired"

# per-replica stats that are GAUGES (a dead incarnation's value is
# meaningless going forward): never folded into cumulative _stats_base.
# The construction labels (paged_kernel, kv_quant, weight_quant) are
# non-numeric gauges: folding them would TypeError on replica death
_GAUGE_STATS = ("kv_blocks_in_use", "step_ewma_s", "busy",
                "paged_kernel", "kv_quant", "weight_quant")


def _lower_median(xs: List[float]) -> Optional[float]:
    """LOWER median of the LATENCY samples (lower = healthier): with
    two live replicas the upper median IS the slow one, and nothing
    would ever look slow relative to it. Shared by the demotion and
    restore thresholds so they cannot silently diverge."""
    if not xs:
        return None
    return sorted(xs)[(len(xs) - 1) // 2]


def _upper_median(xs: List[float]) -> Optional[float]:
    """UPPER median of the RATE samples — polarity is the INVERSE of
    latency (higher = healthier): with two busy replicas the lower
    median IS the gray one's trickle, and judging it against its own
    rate would veto demotion forever."""
    if not xs:
        return None
    return sorted(xs)[len(xs) // 2]

_DEFAULT_SLO_CLASSES = {
    # interactive: one prefill chunk per step fleet-wide per replica —
    # the flattest decode latency for that replica's neighbors (TTFT of
    # long prompts pays); batch: every pending slot advances (highest
    # prefill throughput, decode latency of neighbors pays)
    "interactive": {"max_prefills_per_step": 1},
    "batch": {"max_prefills_per_step": None},
}

_DEFAULT_TIER_CLASSES = {
    # prefill tier: every pending slot advances a chunk per step —
    # maximum prefill throughput, and its decode latency does not
    # matter because requests MIGRATE OUT at first token; decode tier:
    # at most one prefill chunk per step (only the resume re-prefill of
    # migrated-in work runs here), keeping the batched decode cadence
    # flat — the disaggregation split (DistServe/Splitwise lineage)
    "prefill": {"max_prefills_per_step": None},
    "decode": {"max_prefills_per_step": 1},
}


class FleetSaturated(RuntimeError):
    """`submit()` refused: the fleet already holds `max_pending` open
    requests. Explicit load-shed — retry later or scale out; the fleet
    never grows an unbounded admission queue."""


class DeadlineExceeded(RuntimeError):
    """Terminal per-request verdict (ISSUE 8): the request's
    `deadline_s` budget ran out before it could finish. Raised by
    `submit()` when the deadline is already spent on arrival (checked
    BEFORE the `FleetSaturated` shed, so overload metrics never absorb
    client-side lateness), and by `FleetHandle.result()` when the
    request expired at a later queue hop. The journal records the
    expiry — a verdict, never a silent hang — and `tokens` carries
    whatever was emitted before the budget died."""

    def __init__(self, msg: str, rid=None, tokens=None):
        super().__init__(msg)
        self.rid = rid
        self.tokens = list(tokens) if tokens else []


class FleetTimeout(TimeoutError):
    """`FleetHandle.result(timeout=...)` ran out of caller patience —
    NOT a fleet verdict: the request is still open. Carries the fleet
    context an operator needs to tell a slow request from a lost one:
    rid, the journal state (queued / assigned / decoding), the replica
    currently holding the assignment, and how many tokens have been
    emitted so far (ISSUE 8 satellite)."""

    def __init__(self, msg: str, rid=None, state=None, replica=None,
                 tokens_emitted=0):
        super().__init__(msg)
        self.rid = rid
        self.state = state
        self.replica = replica
        self.tokens_emitted = tokens_emitted


class RequestCancelled(RuntimeError):
    """Terminal CLIENT verdict (ISSUE 18): the request was cancelled
    by its submitter — a dropped wire connection, an explicit cancel
    frame, or a direct `ServingFleet.cancel()` call — before the fleet
    finished it. The journal records a `cancelled` terminal (the DFA
    accepts it as closed), every engine-side slot and KV block the
    request held is clawed back through the same cancel path demotion
    hedging uses, and `tokens` carries the journaled prefix emitted
    before the cancel landed. Distinct from `expired` (the FLEET's
    deadline verdict) so shed/SLO metrics never blame the fleet for an
    abandoned stream."""

    def __init__(self, msg: str, rid=None, tokens=None):
        super().__init__(msg)
        self.rid = rid
        self.tokens = list(tokens) if tokens else []


class RolloutAborted(RuntimeError):
    """`roll_weights()` refused to start: the candidate checkpoint
    failed its CRC/metas verification (or no known-good step exists).
    The fleet is UNTOUCHED — no replica was drained, every replica
    still serves the previous weights version. Carries the per-file
    evidence in `problems`."""

    def __init__(self, msg: str, problems=None):
        super().__init__(msg)
        self.problems = list(problems or [])


class _KillDrill(RuntimeError):
    """Injected replica death (ServingFleet.kill_replica)."""


class FleetHandle(object):
    """Per-request future filled in by whichever replica completes the
    request (possibly a survivor after failover). Thread-safe: waiters
    block on an event, never by driving an engine."""

    def __init__(self, rid: int, prompt: np.ndarray, spec: dict,
                 slo: Optional[str], fleet=None, deadline_at=None):
        self.rid = rid
        self.prompt = prompt  # np.int32 [T0]
        self.spec = spec      # JSON-able request record (journal form)
        self.slo = slo
        self.generation = 0   # bumped on every resubmission
        # absolute time.monotonic() budget (None = none); journaled as
        # (deadline_s, submit_unix) so a recovered front door can
        # recompute the remaining budget across a process restart
        self.deadline_at = deadline_at
        # tokens already emitted by a dead/demoted incarnation; the
        # next assignee prefill-aliases these and decodes ONLY the
        # remainder (token-level resume). Replaced wholesale (never
        # mutated in place) under the fleet lock at re-route time.
        self.resume: List[int] = []
        # running count of journaled emitted tokens (resume included) —
        # cheap operator context for FleetTimeout
        self.emitted = 0
        self.ttft_s: Optional[float] = None  # first journaled token
        self.tokens: Optional[List[int]] = None
        self.replica: Optional[str] = None  # who answered
        # live-rollout version fence (ISSUE 11): the weights_version of
        # the replica that COMPLETED this request (None when the fleet
        # is unversioned, or when the answer came straight from
        # journaled progress of a holder whose version is unrecorded)
        self.weights_version: Optional[int] = None
        self.error: Optional[BaseException] = None
        self.chain: List[int] = []  # affinity keys (set by the fleet)
        # multi-tenant side-band (ISSUE 12): the admitting tenant
        # (None on a single-tenant fleet), the WFQ service-cost
        # estimate, and — for batch-lane (zoo) jobs — the host
        # callable a replica runs between engine steps plus its return
        # value. All set by the fleet at submit time.
        self.tenant: Optional[str] = None
        self.cost: float = 1.0
        self.batch_fn = None
        self.batch_result = None
        # durable-KV handoff (ISSUE 16): the block package fetched from
        # the fleet store at re-route (consumed by the assignee's
        # submit) and the journal side-band describing it ({"len",
        # "digest"} — stamped onto the assign record, the J011 fence).
        # Both replaced wholesale under the fleet lock at re-route.
        self.handoff_package: Optional[list] = None
        self.handoff_meta: Optional[dict] = None
        self._probe = False   # internal health probe, never journaled
        # known-answer canary (ISSUE 15): a _probe-shaped request on a
        # LIVE replica whose completion is judged against the golden
        # trace instead of the demotion-restore machinery
        self._canary = False
        # wire/streaming side-band (ISSUE 18): the front-door
        # connection id this request arrived on (None for direct
        # Python callers) and whether the caller asked for incremental
        # delivery. Both journaled on the submit record (typed by the
        # DFA's J008 rule) so a wire-level FleetTimeout names them.
        self.conn: Optional[str] = None
        self.streaming = False
        # journal-accumulation index already queued to the stream —
        # written only under the FLEET lock (guarded-by: fleet._cond),
        # so pushes are ordered exactly like the journal mirror
        self._stream_sent = 0
        # delivered-token buffer + close flag; its own leaf lock
        # (guarded-by: _stream_cv — taken inside fleet._cond at feed
        # time, never the other way) so iterators never touch the
        # scheduler lock. Tokens land here only AFTER the journal
        # records describing them are on disk (the _flush_journal
        # read-your-writes discipline, same as _event).
        self._stream_buf: List[int] = []   # guarded-by: _stream_cv
        self._stream_closed = False        # guarded-by: _stream_cv
        self._stream_cv = threading.Condition()
        self._fleet = fleet
        self._submit_t = time.monotonic()
        self._event = threading.Event()

    @property
    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block until the request completes somewhere in the fleet;
        returns prompt + generated tokens. Raises `EngineFailed` if the
        fleet lost every replica (or was closed) with this request
        pending, `DeadlineExceeded` if the request's budget expired,
        and `FleetTimeout` — carrying rid, journal state, assigned
        replica, and tokens emitted so far — when the CALLER's timeout
        runs out with the request still open."""
        if not self._event.wait(timeout):
            ctx = (self._fleet._describe(self.rid)
                   if self._fleet is not None else {})
            raise FleetTimeout(
                "request %d not completed within %r s: %s "
                "(%d token(s) emitted so far)" % (
                    self.rid, timeout,
                    ctx.get("describe", "state unknown"),
                    ctx.get("tokens_emitted", self.emitted)),
                rid=self.rid, state=ctx.get("state"),
                replica=ctx.get("replica"),
                tokens_emitted=ctx.get("tokens_emitted", self.emitted))
        if self.error is not None:
            raise self.error
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)])

    def _stream_feed(self, tokens: List[int], closing: bool):
        """Deliver journaled tokens to stream iterators (called by the
        fleet AFTER the journal flush wrote the records describing
        them — never under `fleet._cond`). Idempotent past close: a
        handle swept by close() may see a second deferred close from
        the flush straggler; once closed, nothing changes."""
        with self._stream_cv:
            if self._stream_closed:
                return
            if tokens:
                self._stream_buf.extend(int(t) for t in tokens)
            if closing:
                self._stream_closed = True
            self._stream_cv.notify_all()

    def stream_chunks(self, timeout: Optional[float] = None):
        """Incremental delivery (ISSUE 18 / ROADMAP 4a): yield lists
        of newly journaled generated tokens as the fleet's batched
        journal flushes land them — one chunk per flushed progress
        batch, so wire framing rides the journal's own cadence. The
        concatenation of every chunk is bit-identical to the generated
        half of `result()` for every request, across failover and
        migration: chunks are fed from the SAME fenced, exactly-once
        journal mirror failover resumes from, so a spliced stream is
        the resumed prefix plus the survivor's deltas — never a
        re-decoded or interleaved token. Terminal errors (deadline,
        reject, cancel, fleet death) raise HERE after the delivered
        prefix, exactly like `result()` would; `timeout` bounds the
        wait for each NEXT chunk and raises `FleetTimeout` with the
        fleet's describe context."""
        sent = 0
        while True:
            with self._stream_cv:
                while (sent >= len(self._stream_buf)
                        and not self._stream_closed):
                    if not self._stream_cv.wait(timeout):
                        ctx = (self._fleet._describe(self.rid)
                               if self._fleet is not None else {})
                        raise FleetTimeout(
                            "stream for request %d idle for %r s: %s "
                            "(%d token(s) delivered so far)" % (
                                self.rid, timeout,
                                ctx.get("describe", "state unknown"),
                                sent),
                            rid=self.rid, state=ctx.get("state"),
                            replica=ctx.get("replica"),
                            tokens_emitted=ctx.get(
                                "tokens_emitted", sent))
                chunk = self._stream_buf[sent:]
                closed = self._stream_closed
            if chunk:
                sent += len(chunk)
                yield chunk
            if closed and sent >= len(self._stream_buf):
                break
        # the close fed by a terminal always trails its _event/error
        # publication, so a drained stream can report the verdict
        if self.error is not None:
            raise self.error

    def stream(self, timeout: Optional[float] = None):
        """Per-token view of `stream_chunks()` — yields ints."""
        for chunk in self.stream_chunks(timeout=timeout):
            for t in chunk:
                yield t

    def cancel(self) -> bool:
        """Client-side cancel (ISSUE 18): ask the fleet to stop this
        request. Returns False when it already went terminal."""
        if self._fleet is None:
            return False
        return self._fleet.cancel(self.rid)


_TERMINAL_KINDS = ("done", "rejected", "expired", "cancelled")

# submit(slo=...)'s "caller said nothing" sentinel: distinguishes the
# implicit default ("interactive", or the tenant's registered default
# class on a multi-tenant fleet) from an EXPLICIT slo=None (wildcard —
# any replica class). A plain string default could not tell the two
# apart, and the tenant default would be unreachable.
_SLO_UNSET = object()


class RequestJournal(object):
    """Durable request table: every submit/assign/progress/terminal
    (done / rejected / expired) transition is appended (JSON lines)
    BEFORE the fleet acts on it, and mirrored in memory as the
    authoritative OPEN-request index (terminal records prune their
    mirror entries, so memory is bounded by in-flight work, not
    lifetime traffic). Failover reads the journal mirror —
    `lost(replica, incarnation)`, which now carries the PROGRESS
    tokens for token-level resume — not scheduler guesswork. Opening
    an EXISTING journal replays it: the mirror resumes the open set
    and `next_rid()` continues past every rid ever issued, so a
    restarted front door appending to the same file can never collide
    with (and thereby corrupt) the history. `path=None` keeps the
    mirror only (tests); `recover(path)` is the read-only restart
    helper.

    Durability: records are flushed per append (they survive any
    process death — the failure mode the fleet handles). `fsync=True`
    additionally fsyncs each record for OS-crash/power-loss
    durability, at per-request disk latency cost.

    Compaction (ISSUE 8 satellite): per-token progress records make an
    append-only file grow with lifetime TRAFFIC, not in-flight work.
    With `compact_every=N`, once the file holds >= N records (and the
    rewrite would actually shrink it) the journal atomically rewrites
    itself to just a meta record (preserving the rid history) plus the
    open requests' submit/assign/progress state — `recover()` after a
    compaction sees exactly the same open set."""

    def __init__(self, path: Optional[str] = None, fsync: bool = False,
                 compact_every: Optional[int] = None):
        self._lock = threading.Lock()
        self.path = path
        self.fsync = bool(fsync)
        if compact_every is not None and int(compact_every) < 1:
            raise ValueError("compact_every must be >= 1 or None")
        self.compact_every = (
            None if compact_every is None else int(compact_every))
        self.compactions = 0                         # guarded-by: _lock
        self._file_records = 0                       # guarded-by: _lock
        self._open_specs: Dict[int, dict] = {}       # guarded-by: _lock
        self._assign: Dict[int, Tuple[str, int, int]] = {}  # guarded-by: _lock
        # (tier, weights_version, tenant) side-band of the latest
        # assignment (ISSUEs 11 + 12): kept apart from _assign so the
        # 3-tuple fence consumers stay unchanged; compaction must
        # reproduce it
        self._assign_meta: Dict[int, Tuple[Optional[str], Optional[int], Optional[str]]] = {}  # guarded-by: _lock
        self._progress: Dict[int, List[int]] = {}    # guarded-by: _lock
        # taint side-band (ISSUE 15): open rids whose journaled
        # progress was truncated by an integrity record — rid ->
        # (replica, incarnation, from, upto). Compaction must
        # reproduce these (the J010 re-decode audit spans rotations);
        # terminal records prune them like every other mirror entry
        self._taint: Dict[int, Tuple[str, int, int, int]] = {}  # guarded-by: _lock
        self._done: Set[int] = set()                 # guarded-by: _lock
        # records handed out via defer=True whose file append is still
        # pending in the caller: while any are outstanding the mirror
        # is AHEAD of the file, so no compaction may snapshot it
        self._deferred_out = 0                       # guarded-by: _lock
        self._max_rid = -1                           # guarded-by: _lock
        # True when this journal object REOPENED an existing file (a
        # restarted front door): its predecessor's unterminated rids
        # legitimately stay open forever, so the close()-audit must not
        # assert the everything-terminal invariant over them
        self.preexisting = bool(path and os.path.exists(path))
        if self.preexisting:
            self._replay_and_heal(path)
        self._f = open(path, "a") if path else None  # guarded-by: _lock

    @staticmethod
    def _read(path: str):
        """Parse a journal file, tolerating a TORN FINAL line (the
        process died mid-append — the crash this journal exists to
        survive must not make it unreadable). A malformed line
        followed by valid records is real corruption and raises."""
        pending_error = None
        with open(path) as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                if pending_error is not None:
                    raise ValueError(
                        "corrupt journal %s: unparseable line %d is "
                        "not a torn tail" % (path, pending_error))
                try:
                    rec = json.loads(line)
                except ValueError:
                    pending_error = lineno  # torn IF nothing follows
                    continue
                yield rec

    def _replay_and_heal(self, path: str):
        """Replay an existing journal into the mirror and TRUNCATE a
        torn final line: reopening in append mode would otherwise glue
        the next record onto the partial text, turning a tolerated
        torn tail into mid-file corruption for every later reader."""
        good_end = 0
        torn_at = None
        with open(path, "rb") as f:
            for lineno, raw in enumerate(f.readlines(), 1):
                line = raw.decode("utf-8", "replace").strip()
                if not line:
                    if torn_at is None:
                        good_end += len(raw)
                    continue
                if torn_at is not None:
                    raise ValueError(
                        "corrupt journal %s: unparseable line %d is "
                        "not a torn tail" % (path, torn_at))
                try:
                    rec = json.loads(line)
                except ValueError:
                    torn_at = lineno
                    continue
                self._replay(rec)
                self._file_records += 1
                good_end += len(raw)
        if torn_at is not None:
            with open(path, "r+b") as f:
                f.truncate(good_end)

    def _replay(self, rec: dict):
        if rec["kind"] == "meta":  # compaction marker: rid history
            self._max_rid = max(self._max_rid, rec["max_rid"])
            return
        if rec["kind"] == "integrity":  # taint side-band (ISSUE 15)
            self._apply_taint(rec["replica"], rec["incarnation"],
                              {int(r): (w[0], w[1])
                               for r, w in rec["taint"].items()})
            return
        rid = rec["rid"]
        self._max_rid = max(self._max_rid, rid)
        if rec["kind"] == "submit":
            self._open_specs[rid] = rec["spec"]
        elif rec["kind"] == "assign":
            self._assign[rid] = (rec["replica"], rec["incarnation"],
                                 rec["gen"])
            self._assign_meta[rid] = (rec.get("tier"),
                                      rec.get("weights_version"),
                                      rec.get("tenant"),
                                      rec.get("handoff"))
        elif rec["kind"] == "progress":
            self._progress.setdefault(rid, []).extend(rec["tokens"])
        elif rec["kind"] in _TERMINAL_KINDS:
            self._done.add(rid)
            self._open_specs.pop(rid, None)
            self._assign.pop(rid, None)
            self._assign_meta.pop(rid, None)
            self._progress.pop(rid, None)
            self._taint.pop(rid, None)

    def _append(self, rec: dict, flush: bool = True):
        if self._f is not None:
            self._f.write(json.dumps(rec) + "\n")
            self._file_records += 1
            if flush:
                self._flush_file()
                # auto-compaction only at a batch boundary (here =
                # single-record batch): the snapshot is built from the
                # MIRROR, which already holds the effects of deferred
                # records not yet appended — compacting mid-batch
                # would write those effects AND then append the
                # records on top, duplicating progress tokens in the
                # file (wrong resume prefixes after a restart)
                self._maybe_compact()

    def _flush_file(self):  # holds: _lock
        self._f.flush()
        if self.fsync:
            os.fsync(self._f.fileno())

    def _open_records(self) -> List[dict]:
        """The records a compaction must preserve: one meta record (the
        rid history, so next_rid() survives the rewrite) plus each open
        request's submit, latest assign, and accumulated progress —
        and, for rids inside an active taint window, the consolidated
        `integrity` side-band (grouped by quarantined holder), so the
        J010 re-decode audit still knows which token indices are
        sanctioned to re-decode after a rotation (ISSUE 15)."""
        recs: List[dict] = [{"kind": "meta", "max_rid": self._max_rid}]
        for rid in sorted(self._open_specs):
            recs.append({"kind": "submit", "rid": rid,
                         "spec": self._open_specs[rid]})
            # consolidated progress BEFORE the re-emitted assignment:
            # the verifier's handoff fence (J011) anchors a package-
            # carrying assign against the history that precedes it —
            # progress-first keeps the re-route shape of the live file
            # (tokens journaled, then the new holder assigned)
            if self._progress.get(rid):
                recs.append({"kind": "progress", "rid": rid,
                             "replica": None, "incarnation": None,
                             "gen": None,
                             "tokens": list(self._progress[rid])})
            if rid in self._assign:
                rep, inc, gen = self._assign[rid]
                tier, wv, ten, ho = self._assign_meta.get(
                    rid, (None, None, None, None))
                recs.append({"kind": "assign", "rid": rid, "replica": rep,
                             "incarnation": inc, "gen": gen,
                             "tier": tier, "weights_version": wv,
                             "tenant": ten,
                             # the handoff side-band survives rotation:
                             # the J011 fence must still tie the open
                             # rid's eventual done to THIS transfer
                             "handoff": ho})
        by_holder: Dict[Tuple[str, int], Dict[int, Tuple[int, int]]] = {}
        for rid, (rep, inc, frm, upto) in self._taint.items():
            if rid not in self._open_specs:
                continue
            # emit only the REMAINING sanctioned span: the consolidated
            # progress record above already reflects the truncation
            # (plus any re-decode the survivor journaled since), so
            # replaying this record must truncate NOTHING — a window
            # anchored at the original `from` would discard the
            # survivor's verified re-decode on restart. Fully-consumed
            # windows were already dropped by progress(); this guards
            # the same invariant for windows consumed between there
            # and the snapshot
            cur = len(self._progress.get(rid, []))
            lo = max(frm, cur)
            if lo < upto:
                by_holder.setdefault((rep, inc), {})[rid] = (lo, upto)
        for (rep, inc) in sorted(by_holder):
            recs.append({
                "kind": "integrity", "replica": rep, "incarnation": inc,
                "taint": {str(r): [f, u] for r, (f, u)
                          in sorted(by_holder[(rep, inc)].items())}})
        return recs

    def _maybe_compact(self):  # holds: _lock
        """Auto-rotation: rewrite once the file crosses the threshold —
        but only when the rewrite actually SHRINKS it (a fleet whose
        open set alone exceeds the threshold must not rewrite the whole
        file on every append), and never while deferred records are
        outstanding (a direct append — e.g. submit — can land while
        another thread holds mirror-applied-but-unwritten progress
        records: the snapshot would write those tokens AND the later
        write() would append the same deltas on top, duplicating
        progress in the file and corrupting restart resume prefixes)."""
        if self.compact_every is None or self._f is None:
            return
        if self._deferred_out > 0:
            return
        if self._file_records < self.compact_every:
            return
        if self._file_records < 2 * (3 * len(self._open_specs) + 1):
            return
        self._compact_locked()

    def _compact_locked(self):  # holds: _lock
        recs = self._open_records()
        tmp = self.path + ".compact"
        with open(tmp, "w") as f:
            for rec in recs:
                f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())
        self._f.close()
        os.replace(tmp, self.path)  # atomic: crash keeps old OR new
        self._f = open(self.path, "a")
        self._file_records = len(recs)
        # _done is KEPT: is_done() must stay truthful across rotations
        # (ints only — bounded by lifetime, like the fleet's own
        # _done_rids dedupe set)
        self.compactions += 1

    def compact(self) -> bool:
        """Explicit rewrite-to-open-set (see class docstring). Returns
        False for a mirror-only journal, or while deferred records are
        outstanding (the mirror is ahead of the file — see
        _maybe_compact; retry after the pending write())."""
        with self._lock:
            if self._f is None or self._deferred_out > 0:
                return False
            self._compact_locked()
            return True

    def next_rid(self) -> int:
        """First rid safe to issue: past everything this journal file
        has ever seen (restart-collision guard)."""
        with self._lock:
            return self._max_rid + 1

    def submit(self, rid: int, spec: dict,
               conn: Optional[str] = None, stream: bool = False):
        """`conn`/`stream` are the wire side-band (ISSUE 18): the
        front-door connection id the request arrived on and whether
        the caller asked for incremental delivery — typed by the DFA
        (J008), absent entirely for direct Python submits so old
        journals stay valid byte-for-byte."""
        rec = {"kind": "submit", "rid": rid, "spec": spec}
        if conn is not None:
            rec["conn"] = str(conn)
        if stream:
            rec["stream"] = True
        with self._lock:
            self._open_specs[rid] = spec
            self._max_rid = max(self._max_rid, rid)
            self._append(rec)

    def assign(self, rid: int, replica: str, incarnation: int, gen: int,
               tier: Optional[str] = None,
               weights_version: Optional[int] = None,
               tenant: Optional[str] = None,
               handoff: Optional[dict] = None,
               defer: bool = False) -> Optional[dict]:
        """Record an assignment. The MIRROR updates synchronously (a
        failover consulting `lost()` an instant later must see it);
        with `defer=True` the file append is returned as a record for
        the caller to `write()` later — the fleet defers file I/O
        until it has released its scheduler lock. `tier`,
        `weights_version`, and `tenant` ride as an optional side-band
        (ISSUEs 11 + 12): the assignee's disaggregation tier, the
        weight version it serves — the journal DFA's version fence
        (J009) checks every done record against its latest
        assignment's version — and the tenant whose quota admitted
        the request (typed by the DFA: an ill-typed tenant is J008),
        so a per-tenant exactly-once audit can group the journal by
        consumer. `handoff` (ISSUE 16) records that this assignment
        ships a durable-KV block package — {"len": imported-prefix
        tokens, "digest": fp_digest of the chain} — the J011 handoff
        fence's assign half: the eventual done must account for the
        transfer (verified import or counted fallback)."""
        rec = {"kind": "assign", "rid": rid, "replica": replica,
               "incarnation": incarnation, "gen": gen,
               "tier": tier, "weights_version": weights_version,
               "tenant": tenant}
        if handoff is not None:
            rec["handoff"] = dict(handoff)
        with self._lock:
            self._assign[rid] = (replica, incarnation, gen)
            self._assign_meta[rid] = (tier, weights_version, tenant,
                                      handoff)
            if defer:
                self._deferred_out += 1
                return rec
            self._append(rec)
        return None

    def _terminal(self, rid: int, rec: dict,
                  defer: bool) -> Optional[dict]:
        """Shared body of every terminal kind (done/expired/rejected):
        mark the rid done, prune it from the open mirror, then append
        the record (or hand it back deferred)."""
        with self._lock:
            self._done.add(rid)
            self._open_specs.pop(rid, None)
            self._assign.pop(rid, None)
            self._assign_meta.pop(rid, None)
            self._progress.pop(rid, None)
            self._taint.pop(rid, None)
            if defer:
                self._deferred_out += 1
                return rec
            self._append(rec)
        return None

    def _apply_taint(self, replica: str, incarnation: int,
                     taint: Dict[int, Tuple[int, int]]):  # holds: _lock
        """Mirror effect of one integrity record: truncate each tainted
        rid's accumulated progress back to its verified index `from`,
        so `lost()`/`progress_of()` hand failover the CLEAN prefix and
        the taint window [from, upto) re-decodes on the survivor."""
        for rid, (frm, upto) in taint.items():
            rid = int(rid)
            cur = self._progress.get(rid)
            if cur is not None:
                self._progress[rid] = cur[:int(frm)]
            if rid in self._open_specs:
                self._taint[rid] = (replica, int(incarnation),
                                    int(frm), int(upto))

    def integrity(self, replica: str, incarnation: int,
                  taint: Dict[int, Tuple[int, int]], reason=None,
                  defer: bool = False) -> Optional[dict]:
        """Integrity quarantine record (ISSUE 15): replica
        (replica, incarnation) tripped the serving sentinel, and every
        journaled progress token it produced since its last clean
        canary is TAINTED. `taint` maps rid -> (from, upto): token
        indices [from, upto) of that rid's accumulated progress are
        suspect. The MIRROR truncates each rid's progress to `from`
        synchronously (the failover an instant later resumes from the
        verified prefix — the one sanctioned exception to PR 8's
        zero-re-decode rule), and the DFA's J010 audits that ONLY
        indices inside a journaled taint window ever re-decode."""
        rec = {"kind": "integrity", "replica": str(replica),
               "incarnation": int(incarnation),
               "taint": {str(int(r)): [int(f), int(u)]
                         for r, (f, u) in sorted(taint.items())}}
        if reason is not None:
            rec["reason"] = str(reason)
        with self._lock:
            self._apply_taint(str(replica), int(incarnation),
                              {int(r): (int(f), int(u))
                               for r, (f, u) in taint.items()})
            if defer:
                self._deferred_out += 1
                return rec
            self._append(rec)
        return None

    def taint_of(self, rid: int) -> Optional[Tuple[str, int, int, int]]:
        """(replica, incarnation, from, upto) of the rid's active taint
        window, or None."""
        with self._lock:
            return self._taint.get(rid)

    def complete(self, rid: int, replica: str, incarnation: int,
                 gen: int, tokens: List[int],
                 weights_version: Optional[int] = None,
                 tenant: Optional[str] = None,
                 handoff: Optional[dict] = None,
                 defer: bool = False) -> Optional[dict]:
        rec = {"kind": "done", "rid": rid, "replica": replica,
               "incarnation": incarnation, "gen": gen,
               "tokens": list(tokens)}
        if handoff is not None:
            # the J011 fence's done half: what became of the block
            # package the latest assignment shipped — {"imported":
            # tokens imported clean, "fallback": any re-prefill}
            rec["handoff"] = dict(handoff)
        if weights_version is not None:
            # the version fence's done half: which weights produced
            # this output (must equal the latest assignment's — J009)
            rec["weights_version"] = int(weights_version)
        if tenant is not None:
            # the tenant side-band's done half (ISSUE 12): which
            # consumer this verdict answered — typed by the DFA (J008)
            rec["tenant"] = str(tenant)
        return self._terminal(rid, rec, defer)

    def progress(self, rid: int, replica: str, incarnation: int,
                 gen: int, tokens: List[int],
                 conn: Optional[str] = None, stream: bool = False,
                 defer: bool = False) -> Optional[dict]:
        """Incremental emitted-token record (token-level resume,
        ISSUE 8): `tokens` is the DELTA since the last progress record
        for this rid. Batched by the fleet (one record per scheduler
        handshake, not per token) and flush-deferred like assign —
        the mirror is what failover resumes from. For a STREAMED
        request (ISSUE 18) the record carries the wire side-band:
        `conn` and the `stream` CURSOR — the accumulated journaled
        length after this delta, i.e. exactly how many generated
        tokens a front door restarted off this file may have already
        delivered to the client (typed by the DFA's J008 rule)."""
        rec = {"kind": "progress", "rid": rid, "replica": replica,
               "incarnation": incarnation, "gen": gen,
               "tokens": [int(t) for t in tokens]}
        if conn is not None:
            rec["conn"] = str(conn)
        with self._lock:
            acc = self._progress.setdefault(rid, [])
            acc.extend(rec["tokens"])
            if stream:
                rec["stream"] = len(acc)
            t = self._taint.get(rid)
            if t is not None and len(acc) >= t[3]:
                # the survivor's re-decode caught up with the taint
                # window: it is CONSUMED — a later compaction must not
                # re-emit (and replay must not re-truncate) a window
                # whose re-decode already happened
                del self._taint[rid]
            if defer:
                self._deferred_out += 1
                return rec
            self._append(rec)
        return None

    def expire(self, rid: int, tokens: List[int],
               defer: bool = False) -> Optional[dict]:
        """Terminal DEADLINE verdict: the request ran out of budget.
        Distinct from `rejected` (unservable) and `done` (answered) so
        shed/SLO metrics never conflate overload, malformed input, and
        lateness; `tokens` records what was emitted before expiry."""
        rec = {"kind": "expired", "rid": rid,
               "tokens": [int(t) for t in tokens]}
        return self._terminal(rid, rec, defer)

    def cancel(self, rid: int, tokens: List[int],
               conn: Optional[str] = None,
               defer: bool = False) -> Optional[dict]:
        """Terminal CLIENT verdict (ISSUE 18): the submitter walked
        away — a dropped wire connection or an explicit cancel frame.
        Distinct from `expired` (the fleet's own deadline) and
        `rejected` (unservable) so abandonment never pollutes shed or
        SLO metrics; `tokens` records the journaled prefix emitted
        before the cancel, `conn` the connection that owned the
        request. The DFA accepts it as closed (J007)."""
        rec = {"kind": "cancelled", "rid": rid,
               "tokens": [int(t) for t in tokens]}
        if conn is not None:
            rec["conn"] = str(conn)
        return self._terminal(rid, rec, defer)

    def write(self, recs: List[dict]):
        """File-append records whose mirror updates already happened
        (the deferred half of assign/complete/progress/expire). One
        flush per batch, not per record — and auto-compaction only
        AFTER the whole batch is on disk (see _append: a mid-batch
        snapshot would duplicate the not-yet-appended records'
        effects)."""
        with self._lock:
            for rec in recs:
                self._append(rec, flush=False)
            self._deferred_out = max(0, self._deferred_out - len(recs))
            if self._f is not None:
                self._flush_file()
                self._maybe_compact()

    def reject(self, rid: int, reason: str,
               defer: bool = False) -> Optional[dict]:
        """Terminal record for a request that can never complete (a
        malformed spec an engine refused, or no live replica to serve
        it): without it the rid would stay open forever and every
        future recover() would resubmit an unservable request."""
        rec = {"kind": "rejected", "rid": rid, "reason": reason}
        return self._terminal(rid, rec, defer)

    def lost(self, replica: str, incarnation: int
             ) -> List[Tuple[int, dict, int, List[int]]]:
        """(rid, spec, gen, emitted_tokens) of every OPEN request whose
        latest assignment is (replica, incarnation) — the set a
        failover/demotion must resubmit, with the progress tokens the
        survivor resumes from instead of re-decoding."""
        with self._lock:
            out = []
            for rid, (rep, inc, gen) in sorted(self._assign.items()):
                if rep == replica and inc == incarnation \
                        and rid in self._open_specs:
                    out.append((rid, self._open_specs[rid], gen,
                                list(self._progress.get(rid, []))))
            return out

    def assigned_to(self, rid: int) -> Optional[Tuple[str, int, int]]:
        """Latest (replica, incarnation, gen) assignment, or None. The
        completion/progress fence: only the current holder's reports
        count (the lease-generation rule, recast for request SLO)."""
        with self._lock:
            return self._assign.get(rid)

    def assigned_meta(self, rid: int
                      ) -> Tuple[Optional[str], Optional[int],
                                 Optional[str], Optional[dict]]:
        """(tier, weights_version, tenant, handoff) side-band of the
        latest assignment — all None when unassigned or unversioned.
        Lets a completion recovered straight from journaled progress
        record the version of the holder that actually produced the
        tokens, and lets _accept close the J011 handoff fence."""
        with self._lock:
            return self._assign_meta.get(rid, (None, None, None, None))

    def progress_of(self, rid: int) -> List[int]:
        with self._lock:
            return list(self._progress.get(rid, []))

    def open_count(self) -> int:
        with self._lock:
            return len(self._open_specs)

    def is_done(self, rid: int) -> bool:
        with self._lock:
            return rid in self._done

    def close(self):
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    @staticmethod
    def recover(path: str) -> List[Tuple[int, dict]]:
        """Rebuild the incomplete-request list from a journal file:
        (rid, spec) for every submitted rid with no terminal
        (done/rejected/expired) record, in submission order. A
        restarted front door resubmits exactly these — requests
        survive even a full fleet-process crash. (Use
        `recover_progress(path)` for the emitted-token prefixes and
        pass them to `ServingFleet.submit(resume_tokens=...)`.)"""
        specs: Dict[int, dict] = {}
        done: Set[int] = set()
        for rec in RequestJournal._read(path):
            if rec["kind"] == "submit":
                specs[rec["rid"]] = rec["spec"]
            elif rec["kind"] in _TERMINAL_KINDS:
                done.add(rec["rid"])
        return [(rid, specs[rid]) for rid in sorted(specs)
                if rid not in done]

    @staticmethod
    def recover_progress(path: str) -> Dict[int, List[int]]:
        """Emitted-token prefixes of the incomplete requests (rid ->
        tokens, in emission order): the restart counterpart of the
        in-process resume path — resubmit recover()'s specs via
        `ServingFleet.submit(..., resume_tokens=these[rid])` and no
        decode step is re-spent."""
        open_set = {rid for rid, _ in RequestJournal.recover(path)}
        prog: Dict[int, List[int]] = {}
        for rec in RequestJournal._read(path):
            if rec["kind"] == "progress" and rec["rid"] in open_set:
                prog.setdefault(rec["rid"], []).extend(rec["tokens"])
            elif rec["kind"] == "integrity":
                # taint truncation applies across restarts too: a
                # restarted front door must not resume a corrupt
                # replica's tainted suffix (ISSUE 15)
                for rid_s, (frm, _upto) in rec["taint"].items():
                    rid = int(rid_s)
                    if rid in prog:
                        prog[rid] = prog[rid][:int(frm)]
        return prog


class _FlatScope(object):
    """Checkpoint-scope adapter over a flat {name: array} dict — the
    bridge between a model params pytree and the training checkpoint
    machinery (save_checkpoint / load_checkpoint verify CRCs per
    entry; the scope protocol is keys/get/set)."""

    def __init__(self, arrays):
        self._arrays = arrays

    def keys(self):
        return self._arrays.keys()

    def get(self, name):
        return self._arrays.get(name)

    def set(self, name, val):
        self._arrays[name] = val


def _flat_names(params):
    """Positional leaf naming for a params pytree: stable across save
    and load because both sides flatten the SAME tree structure —
    no keypath escaping, and a checkpoint from a different model
    shows up as a count/shape mismatch, never a silent misload."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(params)
    return ["w%05d" % k for k in range(len(leaves))], leaves, treedef


def save_weights(params, ckpt_dir: str, step: int, keep_last: int = 8,
                 protect=None) -> dict:
    """Write one weight version under `ckpt_dir/step_<N>/` with the
    training checkpoint machinery (CRC sidecars, atomic meta commit) —
    the PUSH half of the reference's pserver push/pull recast as
    checkpoint promotion: a training job (or its sentinel, which
    promotes known-good steps) saves here, and
    `ServingFleet.roll_weights(step)` rolls the fleet onto it after
    the same CRC walk `resume_or_init` trusts. Returns the save
    meta."""
    from ..distributed.checkpoint import save_checkpoint

    names, leaves, _treedef = _flat_names(params)
    arrays = {n: np.asarray(v) for n, v in zip(names, leaves)}
    return save_checkpoint(_FlatScope(arrays), ckpt_dir, step=int(step),
                           keep_last=keep_last, protect=protect)


class _Replica(object):
    """One engine replica: a thread that builds and exclusively owns a
    `ServingEngine`, pulls work from the fleet, steps, and reports
    completions. Identity (object + incarnation) IS the liveness lease
    the fleet fences on. Everything here is confined to the replica
    thread; the fleet reads only the immutable fields (name, index,
    incarnation, slo, and the composed `_engine_kw` — set once at
    construction, never mutated — for probe sizing)."""

    def __init__(self, fleet: "ServingFleet", index: int, incarnation: int,
                 slo: Optional[str], engine_kw: dict,
                 tier: Optional[str] = None, params=None,
                 weights_version: Optional[int] = None):
        self.index = index
        self.incarnation = incarnation
        self.slo = slo
        self.tier = tier
        # weight snapshot (ISSUE 11): the params + version this
        # incarnation serves, FIXED at construction — a rolling weight
        # swap never mutates a live replica, it replaces it (fresh
        # incarnation built against the fleet's new current weights),
        # so every token is attributable to exactly one version
        self.params = params
        self.weights_version = weights_version
        self.name = "r%d" % index
        self._fleet = fleet
        self._engine_kw = engine_kw
        self.engine: Optional[ServingEngine] = None  # guarded-by: replica
        self._serving: Dict[int, Any] = {}           # guarded-by: replica
        self._reported: Dict[int, int] = {}          # guarded-by: replica
        # batch-lane (zoo) jobs waiting their turn: at most ONE runs
        # per scheduler handshake, interleaved with engine steps
        self._batch_q: collections.deque = collections.deque()  # guarded-by: replica
        self._pool_rev = (0, 0)                      # guarded-by: replica
        self.thread = threading.Thread(
            target=self._loop, name="fleet-%s-i%d" % (self.name, incarnation),
            daemon=True)

    def start(self):
        self.thread.start()
        return self

    def _idle(self) -> bool:  # thread: replica
        e = self.engine
        return (not self._serving and not self._batch_q
                and e is not None
                and not e.live_slots and not e.queue_depth
                and not e.prefilling_slots)

    def _pool_summary(self):  # thread: replica
        """Rebuild the routing summary only when the pool changed (the
        trie is thread-confined here; the summary set handed to the
        fleet is immutable)."""
        pc = self.engine.prefix_cache
        if pc is None:
            return None
        rev = (pc.inserted_blocks, pc.evictions)
        if rev == self._pool_rev:
            return None
        self._pool_rev = rev
        return pc.summary()

    def _loop(self):  # thread: replica
        fleet = self._fleet
        hook = fleet._hook
        if hook is not None:
            hook.thread_started(
                "replica", "%s.i%d" % (self.name, self.incarnation))
        try:
            self._loop_body(fleet, hook)
        finally:
            if hook is not None:
                hook.thread_exiting()

    def _loop_body(self, fleet, hook):  # thread: replica
        try:
            params = self.params if self.params is not None \
                else fleet._params
            self.engine = fleet._engine_factory(
                params, fleet._cfg, replica_id=self.name,
                scheduler_hook=hook,
                weights_version=self.weights_version,
                **self._engine_kw)
            completed: List[Tuple[int, List[int], str, Optional[dict]]] = []
            progress: List[Tuple[int, List[int]]] = []
            while True:
                if hook is not None:
                    hook.yield_point("replica:%s:sync" % self.name)
                cmd, work, cancels, resync = fleet._sync(
                    self, completed, progress, idle=self._idle(),
                    summary=self._pool_summary(), stats=self._stats())
                completed = []
                progress = []
                if cmd == "stop":
                    return
                if resync:
                    # post-restore refresh: the fleet dropped this
                    # replica's routing summary at demotion but the
                    # pool (warm, unchanged) would never re-trigger
                    # the revision cache — invalidate it so the next
                    # handshake carries the full summary again
                    self._pool_rev = (-1, -1)
                for rid in cancels:
                    # work hedged away from this replica (demotion):
                    # stop spending steps on it; the journal fence
                    # already refuses anything it might still report
                    sh = self._serving.pop(rid, None)
                    if sh is not None:
                        self._reported.pop(rid, None)
                        self.engine.cancel(sh.rid)
                    if self._batch_q:
                        # a hedged-away batch job: drop our copy — the
                        # survivor re-runs the callable (idempotent
                        # zoo inference; the dedupe fence keeps one
                        # verdict even if both finish)
                        self._batch_q = collections.deque(
                            bh for bh in self._batch_q
                            if bh.rid != rid)
                for h in work:
                    if h.batch_fn is not None:
                        # batch-lane (zoo) job: runs between engine
                        # steps below, one per handshake
                        self._batch_q.append(h)
                        continue
                    try:
                        subkw = dict(
                            temperature=h.spec["temperature"],
                            eos_id=h.spec["eos_id"], seed=h.spec["seed"],
                            publish_len=h.spec["publish_len"],
                            deadline_at=h.deadline_at,
                            resume_tokens=h.resume or None)
                        if h.handoff_package is not None:
                            # durable-KV handoff (ISSUE 16): the block
                            # package the fleet fetched from the store
                            # at re-route — consumed once, here
                            subkw["handoff"] = h.handoff_package
                            h.handoff_package = None
                        if h.spec.get("adapter") is not None:
                            # keyword passed only when set: scripted
                            # engines without the adapter surface keep
                            # working (sched_explore.ScriptEngine)
                            subkw["adapter"] = h.spec["adapter"]
                        sh = self.engine.submit(
                            h.prompt, h.spec["max_new_tokens"], **subkw)
                    except ValueError as exc:
                        # a malformed request must fail ITSELF, not
                        # crash-loop the replica through failover
                        fleet._reject(h.rid, exc, rep=self)
                        continue
                    self._serving[h.rid] = sh
                    self._reported[h.rid] = 0
                if not self._idle():
                    if hook is not None:
                        hook.yield_point("replica:%s:step" % self.name)
                    self.engine.step()
                if self._batch_q:
                    # ONE zoo micro-batch per handshake, after the
                    # engine step: batch throughput rides the same
                    # scheduler cadence as prefill chunks do, so it
                    # can never starve the batched decode (the
                    # Sarathi interleave rule across workload kinds)
                    bh = self._batch_q.popleft()
                    if bh.deadline_at is not None \
                            and time.monotonic() >= bh.deadline_at:
                        # the deadline died waiting behind the engine:
                        # the expiry verdict, not a late 'done' — the
                        # every-queue-hop rule batch jobs get too
                        completed.append((bh.rid, [], "expired", None))
                    else:
                        try:
                            bh.batch_result = bh.batch_fn()
                        except Exception as exc:
                            # the JOB failed, not the replica: a
                            # terminal rejected verdict for this rid
                            # alone — fenced (rep=self), so a stale
                            # holder's local failure cannot reject a
                            # rid hedged to a healthy survivor
                            fleet._reject(bh.rid, exc, rep=self)
                        else:
                            completed.append((bh.rid, [], "done", None))
                for rid, sh in list(self._serving.items()):
                    # batched incremental progress: every token emitted
                    # since the last handshake rides ONE journal record
                    n = len(sh.tokens)
                    if n > self._reported[rid]:
                        progress.append(
                            (rid, list(sh.tokens[self._reported[rid]:n])))
                        self._reported[rid] = n
                    if sh.done:
                        reason = ("expired"
                                  if sh.finish_reason == "expired"
                                  else "done")
                        # handoff outcome side-band (ISSUE 16): what
                        # became of an imported block package — read
                        # via getattr so scripted engines without the
                        # surface keep working (_accept defaults the
                        # outcome for them when the assign shipped one)
                        outcome = getattr(sh, "handoff_outcome", None)
                        completed.append(
                            (rid, list(sh.tokens), reason, outcome))
                        del self._serving[rid]
                        del self._reported[rid]
        except Exception as exc:  # crash -> failover (incl. _KillDrill)
            if self.engine is not None:
                self.engine.abort(exc)
            self._fleet._on_crash(self, exc)

    def _stats(self) -> Optional[dict]:  # thread: replica
        e = self.engine
        if e is None:
            return None
        m = e.metrics
        out = {
            "tokens_out": m.tokens_out,
            "decode_steps": m.decode_steps,
            "prefills": m.prefills,
            "prefill_tokens_computed": m.prefill_tokens_computed,
            # ISSUE 7 block-pool / spec counters: the cumulative ones
            # fold into the fleet's _stats_base on replica death like
            # every other int here; kv_blocks_in_use is a GAUGE (a dead
            # replica's pool is gone), summed over LIVE snapshots only
            "kv_blocks_in_use": m.kv_blocks_in_use,
            "kv_blocks_freed_at_retire": m.kv_blocks_freed_at_retire,
            "kv_tail_blocks_freed": m.kv_tail_blocks_freed,
            "cow_blocks": m.cow_blocks,
            "spec_drafted": m.spec_drafted,
            "spec_accepted": m.spec_accepted,
            "expired": m.expired,
            "resumed_requests": m.resumed_requests,
            "resume_tokens_reused": m.resume_tokens_reused,
            # health-score inputs (ISSUE 8): step-latency EWMA is a
            # GAUGE (never folded into _stats_base); busy says whether
            # a progress watermark is even expected of this replica
            "step_ewma_s": m.step_ewma_s,
            "busy": bool(self._serving) or bool(e.live_slots)
            or bool(e.queue_depth) or bool(e.prefilling_slots),
            # construction gauges the fleet's per-replica rows surface:
            # which paged kernel this incarnation's steps attend with
            # (ISSUE 13 — previously only read, never exported, so the
            # row was always None) and the ISSUE 14 storage dtypes
            # (getattr: scripted metric surfaces predate them)
            "paged_kernel": getattr(m, "paged_kernel", None),
            "kv_quant": getattr(m, "kv_quant", None),
            "weight_quant": getattr(m, "weight_quant", None),
        }
        if e.prefix_cache is not None:
            out["prefix_hits"] = e.prefix_cache.hits
            out["prefix_misses"] = e.prefix_cache.misses
            out["prefix_tokens_saved"] = e.prefix_cache.tokens_saved
        # getattr: scripted metric surfaces (sched_explore) predate it
        bf = getattr(m, "block_fp", None)
        if bf is not None:
            # ISSUE 15 fingerprint counters: cumulative ints, folded
            # into _stats_base on replica death/retire like the rest
            out["fp_committed"] = bf.committed
            out["fp_verified"] = bf.verified
            out["fp_mismatches"] = bf.mismatches
        if getattr(m, "kv_store", None) is not None:
            # ISSUE 16 durable-KV counters: cumulative ints, folded
            # into _stats_base on replica death/retire like the rest
            out["tokens_recomputed_at_migration"] = \
                m.tokens_recomputed_at_migration
            out["handoff_imports"] = m.handoff_imports
            out["handoff_blocks_imported"] = m.handoff_blocks_imported
            out["handoff_tokens_imported"] = m.handoff_tokens_imported
            out["handoff_fallbacks"] = m.handoff_fallbacks
            out["store_spilled_blocks"] = m.store_spilled_blocks
            out["store_warm_blocks"] = m.store_warm_blocks
            out["store_quarantined"] = m.store_quarantined
        ap = getattr(e.metrics, "adapter_pool", None)
        if ap is not None:
            # cumulative adapter-pool counters (ISSUE 12): fold into
            # _stats_base on replica death/retire like the rest
            out["adapter_hits"] = ap.hits
            out["adapter_misses"] = ap.misses
            out["adapter_evictions"] = ap.evictions
            out["adapter_uploads"] = ap.uploads
        return out


class ServingFleet(object):
    """Front door over N `ServingEngine` replica threads. Knobs:

      n_replicas           engine replicas (threads; one engine each)
      journal_path         durable request journal (None = in-memory
                           mirror only — failover still exact, but a
                           whole-process crash loses the table); an
                           existing file is replayed, so a restarted
                           front door resumes rids past its history
      journal_fsync        fsync every journal record (OS-crash
                           durability) instead of flush-only
                           (process-crash durability, the default —
                           fsync costs per-request disk latency)
      max_pending          fleet-wide bound on OPEN requests; past it
                           submit() raises FleetSaturated (load-shed)
      heartbeat_timeout_s  replica declared dead after this long
                           without a scheduler-loop heartbeat; size it
                           a few times the worst single engine step
                           (first-compile included!) or a busy replica
                           reads as dead (README sizing rule)
      affinity             prefix-affinity routing on/off (off =
                           least-loaded only)
      replica_slo          per-replica SLO class name list
                           ("interactive"/"batch"; None entry = serves
                           any class); default: all wildcard
      slo_classes          class -> engine-kw overrides (default maps
                           interactive/batch onto max_prefills_per_step
                           1/None)
      engine_kw            base kwargs for every replica engine
                           (max_slots, prefill_chunk_tokens,
                           prefix_cache_tokens, ...)
      engine_kw_for        optional fn(index) -> extra kwargs for one
                           replica (drills inject per-replica
                           FaultInjectors through this)
      auto_refill          monitor replaces DEAD replicas with a fresh
                           incarnation automatically (default False:
                           drills and operators call refill())
      journal_compact_every
                           rewrite the journal file down to its open
                           set once it holds this many records
                           (default 4096; None = never). Per-token
                           progress records make an append-only
                           journal grow with TRAFFIC, not in-flight
                           work — without compaction a long-lived
                           fleet fills the disk at decode rate
      slow_replica_factor  GRAY-failure detection (ISSUE 8): a BUSY
                           replica whose step-latency EWMA exceeds
                           this multiple of the live-fleet median is
                           slow; sustained past slow_min_duration_s it
                           is DEMOTED — drained of work (hedged to
                           survivors with token-level resume), kept
                           warm, probed, and restored when healthy.
                           None (default) disables detection: enable
                           it only on a WARMED fleet, or set
                           slow_min_duration_s above the first-compile
                           latency (README sizing rule) — a replica
                           compiling its first buckets is slow for
                           honest reasons
      slow_min_duration_s  hysteresis: the slow condition must hold
                           continuously this long before demotion (one
                           GC pause must not flap a healthy replica)
      probe_interval_s     cadence of health probes (tiny internal
                           generate requests) sent to a DEMOTED
                           replica; a probe completed with a healthy
                           step EWMA restores it — same incarnation,
                           warm engine and prefix pool
      probe_ok_needed      consecutive healthy probes required to
                           restore (restore-side hysteresis)
      replica_tier         per-SLOT disaggregation tier list
                           ("prefill"/"decode"/None; length
                           max_replicas). Fresh admissions route to
                           prefill-tier replicas and MIGRATE to a
                           decode-tier replica at first token via the
                           journaled resume path (ISSUE 11); None
                           entries serve both phases. Default: no
                           tiers (every replica does both)
      tier_classes         tier -> engine-kw overrides (default maps
                           prefill/decode onto max_prefills_per_step
                           None/1)
      min_replicas /       autoscaler bounds (ISSUE 11): the fleet
      max_replicas         holds max_replicas SLOTS; slots beyond
                           n_replicas start RETIRED (capacity held
                           back). Defaults: both = n_replicas (scaling
                           off). The scaler never retires below
                           min_replicas live replicas
      scale_up_open_per_replica
                           spawn a replica when open requests exceed
                           this many per live replica (queue-depth
                           pressure)
      scale_up_headroom_s  also spawn when any open request's deadline
                           headroom drops below this while requests
                           outnumber live replicas (None = off);
                           clamped up to one decode-window's wall time
                           on a decode_window=K fleet (ISSUE 19:
                           deadlines enforce at window granularity)
      scale_down_idle_s    retire a replica only after low load (open
                           requests < live replicas) holds this long
                           (sustained-idle hysteresis)
      scale_cooldown_s     ONE cool-down gate for both directions: at
                           most one scale operation per window, so a
                           burst cannot flap the fleet
      ckpt_dir             weight-PUBLISH dir `roll_weights()` reads
                           candidate weight sets from: step dirs
                           written by `save_weights(params, dir,
                           step)` (NOT a raw training save_checkpoint
                           scope — its entry names differ and the
                           load refuses them loudly). The training
                           side publishes here next to its own
                           checkpoints; a `sentinel.json` in this dir
                           (written or copied from the training run)
                           gives no-argument roll_weights() its
                           known-good default. None = rollout only
                           via explicit params=
      rollout_policy       what happens to in-flight requests when
                           their replica is swapped: "finish" (default
                           — the drain waits; tokens never mix
                           versions) or "migrate" (hedged to survivors
                           from the journal with token-level resume —
                           faster swap; the completion records the
                           final holder's version)
      weights_version      version tag of the CONSTRUCTION params
                           (default 0); roll_weights bumps it to the
                           checkpoint step it rolled to
      tenants              a `tenancy.TenantRegistry` turns on the
                           multi-tenant front door (ISSUE 12):
                           submit(tenant=) becomes required, each
                           submit is charged against the tenant's
                           token bucket (TenantQuotaExceeded — never
                           journaled, checked before FleetSaturated),
                           routing goes through a weighted fair queue
                           (one tenant's burst cannot starve
                           another's share), assign/done journal
                           records carry the typed tenant side-band,
                           and submit_batch() admits model-zoo jobs
                           into the same scheduler
      wfq_window           dispatch-window cap for the fair queue:
                           at most this many requests sit in replica
                           inboxes/engines at once, the rest wait in
                           WFQ order (None = live replicas x the
                           engine's max_slots). Smaller = fairer
                           under contention, larger = deeper engine
                           queues
      canary_interval_s    known-answer canary cadence (ISSUE 15):
                           every LIVE replica gets a tiny greedy
                           canary request on this period, judged
                           against a GOLDEN trace computed once per
                           weights_version (construction + every
                           roll_weights commit); a mismatch is an
                           integrity trip — quarantine + taint-aware
                           resume, exactly-once per incarnation. A
                           clean canary advances the replica's TAINT
                           BASE: a later trip taints (and re-decodes)
                           only tokens journaled past it. None
                           (default) = canaries off
      canary_max_new       golden-trace length in tokens (default 4);
                           see the README cadence-vs-step-latency
                           sizing rule
      canary_prompt /      explicit canary prompt / golden tokens —
      canary_golden        golden is REQUIRED for scripted engine
                           factories and quantized fleets (their
                           outputs are not token-identical to
                           generate(), so the fleet refuses to derive
                           the known answer itself)
      kv_store /           durable KV tier (ISSUE 16): pass a
      kv_store_dir /       KVBlockStore, or set kv_store_dir (spill
      kv_store_bytes       directory; store.jsonl under it) and/or
                           kv_store_bytes (host-RAM byte budget,
                           leaf-first eviction) and the fleet builds
                           ONE store shared by every replica: closed
                           blocks spill write-through at publish,
                           restarted/autoscaled replicas warm their
                           tries from it, and the router credits what
                           a replica can cheaply RESTORE, not just
                           what is resident. Default: no store (the
                           pre-PR-16 fleet exactly)
      handoff              ship finished-prefix block packages at
                           migration/failover re-routes (default True;
                           needs a store). The clean path re-prefills
                           ZERO closed-block tokens; mismatch/absence
                           falls back to re-prefill, counted, never
                           wrong
    """

    def __init__(self, params, cfg, n_replicas=2, journal_path=None,
                 journal_fsync=False, max_pending=64,
                 heartbeat_timeout_s=30.0, monitor_interval_s=None,
                 affinity=True, replica_slo=None, slo_classes=None,
                 engine_kw=None, engine_kw_for=None, auto_refill=False,
                 journal_compact_every=4096, slow_replica_factor=None,
                 slow_min_duration_s=0.5, probe_interval_s=0.25,
                 probe_ok_needed=1, scheduler_hook=None,
                 engine_factory=None, replica_tier=None,
                 tier_classes=None, min_replicas=None, max_replicas=None,
                 scale_up_open_per_replica=4, scale_up_headroom_s=None,
                 scale_down_idle_s=2.0, scale_cooldown_s=1.0,
                 ckpt_dir=None, rollout_policy="finish",
                 weights_version=0, tenants=None, wfq_window=None,
                 canary_interval_s=None, canary_max_new=4,
                 canary_prompt=None, canary_golden=None,
                 kv_store=None, kv_store_dir=None, kv_store_bytes=None,
                 handoff=True):
        if int(n_replicas) < 1:
            raise ValueError("n_replicas must be >= 1")
        if int(max_pending) < 1:
            raise ValueError("max_pending must be >= 1")
        self._params = params  # guarded-by: _cond (swapped by rollout)
        self._cfg = cfg
        # deterministic-exploration seam (ISSUE 9): the hook is called
        # at every thread-handoff point (SchedulerHook contract above);
        # engine_factory lets the explorer substitute a host-only
        # scripted engine so interleavings, not compiles, dominate
        self._hook: Optional[SchedulerHook] = scheduler_hook
        self._engine_factory = (engine_factory if engine_factory
                                is not None else ServingEngine)
        self.n_replicas = int(n_replicas)
        # elastic bounds (ISSUE 11): the fleet owns max_replicas SLOTS;
        # n_replicas of them start live, the rest start RETIRED. All
        # per-slot lists below are sized max_replicas once — the
        # autoscaler changes STATES, never list lengths
        self.min_replicas = (self.n_replicas if min_replicas is None
                             else int(min_replicas))
        self.max_replicas = (self.n_replicas if max_replicas is None
                             else int(max_replicas))
        if not (1 <= self.min_replicas <= self.n_replicas
                <= self.max_replicas):
            raise ValueError(
                "need 1 <= min_replicas (%d) <= n_replicas (%d) <= "
                "max_replicas (%d)" % (self.min_replicas,
                                       self.n_replicas,
                                       self.max_replicas))
        self.scale_up_open_per_replica = int(scale_up_open_per_replica)
        if self.scale_up_open_per_replica < 1:
            raise ValueError("scale_up_open_per_replica must be >= 1")
        self.scale_up_headroom_s = (
            None if scale_up_headroom_s is None
            else float(scale_up_headroom_s))
        self.scale_down_idle_s = float(scale_down_idle_s)
        self.scale_cooldown_s = float(scale_cooldown_s)
        if rollout_policy not in ("finish", "migrate"):
            raise ValueError(
                "rollout_policy must be 'finish' or 'migrate', got %r"
                % (rollout_policy,))
        self.rollout_policy = rollout_policy
        self.ckpt_dir = ckpt_dir
        self.max_pending = int(max_pending)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.affinity = bool(affinity)
        self.auto_refill = bool(auto_refill)
        if slow_replica_factor is not None \
                and float(slow_replica_factor) <= 1.0:
            raise ValueError("slow_replica_factor must be > 1 or None")
        self.slow_replica_factor = (
            None if slow_replica_factor is None
            else float(slow_replica_factor))
        self.slow_min_duration_s = float(slow_min_duration_s)
        self.probe_interval_s = float(probe_interval_s)
        self.probe_ok_needed = int(probe_ok_needed)
        self.slo_classes = dict(_DEFAULT_SLO_CLASSES)
        if slo_classes:
            self.slo_classes.update(slo_classes)
        if replica_slo is not None \
                and len(replica_slo) != self.max_replicas:
            raise ValueError(
                "replica_slo must name a class per SLOT "
                "(max_replicas=%d)" % self.max_replicas)
        self._replica_slo = list(replica_slo
                                 or [None] * self.max_replicas)
        for c in self._replica_slo:
            if c is not None and c not in self.slo_classes:
                raise ValueError("unknown SLO class %r" % c)
        self.tier_classes = dict(_DEFAULT_TIER_CLASSES)
        if tier_classes:
            self.tier_classes.update(tier_classes)
        if replica_tier is not None \
                and len(replica_tier) != self.max_replicas:
            raise ValueError(
                "replica_tier must name a tier per SLOT "
                "(max_replicas=%d)" % self.max_replicas)
        self._replica_tier = list(replica_tier
                                  or [None] * self.max_replicas)
        for t in self._replica_tier:
            if t is not None and t not in self.tier_classes:
                raise ValueError("unknown tier %r" % t)
        # migration only makes sense when both phases have a home
        self._tiered = any(t is not None for t in self._replica_tier)
        self._engine_kw = dict(engine_kw or {})
        self._engine_kw_for = engine_kw_for
        # ONE block granularity: the engine's paged KV pool and the
        # prefix trie share it (kv_block_tokens is the ISSUE 7 name,
        # prefix_block_tokens the pre-paging alias the engine accepts).
        # `is None` defaulting, like the engine: an explicit invalid 0
        # must raise HERE, not as a replica-thread crash loop later
        # block_tokens/_pool_blocks are the BASE-kw limits, used for
        # the submit() precheck: a request whose worst case exceeds a
        # WHOLE replica pool can never be admitted anywhere — fail in
        # the caller (the engine's own rule; a merely saturated pool
        # queues instead)
        _, self.block_tokens, self._pool_blocks = self._limits_for(
            self._engine_kw)
        # durable KV tier (ISSUE 16): ONE store shared by every
        # replica (it carries its own lock — the RequestJournal
        # discipline), constructed only when explicitly requested so
        # the default fleet is byte-identical to the pre-PR-16 one.
        # Injected into the engine base kw: every replica spills its
        # closing blocks write-through and warms its trie from the
        # store at spawn (restart, failover incarnation, autoscale).
        self.handoff = bool(handoff)
        self._kv_store_owned = False
        if kv_store is None and (kv_store_dir is not None
                                 or kv_store_bytes is not None):
            kv_store = KVBlockStore(
                byte_budget=kv_store_bytes, dir=kv_store_dir,
                block_tokens=self.block_tokens,
                fault_injector=self._engine_kw.get("fault_injector"))
            self._kv_store_owned = True
        self.kv_store = kv_store
        if kv_store is not None:
            if int(kv_store.block_tokens) != int(self.block_tokens):
                raise ValueError(
                    "kv_store block_tokens (%d) != fleet block "
                    "granularity (%d) — one store, one geometry"
                    % (int(kv_store.block_tokens),
                       int(self.block_tokens)))
            if not self._engine_kw.get("prefix_cache_tokens"):
                raise ValueError(
                    "kv_store needs the prefix cache (set "
                    "prefix_cache_tokens in engine_kw): blocks spill "
                    "at trie publish and warm-start restores into "
                    "the trie")
            self._engine_kw["kv_store"] = kv_store
            self._engine_kw["kv_store_warm"] = True
        # ONE storage dtype (ISSUE 14): failover, token-level resume,
        # and prefix-summary affinity all assume every replica decodes
        # the same numerics — a request hedged from an int8 replica to
        # an f32 one would change models mid-sequence. The base kw's
        # quant settings are the fleet's; per-replica overrides that
        # differ are refused at spawn (_make_replica), like the block
        # granularity under affinity but unconditionally.
        self.kv_quant = str(self._engine_kw.get("kv_quant") or "none")
        self.weight_quant = self._engine_kw.get("weight_quant")
        # chain keys only pay off when there is a pool to match: with
        # no base prefix_cache_tokens every summary stays empty, so
        # skip the per-submit O(T0) crc work entirely
        self._chain_prompts = bool(affinity) and bool(
            self._engine_kw.get("prefix_cache_tokens"))
        # multi-tenant front door (ISSUE 12): a TenantRegistry turns
        # on (a) token-bucket quota admission — a submit past the
        # tenant's bucket raises TenantQuotaExceeded, never journaled,
        # like FleetSaturated — and (b) weighted fair queueing: when
        # every replica's dispatch window is full, requests wait in a
        # per-fleet WFQ and drain in virtual-finish-tag order at every
        # scheduler handshake, so one tenant's burst cannot starve
        # another's share. `wfq_window` caps requests dispatched into
        # replica inboxes/engines at once (None = live replicas x the
        # engine's max_slots — enough to keep every slot fed while the
        # excess queues fairly at the front door).
        self._tenants = tenants
        self._wfq: Optional[WFQueue] = (
            WFQueue() if tenants is not None else None)
        if wfq_window is not None and int(wfq_window) < 1:
            raise ValueError("wfq_window must be >= 1 or None")
        self._wfq_window = (None if wfq_window is None
                            else int(wfq_window))
        self._slots_per_replica = int(
            self._engine_kw.get("max_slots") or 8)
        # known-answer canaries (ISSUE 15): periodic canary requests on
        # LIVE replicas (PR 8's probe machinery, extended past
        # demoted-only), judged against a GOLDEN token trace computed
        # once per weights_version. A mismatch is an integrity trip:
        # quarantine + taint-aware resume, not demotion.
        self.canary_interval_s = (None if canary_interval_s is None
                                  else float(canary_interval_s))
        self.canary_max_new = int(canary_max_new)
        self._canary_prompt = tuple(
            int(t) for t in (canary_prompt if canary_prompt is not None
                             else CANARY_PROMPT))
        self._canary_golden: Dict[Any, List[int]] = {}  # guarded-by: _cond
        self._canary_golden_default: Optional[List[int]] = None
        self._canary_auto = False
        if self.canary_interval_s is not None:
            if self.canary_interval_s <= 0.0:
                raise ValueError("canary_interval_s must be > 0 or None")
            if self.canary_max_new < 1:
                raise ValueError("canary_max_new must be >= 1")
            if canary_golden is not None:
                # explicit golden: scripted engines (sched_explore) and
                # quantized fleets supply their own known answer
                self._canary_golden_default = [int(t)
                                               for t in canary_golden]
            else:
                if self._engine_factory is not ServingEngine:
                    raise ValueError(
                        "canaries on a custom engine_factory need an "
                        "explicit canary_golden= (the fleet cannot "
                        "derive a golden trace for a scripted engine)")
                if self.kv_quant != "none" or self.weight_quant is not None:
                    raise ValueError(
                        "canaries on a quantized fleet need an explicit "
                        "canary_golden=: quantized engine outputs are "
                        "not token-identical to generate(), so the "
                        "fleet cannot compute the golden trace itself")
                self._canary_auto = True
                self._canary_golden[int(weights_version)] = golden_trace(
                    params, cfg, self._canary_prompt,
                    self.canary_max_new)

        # ONE lock for all fleet scheduler state (the condition owns
        # it); replica + monitor threads mutate ONLY under it
        self._cond = threading.Condition()
        # serializes _flush_journal's swap+write as one unit (always
        # acquired BEFORE _cond, never while holding it): without it
        # two flushers could write their batches to the FILE in the
        # opposite order they were swapped, and per-rid progress
        # records would land inverted on disk — a restart would
        # recover a scrambled resume prefix
        self._flush_lock = threading.Lock()
        self._journal = RequestJournal(journal_path, fsync=journal_fsync,
                                       compact_every=journal_compact_every)
        self._replicas: List[_Replica] = []            # guarded-by: _cond
        self._state: List[str] = []                    # guarded-by: _cond
        self._beats: List[float] = []                  # guarded-by: _cond
        self._kill: List[bool] = []                    # guarded-by: _cond
        self._inbox: List[collections.deque] = []      # guarded-by: _cond
        self._in_flight: List[Dict[int, FleetHandle]] = []  # guarded-by: _cond
        self._summaries: List[Set[int]] = []           # guarded-by: _cond
        self._rep_stats: List[Optional[dict]] = []     # guarded-by: _cond
        # dead incarnations' last stats snapshots fold in here so
        # fleet totals stay monotonic across failover/refill
        self._stats_base: Dict[str, int] = {}          # guarded-by: _cond
        self._spawned: List[float] = []                # guarded-by: _cond
        self._rapid: List[int] = []                    # guarded-by: _cond
        self._refill_at: List[float] = []              # guarded-by: _cond
        self._incarnations: List[int] = []             # guarded-by: _cond
        # gray-failure health tracking (ISSUE 8): when the slow
        # condition first held (None = healthy), per-replica progress
        # watermark samples (monotonic t, tokens_out), pending cancels
        # (work hedged away a demoted replica must stop), outstanding
        # probe handle + schedule + consecutive-good count
        self._slow_since: List[Optional[float]] = []   # guarded-by: _cond
        self._watermark: List[Optional[Tuple[float, int]]] = []  # guarded-by: _cond
        self._rate: List[Optional[float]] = []         # guarded-by: _cond
        self._stall_since: List[Optional[float]] = []  # guarded-by: _cond
        self._cancels: List[Set[int]] = []             # guarded-by: _cond
        self._probes: List[Optional[FleetHandle]] = []  # guarded-by: _cond
        self._probe_at: List[float] = []               # guarded-by: _cond
        self._probe_ok: List[int] = []                 # guarded-by: _cond
        # restore-time summary refresh: demotion cleared the routing
        # summary, and the replica's revision cache would otherwise
        # never resend an UNCHANGED (warm!) pool after restore
        self._want_summary: List[bool] = []            # guarded-by: _cond
        # serving integrity (ISSUE 15): outstanding canary handle +
        # schedule per slot, the TAINT BASE — per in-flight rid, the
        # resume length at ASSIGNMENT (tokens earlier holders already
        # vouched for) — and the CANARY MARK, the journaled-progress
        # length the last clean canary vouched for. A trip taints
        # [start, now) where start is the canary mark ONLY for
        # canary-kind trips: a canary exercises the engine-global
        # compute path (the garble class), so its clean verdict can
        # vouch for every token the engine emitted — but it never
        # attends through another request's KV blocks, so a
        # fingerprint/trap/spike trip (block-level corruption the
        # canary cannot see) must taint from the assignment base
        self._canaries: List[Optional[FleetHandle]] = []  # guarded-by: _cond
        self._canary_at: List[float] = []              # guarded-by: _cond
        self._taint_base: List[Dict[int, int]] = []    # guarded-by: _cond
        self._canary_mark: List[Dict[int, int]] = []   # guarded-by: _cond
        # elastic lifecycle (ISSUE 11): drain-then-retire marker the
        # scaler sets and the replica's own handshake consumes, plus
        # the scaler's shared cool-down gate and sustained-low-load
        # clock, and the rollout mutual-exclusion latch
        self._retire_flag: List[bool] = []             # guarded-by: _cond
        self._scale_gate_at = 0.0                      # guarded-by: _cond
        self._low_load_since: Optional[float] = None   # guarded-by: _cond
        self._rollout = False                          # guarded-by: _cond
        self._weights_version = int(weights_version)   # guarded-by: _cond
        self._next_probe_rid = -1                      # guarded-by: _cond
        self._handles: Dict[int, FleetHandle] = {}     # guarded-by: _cond
        self._open: Set[int] = set()                   # guarded-by: _cond
        self._done_rids: Set[int] = set()              # guarded-by: _cond
        # client-cancelled rids (ISSUE 18): subset of _done_rids, so a
        # holder's late completion for an abandoned request is counted
        # as the CANCEL's expected tail, not a duplicate answer — the
        # kill-drill duplicates==0 bar stays meaningful under
        # disconnect storms
        self._cancelled_rids: Set[int] = set()         # guarded-by: _cond
        # journal FILE records produced under the lock (mirror updates
        # are synchronous); flushed by _flush_journal() after release
        # so disk latency never stalls handshakes or the monitor.
        # Completion events fire AFTER the flush: a caller observing a
        # result implies its done record is already written
        self._pending_journal: List[dict] = []         # guarded-by: _cond
        self._pending_events: List[FleetHandle] = []   # guarded-by: _cond
        # stream deliveries produced under the lock (ISSUE 18): each
        # entry is (handle, tokens, closing) — fed to the handle's
        # stream buffer by _flush_journal AFTER the records describing
        # those tokens are on disk, the same read-your-writes ordering
        # completion events get
        self._pending_stream: List[
            Tuple[FleetHandle, List[int], bool]] = []  # guarded-by: _cond
        # continue past an existing journal's history: a restarted
        # front door appending to the same file must never reuse a rid
        self._next_rid = self._journal.next_rid()      # guarded-by: _cond
        self._closing = False                          # guarded-by: _cond
        # O(1) counters (the ServingMetrics discipline)
        self.submitted = 0                             # guarded-by: _cond
        self.completed = 0                             # guarded-by: _cond
        self.shed = 0                                  # guarded-by: _cond
        self.rejected = 0                              # guarded-by: _cond
        self.expired = 0                               # guarded-by: _cond
        # deadline dead on arrival: shed-like (never journaled, never
        # counted as submitted) but kept APART from `shed` so overload
        # and client-side lateness stay distinguishable (ISSUE 8 fix)
        self.expired_on_arrival = 0                    # guarded-by: _cond
        # per-tenant quota shed (ISSUE 12): like `shed`, never
        # journaled — but scoped to one tenant's bucket, so overload
        # (FleetSaturated) and quota enforcement stay distinguishable
        self.quota_shed = 0                            # guarded-by: _cond
        self.batch_jobs_completed = 0                  # guarded-by: _cond
        # client cancels (ISSUE 18): terminal verdicts the SUBMITTER
        # asked for (disconnect / cancel frame) — kept apart from
        # every fleet-side verdict so stats()['lost'] stays exact; a
        # holder's late completion for a cancelled rid increments
        # cancel_late_refused, never duplicate_refused
        self.cancelled = 0                             # guarded-by: _cond
        self.cancel_late_refused = 0                   # guarded-by: _cond
        self.resubmitted = 0                           # guarded-by: _cond
        self.failovers = 0                             # guarded-by: _cond
        self.zombie_refused = 0                        # guarded-by: _cond
        self.duplicate_refused = 0                     # guarded-by: _cond
        self.demotions = 0                             # guarded-by: _cond
        self.restores = 0                              # guarded-by: _cond
        self.probes_sent = 0                           # guarded-by: _cond
        self.resumed_requests = 0                      # guarded-by: _cond
        self.resumed_tokens = 0                        # guarded-by: _cond
        # elastic lifecycle counters (ISSUE 11 satellite): fleet-scope
        # monotonic ints — they survive any replica's retirement by
        # construction, unlike per-replica stats (which fold into
        # _stats_base when an incarnation ends)
        self.replicas_spawned = 0                      # guarded-by: _cond
        self.replicas_retired = 0                      # guarded-by: _cond
        self.migrations = 0                            # guarded-by: _cond
        self.rollouts_completed = 0                    # guarded-by: _cond
        self.rollout_aborts = 0                        # guarded-by: _cond
        # serving-integrity counters (ISSUE 15): fleet-scope monotonic
        self.integrity_trips = 0                       # guarded-by: _cond
        # trip KIND attribution ("trap"/"fingerprint"/"spike"/"canary")
        self.integrity_trip_kinds: Dict[str, int] = {}  # guarded-by: _cond
        self.canaries_sent = 0                         # guarded-by: _cond
        self.canaries_ok = 0                           # guarded-by: _cond
        self.canary_mismatches = 0                     # guarded-by: _cond
        self.tainted_tokens = 0                        # guarded-by: _cond
        # durable-KV counters (ISSUE 16): fleet-scope monotonic.
        # handoff_packages = block packages attached at re-route;
        # handoff_fallbacks_defaulted = dones whose holder never
        # reported an import outcome (scripted engines) — the fleet
        # stamps the honest {"imported": 0, "fallback": True} so the
        # J011 fence still closes
        self.handoff_packages = 0                      # guarded-by: _cond
        self.handoff_fallbacks_defaulted = 0           # guarded-by: _cond

        self._idle_wait_s = min(0.02, self.heartbeat_timeout_s / 10.0)
        self._monitor_interval_s = (
            monitor_interval_s if monitor_interval_s is not None
            else max(0.01, min(0.2, self.heartbeat_timeout_s / 5.0)))
        with self._cond:
            for i in range(self.max_replicas):
                self._incarnations.append(1)
                # slots past n_replicas are held-back capacity: they
                # start RETIRED (no thread) until scale-up or refill()
                self._state.append(_LIVE if i < self.n_replicas
                                   else _RETIRED)
                self._beats.append(time.monotonic())
                self._kill.append(False)
                self._inbox.append(collections.deque())
                self._in_flight.append({})
                self._summaries.append(set())
                self._rep_stats.append(None)
                self._spawned.append(time.monotonic())
                self._rapid.append(0)
                self._refill_at.append(0.0)
                self._slow_since.append(None)
                self._watermark.append(None)
                self._rate.append(None)
                self._stall_since.append(None)
                self._cancels.append(set())
                self._probes.append(None)
                self._probe_at.append(0.0)
                self._probe_ok.append(0)
                self._want_summary.append(False)
                self._retire_flag.append(False)
                self._canaries.append(None)
                self._canary_at.append(
                    time.monotonic() + (self.canary_interval_s or 0.0))
                self._taint_base.append({})
                self._canary_mark.append({})
                self._replicas.append(self._make_replica(i, 1))
        for i, r in enumerate(self._replicas):
            if self._state[i] == _LIVE:
                r.start()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="fleet-monitor", daemon=True)
        self._monitor.start()

    # -- construction helpers -------------------------------------------
    def _limits_for(self, kw: dict):
        """Structural admission limits — (max context, block tokens,
        pool blocks) — for one set of composed engine kwargs. The ONE
        derivation of the engine's `is None` defaulting rules: the
        constructor applies it to the base kw for the submit()
        precheck, probe sizing applies it to a replica's PER-REPLICA
        composed kw (an engine_kw_for override with a smaller
        context/pool must shrink the probe too, or that replica fails
        every probe at admission and stays demoted forever)."""
        bt = kw.get("kv_block_tokens")
        if bt is None:
            bt = kw.get("prefix_block_tokens")
        bt = 16 if bt is None else int(bt)
        if bt < 1:
            raise ValueError("kv_block_tokens must be >= 1")
        L = min(int(kw.get("max_len") or self._cfg.max_len),
                int(self._params["pos"].shape[0]))
        pb = kw.get("kv_pool_blocks")
        pb = (int(kw.get("max_slots", 8)) * (-(-L // bt))
              if pb is None else int(pb))
        if pb < 1:
            raise ValueError("kv_pool_blocks must be >= 1")
        return L, bt, pb

    def _make_replica(self, index: int, incarnation: int) -> _Replica:
        kw = dict(self._engine_kw)
        slo = self._replica_slo[index]
        if slo is not None:
            kw.update(self.slo_classes[slo])
        tier = self._replica_tier[index]
        if tier is not None:
            # tier overrides win over the SLO class: disaggregation is
            # a structural role, SLO a per-request preference
            kw.update(self.tier_classes[tier])
        if self._engine_kw_for is not None:
            kw.update(self._engine_kw_for(index) or {})
        rep_bt = kw.get("kv_block_tokens")
        if rep_bt is None:
            rep_bt = kw.get("prefix_block_tokens")
        rep_bt = self.block_tokens if rep_bt is None else int(rep_bt)
        if self.affinity and rep_bt != self.block_tokens:
            # chain keys are computed at the FLEET's block size; a
            # replica caching at a different granularity would never
            # match them and affinity would silently degrade to
            # least-loaded — refuse loudly instead
            raise ValueError(
                "affinity routing requires a uniform block granularity "
                "across replicas (fleet %d, replica %d override %r)"
                % (self.block_tokens, index, rep_bt))
        # mixed-quant fleet: refused loudly (ISSUE 14). Unlike the
        # block-size rule this is unconditional — failover/resume move
        # requests between replicas, and a replica decoding different
        # numerics would silently change a request's model mid-stream
        rep_kvq = str(kw.get("kv_quant") or "none")
        if rep_kvq != self.kv_quant:
            raise ValueError(
                "mixed-quant fleet refused: fleet kv_quant=%r, replica "
                "%d override %r — every replica must store KV in one "
                "dtype (failover/resume move requests between them)"
                % (self.kv_quant, index, rep_kvq))
        rep_wq = kw.get("weight_quant")
        if rep_wq != self.weight_quant:
            raise ValueError(
                "mixed-quant fleet refused: fleet weight_quant=%r, "
                "replica %d override %r"
                % (self.weight_quant, index, rep_wq))
        return _Replica(self, index, incarnation, slo, kw, tier=tier,
                        params=self._params,
                        weights_version=self._weights_version)

    # -- admission -------------------------------------------------------
    def submit(self, prompt, max_new_tokens, temperature=0.0,
               eos_id=None, seed=0, publish_len=None,
               slo=_SLO_UNSET, deadline_s=None,
               resume_tokens=None, tenant=None,
               adapter=None, stream=False,
               conn=None) -> FleetHandle:
        """Journal the request durably, then route it (prefix affinity
        within the SLO class). Raises `FleetSaturated` when
        `max_pending` requests are already open — the shed request is
        NOT journaled, so backpressure never grows the durable table
        either. `deadline_s` is the request's end-to-end latency
        budget: journaled with the spec, enforced at every queue hop
        (admission, routing, prefill chunk, decode), and terminally
        `expired` — a verdict, never a silent hang — the moment it
        cannot be met. A deadline already spent on arrival raises
        `DeadlineExceeded` BEFORE the saturation check (and journals
        nothing), so shed metrics never conflate overload with
        client-side lateness. `resume_tokens` is the FRONT-DOOR
        RESTART half of token-level resume: tokens a previous fleet
        process already emitted for this request (from
        `RequestJournal.recover_progress`); they count against
        `max_new_tokens`, are journaled as a progress record before
        routing (durable across a second crash), prefill-aliased by
        the assignee, and never re-decoded — a prefix that already
        reached its budget or `eos_id` completes straight from the
        journal with zero engine work.

        Multi-tenant fleets (ISSUE 12, `tenants=` set): `tenant` is
        REQUIRED and must be registered; the submit is charged against
        the tenant's token bucket FIRST (a spent bucket raises
        `TenantQuotaExceeded` — never journaled, and checked before
        the `FleetSaturated` shed so one tenant's burst is shed as ITS
        quota verdict, not fleet overload), `adapter` defaults to the
        tenant's registered LoRA adapter (engines need
        `adapter_registry` in `engine_kw`), routing goes through the
        weighted fair queue (dispatch may defer — a no-live-replica
        failure then lands on the handle instead of raising here),
        and the journal's assign/done records carry the typed
        `tenant` side-band.

        `stream=True` (ISSUE 18) arms incremental delivery: the
        handle's `stream()`/`stream_chunks()` iterators yield tokens
        as the journal's batched flushes land them, concatenating
        bit-identically to `result()` across failover/migration.
        `conn` names the wire connection the request arrived on; both
        ride the journal's submit record as the typed wire side-band
        and surface in FleetTimeout's describe context."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.shape[0] < 1:
            raise ValueError("empty prompt")
        if int(max_new_tokens) < 1:
            raise ValueError("max_new_tokens must be >= 1")
        resume = None
        if resume_tokens is not None:
            resume = [int(t) for t in resume_tokens]
            if len(resume) > int(max_new_tokens):
                raise ValueError(
                    "resume_tokens longer than max_new_tokens "
                    "(%d > %d): the prefix cannot have come from this "
                    "request's budget" % (len(resume),
                                          int(max_new_tokens)))
            if not resume:
                resume = None
        # fail fast HERE with the engine's admission rule (including a
        # base engine_kw max_len override): a request that cannot fit
        # must error in the caller, not asynchronously at result()
        L = min(int(self._engine_kw.get("max_len") or self._cfg.max_len),
                int(self._params["pos"].shape[0]))
        if prompt.shape[0] + int(max_new_tokens) > L:
            raise ValueError(
                "request needs T0+max_new <= max_len (%d + %d > %d)"
                % (prompt.shape[0], int(max_new_tokens), L))
        need = -(-(prompt.shape[0] + int(max_new_tokens))
                 // self.block_tokens)
        if need > self._pool_blocks:
            raise ValueError(
                "request worst case (%d blocks) exceeds a whole replica "
                "KV pool (%d blocks of %d tokens)"
                % (need, self._pool_blocks, self.block_tokens))
        if publish_len is not None and publish_len < 0:
            raise ValueError("publish_len must be >= 0 or None")
        if self._tenants is not None:
            if tenant is None:
                raise ValueError(
                    "this fleet is multi-tenant: submit(tenant=...) "
                    "is required (registered: %r)"
                    % self._tenants.names())
            t = self._tenants.get(tenant)  # KeyError on unknown
            if adapter is None:
                adapter = t.adapter  # the tenant's default delta
            if slo is _SLO_UNSET:
                slo = t.slo  # the tenant's default class
        elif tenant is not None:
            raise ValueError(
                "tenant %r named but the fleet has no TenantRegistry "
                "(pass tenants=)" % (tenant,))
        if slo is _SLO_UNSET:
            slo = "interactive"
        if slo is not None and slo not in self.slo_classes:
            raise ValueError("unknown SLO class %r" % slo)
        if adapter is not None \
                and "adapter_registry" not in self._engine_kw:
            raise ValueError(
                "request names adapter %r but the engines have no "
                "adapter pool (put adapter_registry in engine_kw)"
                % (adapter,))
        deadline_at = None
        if deadline_s is not None:
            deadline_at = time.monotonic() + float(deadline_s)
        spec = {
            "prompt": [int(t) for t in prompt],
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature),
            "eos_id": None if eos_id is None else int(eos_id),
            "seed": int(seed),
            "publish_len": None if publish_len is None else int(publish_len),
            "slo": slo,
            # wall-clock pair: a recovered front door recomputes the
            # remaining budget as deadline_s - (now - submit_unix)
            "deadline_s": None if deadline_s is None else float(deadline_s),
            "submit_unix": time.time(),
            # multi-tenant side-band (ISSUE 12): the admitting tenant
            # and the LoRA adapter the engines apply (both None on a
            # single-tenant fleet)
            "tenant": tenant,
            "adapter": adapter,
        }
        with self._cond:
            if self._closing:
                raise RuntimeError("fleet is closed")
            if deadline_s is not None and float(deadline_s) <= 0.0:
                # the deadline died client-side BEFORE the fleet could
                # matter: an `expired` verdict, checked ahead of the
                # saturation shed so overload metrics stay honest —
                # and never journaled (like shed: the durable table
                # only holds requests the fleet accepted)
                self.expired_on_arrival += 1
                raise DeadlineExceeded(
                    "request arrived with its deadline already spent "
                    "(deadline_s=%r)" % deadline_s)
            h = self._admit_open_locked(tenant, prompt, spec, slo,
                                        deadline_at)
            rid = h.rid
            h.streaming = bool(stream)
            h.conn = None if conn is None else str(conn)
            # WFQ service estimate: the request's token footprint, so
            # a tenant's fair share is proportional to TOKENS of work,
            # not request count
            h.cost = float(prompt.shape[0] + int(max_new_tokens))
            if self._chain_prompts:  # keys feed ONLY affinity routing
                h.chain = chain_keys(prompt, self.block_tokens)
        # durable BEFORE routing — and OUTSIDE the fleet lock, so the
        # journal's write+flush never stalls replica handshakes or the
        # monitor behind disk latency
        self._journal.submit(rid, spec, conn=h.conn, stream=h.streaming)
        if resume is not None:
            # the restart prefix rides a progress record ahead of any
            # assignment: a second front-door crash recovers it exactly
            # like tokens journaled the normal way, and lost()/failover
            # concatenate later deltas after it
            self._journal.progress(rid, "__restart__", -1, 0, resume,
                                   conn=h.conn, stream=h.streaming)
        if self._hook is not None:
            # the close()-race window: the request is durably journaled
            # and open, but not yet routed — a concurrent close() must
            # leave it with exactly ONE terminal record
            self._hook.yield_point("submit:commit")
        try:
            with self._cond:
                if self._closing:
                    # close() raced the journal write: it already
                    # failed this handle (it was in _open). Terminal
                    # record, or the journaled rid stays open and
                    # every future recover() resubmits a request
                    # whose caller was told it failed
                    self._reject_locked(rid, "fleet closed")
                    raise RuntimeError("fleet is closed")
                if resume is not None:
                    if self._finished_in_journal(spec, resume):
                        self._complete_from_progress(
                            h, resume, "__restart__", -1)
                        return h
                    h.resume = list(resume)
                    h.emitted = len(resume)
                    # the restart prefix is already journaled: stream
                    # it ahead of the assignee's deltas so a resumed
                    # stream splices token-exactly (same order the
                    # journal mirror concatenates for failover)
                    self._stream_queue_locked(h, list(resume))
                    self.resumed_requests += 1
                    self.resumed_tokens += len(resume)
                if self._wfq is not None:
                    # multi-tenant routing goes through the weighted
                    # fair queue: dispatch now if a replica window is
                    # open, else wait in virtual-finish-tag order
                    self._wfq.push(h.tenant,
                                   self._tenants.get(h.tenant).weight,
                                   h.cost, h)
                    self._dispatch_locked()
                else:
                    self._route(h, exclude=None)
        finally:
            # also on the raises above: the terminal reject record
            # must be on disk before the caller sees the error
            self._flush_journal()
        return h

    def _admit_open_locked(self, tenant, prompt, spec, slo,
                           deadline_at) -> FleetHandle:
        """Shared admission core of submit()/submit_batch() (caller
        holds `_cond`): the ORDER-SENSITIVE quota invariant lives here
        ONCE — quota CHECKED before the fleet-wide saturation shed (a
        bursting tenant is refused on ITS quota, TenantQuotaExceeded,
        like FleetSaturated never journaled — overload metrics and
        per-tenant enforcement cannot blur), but CONSUMED only after
        it (a saturation-shed request must not drain the bucket or
        count as submitted) — then rid allocation and handle
        registration."""
        if self._closing:
            raise RuntimeError("fleet is closed")
        if self._tenants is not None and tenant is not None:
            try:
                self._tenants.check_quota(tenant)
            except TenantQuotaExceeded:
                self.quota_shed += 1
                raise
        if len(self._open) >= self.max_pending:
            self.shed += 1
            raise FleetSaturated(
                "fleet saturated: %d open requests (max_pending=%d)"
                % (len(self._open), self.max_pending))
        if self._tenants is not None and tenant is not None:
            self._tenants.consume(tenant)
        rid = self._next_rid
        self._next_rid += 1
        h = FleetHandle(rid, prompt, spec, slo, fleet=self,
                        deadline_at=deadline_at)
        h.tenant = tenant
        self._handles[rid] = h
        self._open.add(rid)
        self.submitted += 1
        return h

    def submit_batch(self, fn, tenant: str, cost: float = 1.0,
                     description: str = "batch", deadline_s=None,
                     slo=_SLO_UNSET) -> FleetHandle:
        """Admit one BATCH-LANE job (ISSUE 12): a host callable — e.g.
        one image/CTR model-zoo micro-batch through the existing
        `fluid.Executor` path (`tenancy.executor_batch_fn`) — that
        shares the continuous-batching scheduler with LM work. The job
        rides the SAME admission as every request: the tenant's quota
        bucket (TenantQuotaExceeded, never journaled), the weighted
        fair queue (`cost` is its service estimate in the same token
        currency as LM requests), the journal (assign/done with the
        typed tenant side-band; the spec records kind="batch" — a
        restarted front door recovers the rid but cannot rebuild the
        callable, so batch jobs recovered from a journal are for the
        CALLER to resubmit), and failover (a replica dying mid-lane
        resubmits the job to a survivor; a job hedged away from a
        demoted replica may execute twice — zoo inference is
        idempotent, the dedupe fence keeps exactly one verdict). A
        replica runs at most ONE batch job per scheduler handshake,
        interleaved with its engine's decode steps, so zoo throughput
        never starves decode latency. The result lands on
        `handle.batch_result`; `handle.result()` returns an empty
        token array once done."""
        if self._tenants is None:
            raise ValueError(
                "submit_batch needs a multi-tenant fleet (tenants=)")
        if not callable(fn):
            raise ValueError("submit_batch needs a callable job")
        t = self._tenants.get(tenant)
        if slo is _SLO_UNSET:
            # same sentinel as submit(): the tenant default applies
            # only when the caller said NOTHING — an explicit slo=None
            # stays the any-replica wildcard
            slo = t.slo
        if slo is not None and slo not in self.slo_classes:
            raise ValueError("unknown SLO class %r" % slo)
        deadline_at = None
        if deadline_s is not None:
            deadline_at = time.monotonic() + float(deadline_s)
        spec = {
            "kind": "batch", "description": str(description),
            "max_new_tokens": 0, "temperature": 0.0, "eos_id": None,
            "seed": 0, "publish_len": None, "slo": slo,
            "deadline_s": (None if deadline_s is None
                           else float(deadline_s)),
            "submit_unix": time.time(),
            "tenant": tenant, "adapter": None,
        }
        with self._cond:
            h = self._admit_open_locked(
                tenant, np.zeros(0, np.int32), spec, slo, deadline_at)
            rid = h.rid
            h.cost = float(cost)
            h.batch_fn = fn
        self._journal.submit(rid, spec)
        if self._hook is not None:
            self._hook.yield_point("submit:commit")
        try:
            with self._cond:
                if self._closing:
                    self._reject_locked(rid, "fleet closed")
                    raise RuntimeError("fleet is closed")
                self._wfq.push(tenant, t.weight, h.cost, h)
                self._dispatch_locked()
        finally:
            self._flush_journal()
        return h

    def cancel(self, rid: int) -> bool:
        """Client-side cancel (ISSUE 18): terminally close an open
        request because its SUBMITTER walked away — the front door
        calls this when a wire connection drops mid-stream or sends a
        cancel frame. Journals a `cancelled` terminal (the DFA accepts
        it as closed), fails the handle with `RequestCancelled`
        carrying the journaled token prefix, and claws the work back
        everywhere it might live: the WFQ/inbox copy is dropped before
        any replica spends a step on it, and an in-flight copy rides
        the SAME per-replica cancel set demotion hedging uses — the
        holder's next handshake calls `engine.cancel`, freeing the
        slot and every KV block the abandoned stream held. Idempotent;
        returns False once the rid is already terminal. A holder that
        finishes anyway loses to the `_cancelled_rids` fence in
        `_accept` (counted `cancel_late_refused`, never a
        duplicate)."""
        with self._cond:
            h = self._handles.get(rid)
            if h is None or h.done or rid in self._done_rids \
                    or h._probe or h._canary:
                return False
            toks = self._journal.progress_of(rid)
            self._done_rids.add(rid)
            self._cancelled_rids.add(rid)
            self._open.discard(rid)
            self._handles.pop(rid, None)
            for i in range(self.max_replicas):
                if rid in self._in_flight[i]:
                    del self._in_flight[i][rid]
                    # engine-side claw-back: the holder consumes this
                    # at its next handshake and frees slot + KV blocks
                    self._cancels[i].add(rid)
                # a routed-but-unclaimed copy: drop it HERE — the
                # inbox drain in _sync_locked does not re-check
                # _done_rids, so a stale entry would be assigned
                try:
                    self._inbox[i].remove(h)
                except ValueError:
                    pass
            for tb in self._taint_base:
                tb.pop(rid, None)
            for cm in self._canary_mark:
                cm.pop(rid, None)
            self.cancelled += 1
            h.error = RequestCancelled(
                "request %d cancelled by client with %d token(s) "
                "emitted%s" % (rid, len(toks),
                               "" if h.conn is None
                               else " (conn %s)" % h.conn),
                rid=rid, tokens=toks)
            self._pending_journal.append(self._journal.cancel(
                rid, toks, conn=h.conn, defer=True))
            self._stream_queue_locked(h, [], closing=True)
            self._pending_events.append(h)
            self._cond.notify_all()
        self._flush_journal()
        return True

    def _dispatch_locked(self):
        """Drain the weighted fair queue into replica inboxes while
        the dispatch window has room (caller holds `_cond`). Called at
        submit and at every replica handshake / monitor sweep, so a
        completion's freed capacity admits the smallest-finish-tag
        request next — the fairness decision point. Entries whose rid
        already went terminal (a close() sweep) are skipped; a
        deadline that died queueing gets its expired verdict HERE,
        before any replica spends anything on it."""
        if self._wfq is None or not self._wfq:
            return
        live = sum(1 for s in self._state if s == _LIVE)
        limit = (self._wfq_window if self._wfq_window is not None
                 else max(1, live) * self._slots_per_replica)
        now = time.monotonic()
        # deadline sweep over WAITING entries first: with the window
        # full the pop loop below never runs, and a deadline that died
        # queueing must still get its verdict at this hop (the PR-8
        # every-queue-hop rule) — never a silent FleetTimeout. The
        # handle stays in the heap; the pop-time done-check skips it.
        for h in self._wfq.entries():
            if not h.done and h.rid not in self._done_rids \
                    and h.deadline_at is not None \
                    and now >= h.deadline_at:
                self._expire_locked(h)
        while self._wfq:
            out = sum(len(self._inbox[i]) + len(self._in_flight[i])
                      for i in range(self.max_replicas))
            if out >= limit:
                break
            h = self._wfq.pop()
            if h.done or h.rid in self._done_rids:
                continue  # went terminal while queued (close/reject)
            if h.deadline_at is not None and now >= h.deadline_at:
                self._expire_locked(h)
                continue
            try:
                self._route(h, exclude=None)
            except EngineFailed:
                pass  # no live replica: _route already failed it

    def _route(self, h: FleetHandle, exclude: Optional[int]):
        """Pick a replica for `h` (caller holds `_cond`): longest
        cached-prefix match against the pool summaries, ties broken by
        load; SLO class first, any live replica as fallback; no live
        replica at all fails the handle."""
        live = [i for i in range(self.max_replicas)
                if self._state[i] == _LIVE and i != exclude]
        if not live:
            # slow beats dead, the _demote_locked rule — but deaths can
            # make a DEMOTED replica the last one alive, and it is warm,
            # heartbeating, and parked only by our own health verdict:
            # strictly better than terminally rejecting every request
            # (probes restore it the moment it behaves; a real death
            # still fails over through the heartbeat deadline)
            live = [i for i in range(self.max_replicas)
                    if self._state[i] == _DEMOTED and i != exclude]
        cands = live
        if self._tiered:
            # disaggregation placement (ISSUE 11): a request with no
            # resumed prefix needs its PREFILL computed — prefill-tier
            # replica; a resumed one (migration, hedge, restart) is in
            # its decode phase — decode-tier replica. None-tier
            # replicas serve both; survival beats tier placement. The
            # tier filter runs BEFORE the SLO filter: tier is the
            # STRUCTURAL phase split, SLO a scheduling preference — if
            # SLO narrowed first, a decode tier whose class differs
            # from the request's would be invisible here, and a
            # migration gated on "a decode-capable replica exists"
            # would land on another prefill replica and ping-pong
            # (re-prefilling the growing prefix every hop) forever
            want = "decode" if h.resume else "prefill"
            tcands = [i for i in cands
                      if self._replica_tier[i] in (want, None)]
            if tcands:
                cands = tcands
        scands = [i for i in cands if self._replica_slo[i] in (None, h.slo)]
        if scands:
            cands = scands  # SLO preference within the tier; survival
            #                 beats SLO placement when none matches
        if not cands:
            # terminal: the caller gets the error NOW, so the request
            # must not stay open (journal-wise) to be resubmitted by
            # every future recover(); prune like _accept does
            # event fires at flush, AFTER the reject record is on disk
            # (submit's caller still gets the raise synchronously)
            self._reject_locked(
                h.rid, "no live replica", fire=True,
                error=EngineFailed(
                    "no live replica for request %d" % h.rid,
                    replica=None))
            raise h.error
        best, best_key = None, None
        # store-aware affinity (ISSUE 16): a chain the durable store
        # holds is cheap for ANY replica to restore (warm/handoff), so
        # routing credits store-held keys to every candidate equally —
        # resident beats absent, ties break by load as ever
        store_keys = (self.kv_store.summary()
                      if self.kv_store is not None and self.affinity
                      and h.chain else ())
        for i in cands:
            depth = 0
            if self.affinity and h.chain:
                s = self._summaries[i]
                for key in h.chain:
                    if key not in s and key not in store_keys:
                        break
                    depth += 1
            load = len(self._inbox[i]) + len(self._in_flight[i])
            key = (-depth, load, i)
            if best_key is None or key < best_key:
                best, best_key = i, key
        rep = self._replicas[best]
        self._inbox[best].append(h)
        # taint base (ISSUE 15): the resume prefix was produced (and
        # vouched for) by EARLIER holders — if this assignee trips, its
        # taint window opens at the resume boundary, never before it.
        # A later clean canary on the replica advances the base.
        self._taint_base[best][h.rid] = len(h.resume)
        # mirror updates NOW (a failover consulting lost() must see
        # this assignment); the file record flushes after the lock.
        # tier + weights_version ride the record as the version-fence
        # side-band (journal DFA J009)
        self._pending_journal.append(self._journal.assign(
            h.rid, rep.name, rep.incarnation, h.generation,
            tier=rep.tier, weights_version=rep.weights_version,
            tenant=h.tenant, handoff=h.handoff_meta, defer=True))
        # the side-band describes THIS assignment only: a later
        # re-route without a fresh package must not re-stamp it (the
        # package itself stays on the handle until the assignee's
        # submit consumes it — or a newer re-route replaces it)
        h.handoff_meta = None
        self._cond.notify_all()

    def _flush_journal(self):
        """Write journal records produced under the lock, THEN release
        the waiters whose completions those records describe — called
        by every entry point after dropping the lock (submit, replica
        syncs, monitor sweeps, drain, close). The ordering makes the
        journal read-your-writes for anyone a result just unblocked.
        The swap and the file write happen as ONE unit under
        `_flush_lock` (outer to `_cond`, never taken while holding
        it): concurrent flushers must hit the file in swap order, or
        a rid's progress deltas could land inverted on disk while the
        mirror has them straight — and a restarted front door would
        resume a scrambled token prefix."""
        if self._hook is not None:
            self._hook.yield_point("journal:flush")
        fired: List[FleetHandle] = []
        with self._flush_lock:
            with self._cond:
                if not self._pending_journal \
                        and not self._pending_events \
                        and not self._pending_stream:
                    return
                pending, self._pending_journal = self._pending_journal, []
                fired, self._pending_events = self._pending_events, []
                streams, self._pending_stream = self._pending_stream, []
            if pending:
                self._journal.write(pending)
        # stream deliveries BEFORE completion events: a waiter whose
        # result() just unblocked must find its stream already closed
        # (both ride the same flush, so both are read-your-writes)
        for h, toks, closing in streams:
            h._stream_feed(toks, closing)
        for h in fired:
            h._event.set()

    def _stream_queue_locked(self, h: FleetHandle, tokens,
                             closing: bool = False):
        """Queue journaled tokens (and/or the terminal close) for a
        streaming handle (caller holds `_cond`): _flush_journal feeds
        them AFTER the file write. Advances the handle's stream cursor
        here, under the scheduler lock, so a failover's re-journaled
        resume prefix — already queued once — is never delivered
        twice. No-op for non-streaming handles."""
        if not h.streaming:
            return
        toks = [int(t) for t in tokens] if tokens else []
        if toks:
            h._stream_sent += len(toks)
        if toks or closing:
            self._pending_stream.append((h, toks, closing))

    def _reject_locked(self, rid: int, reason: str, error=None,
                       fire: bool = False) -> Optional[FleetHandle]:
        """Terminal `rejected` bookkeeping for an open rid (caller
        holds `_cond`): prune every in-memory mirror, count it, queue
        the journal record. The ONE place the reject invariant lives —
        engine-admission failure, the no-live-replica route, submit's
        close race, and close() all share it, so a future change to
        the terminal shape cannot desynchronize the journal from the
        mirrors at just one site. `error` lands on a not-yet-done
        handle; `fire` queues its event for the post-flush release
        (read-your-writes for the waiter it unblocks). Idempotent: a
        rid that is already terminal (close()'s open-request sweep
        racing submit's close branch reaches the same rid from both
        sides) is left alone — a second pass would double-count
        `rejected` and journal a duplicate terminal record, driving
        stats()['lost'] negative."""
        if rid in self._done_rids and "double_reject" not in _MUTANTS:
            return self._handles.pop(rid, None)
        h = self._handles.pop(rid, None)
        self._open.discard(rid)
        self._done_rids.add(rid)
        for fl in self._in_flight:
            fl.pop(rid, None)
        for tb in self._taint_base:
            tb.pop(rid, None)
        for cm in self._canary_mark:
            cm.pop(rid, None)
        self.rejected += 1
        if h is not None and h.tenant is not None \
                and self._tenants is not None:
            self._tenants.on_reject(h.tenant)
        self._pending_journal.append(self._journal.reject(
            rid, reason, defer=True))
        if h is not None and not h.done:
            if error is not None:
                h.error = error
            self._stream_queue_locked(h, [], closing=True)
            if fire:
                self._pending_events.append(h)
        return h

    def _reject(self, rid: int, exc: Exception, rep=None):
        """A single malformed request failed engine admission, or a
        batch-lane job raised: fail it alone (called from replica
        threads), with a TERMINAL journal record — an unservable
        request must not stay open forever and be resubmitted by every
        future recover(). `rep` (the reporting replica) arms the SAME
        journal-lease fence completions get in `_accept`: a demoted/
        superseded holder whose local copy fails must not terminally
        reject a rid a healthy survivor is re-running — its report is
        refused (zombie_refused) and the survivor's verdict stands."""
        with self._cond:
            h = self._handles.get(rid)
            if h is None or h.done:
                return
            if rep is not None and not h._probe:
                a = self._journal.assigned_to(rid)
                if a is None or a[0] != rep.name \
                        or a[1] != rep.incarnation \
                        or rid not in self._in_flight[rep.index]:
                    self.zombie_refused += 1
                    return
            if h._probe:
                # a probe that failed engine ADMISSION is a failed
                # probe, not a rejected request: journaling its
                # negative rid would corrupt the durable table and
                # stats()["lost"], and leaving _probes[i] set would
                # stop all future probes — the replica would stay
                # DEMOTED forever with no path back
                for i, ph in enumerate(self._probes):
                    if ph is h:
                        self._probes[i] = None
                        self._probe_ok[i] = 0
                        self._probe_at[i] = (time.monotonic()
                                             + self.probe_interval_s)
                self._handles.pop(rid, None)
                # the handshake tracked the probe in-flight when it was
                # handed out; a leaked negative rid would block the
                # DRAINING->DRAINED transition forever and inflate this
                # replica's routing load on every failed probe
                for fl in self._in_flight:
                    fl.pop(rid, None)
                h._event.set()
                self._cond.notify_all()
                return
            self._reject_locked(rid, repr(exc), error=exc, fire=True)
            self._cond.notify_all()
        self._flush_journal()

    # -- replica protocol ------------------------------------------------
    def _sync(self, rep: _Replica, completed, progress, idle: bool,
              summary: Optional[Set[int]],
              stats: Optional[dict]):  # thread: replica
        """One replica scheduler handshake: report completions (fenced
        + deduped) and incremental token progress (fenced the same
        way, batched into flush-deferred journal records), heartbeat,
        absorb the pool summary, pick up new work and cancellations.
        The 4th element of the return asks the replica to RESEND its
        pool summary even though the pool revision is unchanged (the
        post-restore refresh). Returns ("stop", [], [], False) when
        this replica object is no longer the registered incarnation
        (fenced zombie, closing fleet) — the loop must exit. May raise
        `_KillDrill`."""
        ret = self._sync_locked(rep, completed, progress, idle, summary,
                                stats)
        self._flush_journal()
        return ret

    def _sync_locked(self, rep: _Replica, completed, progress, idle: bool,
                     summary: Optional[Set[int]],
                     stats: Optional[dict]):  # thread: replica
        with self._cond:
            i = rep.index
            current = (self._replicas[i] is rep
                       and self._state[i] not in (_DEAD, _RETIRED))
            if current:
                self._beats[i] = time.monotonic()
                if stats is not None:
                    # stored BEFORE completions are judged: a probe
                    # completion in this batch must be scored against
                    # the step-latency EWMA that rode the SAME
                    # handshake, not the previous one's snapshot
                    self._rep_stats[i] = stats
                self._absorb_progress(rep, progress)
            for rid, tokens, reason, outcome in completed:
                self._accept(rid, tokens, reason, rep, accepted=current,
                             outcome=outcome)
            if not current or self._closing \
                    or self._replicas[i] is not rep \
                    or self._state[i] in (_DEAD, _RETIRED):
                # the re-check matters: a canary MISMATCH judged in the
                # _accept loop above quarantines this very replica
                # (ISSUE 15) — its own handshake must observe the
                # verdict and stop, not pick up another round of work
                return "stop", [], [], False
            if summary is not None:
                self._summaries[i] = summary
            if self._wfq is not None:
                # the completions judged above freed dispatch-window
                # capacity: admit the smallest-finish-tag WFQ entries
                # now — every handshake is a fairness decision point
                self._dispatch_locked()
            if self._kill[i]:
                self._kill[i] = False
                raise _KillDrill("replica %s killed by drill" % rep.name)
            if self._tiered and rep.tier == "prefill" \
                    and self._state[i] == _LIVE:
                # disaggregation migration (ISSUE 11): any in-flight
                # request that produced NEW tokens on this prefill
                # replica has finished its prefill — hand it to a
                # decode-tier replica via the journaled resume path.
                # Runs AFTER completions were judged, so a request
                # that already finished here is never migrated, and
                # the cancel lands in THIS handshake's return — the
                # prefill engine never spends another step on it
                self._maybe_migrate_locked(rep)
            if self._state[i] == _DRAINING and idle \
                    and not self._inbox[i] and not self._in_flight[i]:
                if self._retire_flag[i]:
                    # autoscaler scale-down completes: fold the
                    # incarnation's stats into the cumulative base
                    # (fleet totals stay monotonic), free the slot,
                    # and stop the thread — the graceful half of the
                    # supervisor's restart story
                    self._retire_flag[i] = False
                    self._state[i] = _RETIRED
                    self._fold_stats_locked(i)
                    self._summaries[i] = set()
                    self.replicas_retired += 1
                    self._cond.notify_all()
                    return "stop", [], [], False
                self._state[i] = _DRAINED
                self._cond.notify_all()
            if self._state[i] == _DRAINED:
                # parked: wait for refill/close; the monitor exempts
                # DRAINED replicas from the heartbeat deadline
                self._cond.wait(timeout=self._idle_wait_s)
                return "park", [], [], False
            resync = self._want_summary[i]
            if resync:
                self._want_summary[i] = False
            cancels = list(self._cancels[i])
            self._cancels[i].clear()
            work: List[FleetHandle] = []
            now = time.monotonic()
            q = self._inbox[i]
            while q:
                h = q.popleft()
                if not h._probe and h.deadline_at is not None \
                        and now >= h.deadline_at:
                    # the ROUTING hop's deadline check: the budget died
                    # in the inbox — verdict now, zero engine steps
                    self._expire_locked(h)
                    continue
                self._in_flight[i][h.rid] = h
                work.append(h)
            if not work and not cancels and idle:
                # nothing to do: sleep on the condition (bounded, so
                # heartbeats keep flowing) instead of spinning
                self._cond.wait(timeout=self._idle_wait_s)
            return "run", work, cancels, resync

    def _absorb_progress(self, rep: _Replica, progress):
        """Journal incremental emitted tokens (caller holds `_cond`;
        the file records are deferred to the post-lock flush). FENCED
        like completions: only the journal-assigned holder's progress
        counts — a demoted replica racing its hedged survivor must not
        interleave tokens into the mirror the survivor resumes from."""
        for rid, delta in progress:
            h = self._handles.get(rid)
            if h is None or h.done or h._probe:
                continue
            a = self._journal.assigned_to(rid)
            if a is None or a[0] != rep.name or a[1] != rep.incarnation:
                continue  # stale holder: journal fence refuses
            if rid not in self._in_flight[rep.index]:
                # clawed back (demotion hedge) and possibly routed BACK
                # here under a bumped generation still in the inbox:
                # the journal names this replica again, but this delta
                # is from the superseded submission — the mirror the
                # new holder resumes from must not absorb it
                continue
            rec = self._journal.progress(
                rid, rep.name, rep.incarnation, h.generation, delta,
                conn=h.conn, stream=h.streaming, defer=True)
            self._pending_journal.append(rec)
            if h.streaming:
                # stream exactly the journal's accumulation: the
                # record's cursor is the accumulated length AFTER this
                # delta, so indices below the handle's cursor (a taint
                # window's sanctioned re-decode of already-delivered
                # tokens) are never pushed twice
                start = rec["stream"] - len(rec["tokens"])
                fresh = rec["tokens"][max(0, h._stream_sent - start):]
                self._stream_queue_locked(h, fresh)
            h.emitted += len(delta)
            if h.ttft_s is None:  # fleet-level TTFT: first journaled token
                h.ttft_s = time.monotonic() - h._submit_t

    def _maybe_migrate_locked(self, rep: _Replica):  # band-verb: resume
        """Migrate requests whose prefill finished on this PREFILL-tier
        replica to a decode-tier replica (caller holds `_cond`). The
        trigger is journaled progress BEYOND the request's resumed
        prefix — the first token only exists once the whole prompt was
        prefilled, so this is exactly the prefill/decode phase
        boundary. Mechanism is PR 8's hedge, on purpose instead of on
        failure: bump the generation, resubmit with the journaled
        prefix as `resume_tokens` (the decode replica prefill-aliases
        it and re-decodes ZERO journaled tokens), queue a cancel this
        replica consumes in the SAME handshake. Skipped when no other
        live decode-capable replica exists — a migration that could
        only route back here (or fail the handle) is worse than
        letting the prefill replica decode."""
        i = rep.index
        if not any(self._state[j] == _LIVE
                   and self._replica_tier[j] in ("decode", None)
                   for j in range(self.max_replicas) if j != i):
            return
        for rid in list(self._in_flight[i]):
            h = self._handles.get(rid)
            if h is None or h.done or h._probe:
                continue
            toks = self._journal.progress_of(rid)
            if len(toks) <= len(h.resume):
                continue  # still prefilling: no new token yet
            self._cancels[i].add(rid)
            self._in_flight[i].pop(rid, None)
            if self._finished_in_journal(h.spec, toks):
                # the first token already satisfied the budget/EOS:
                # complete straight from the journal, zero extra hops
                self._complete_from_progress(
                    h, toks, rep.name, rep.incarnation)
                continue
            h.generation += 1
            h.resume = list(toks)  # replace wholesale, never mutate
            self.migrations += 1
            self.resubmitted += 1
            self.resumed_requests += 1
            self.resumed_tokens += len(toks)
            self._attach_handoff_locked(h, toks)
            try:
                self._route(h, exclude=i)
            except EngineFailed:
                pass  # no survivors: handle already failed by _route

    def _attach_handoff_locked(self, h: FleetHandle, toks: List[int]):  # band-verb: import
        """Build the checksummed block package for a resumed request
        (caller holds `_cond`): the durable KV tier ships the finished
        prefix's closed blocks to the resuming replica so re-prefill
        becomes the FALLBACK path, not the plan (ISSUE 16). The store
        lookup is fingerprint-carrying — the target verifies each block
        after upload and falls back per-block on mismatch — and the
        assign record's `handoff` side-band (length + fp digest) lets
        the journal audit tie the done to THIS transfer (J011)."""
        if self.kv_store is None or not self.handoff:
            return
        package = self.kv_store.chain_fetch(
            list(h.prompt) + list(toks), self.block_tokens)
        if package:
            h.handoff_package = package
            h.handoff_meta = {
                "len": len(package) * self.block_tokens,
                "digest": fp_digest(r["fp"] for r in package)}
            self.handoff_packages += 1

    def _accept(self, rid: int, tokens: List[int], reason: str,
                rep: _Replica, accepted: bool, outcome=None):
        """Completion fence + dedupe (caller holds `_cond`): refuse a
        dead/superseded replica's late result, refuse a STALE holder's
        result (the journal's latest assignment is the lease — a
        demoted replica racing the survivor its work was hedged to
        loses, exactly like a zombie lease-holder), refuse a second
        answer for an already-done rid. `tokens` are the reporting
        incarnation's NEWLY generated tokens; the resumed prefix is
        prepended here so the caller always sees the full output."""
        if rid < 0:  # internal health probe / canary: never journaled
            self._in_flight[rep.index].pop(rid, None)
            h = self._handles.get(rid)
            if h is not None and h._canary:
                self._canary_done(rep, h, tokens, ok=accepted)
                return
            ph = self._probes[rep.index]
            if ph is not None and ph.rid == rid:
                # identity-routed: a DROPPED canary's late completion
                # (its handle already released at demote/drain) must
                # not masquerade as health-probe evidence and credit a
                # restore the probe never earned
                self._probe_done(rep, completed_ok=accepted)
            return
        if not accepted:
            self.zombie_refused += 1
            return
        if rid in self._cancelled_rids:
            # the holder finished work the client already abandoned —
            # the cancel's expected tail (the engine-side claw-back
            # races the final steps by design), NOT a duplicate
            # answer: duplicate_refused must stay 0 under disconnect
            # drills or the exactly-once bar loses its meaning
            self.cancel_late_refused += 1
            return
        if rid in self._done_rids:
            self.duplicate_refused += 1
            return
        h = self._handles.get(rid)
        if h is None or h.done:
            self.duplicate_refused += 1
            return
        a = self._journal.assigned_to(rid)
        if a is not None and (a[0] != rep.name or a[1] != rep.incarnation):
            # hedged elsewhere: this holder's lease is stale
            self.zombie_refused += 1
            return
        if rid not in self._in_flight[rep.index] \
                and "superseded_report" not in _MUTANTS:
            # the (replica, incarnation) pair can RE-match after a
            # demote -> survivor-death -> route-back-to-demoted cycle:
            # the journal's latest assignment names this replica again
            # while the bumped-generation copy is still in its inbox
            # (inboxes drain AFTER completions in this handshake). A
            # report for work the fleet does not track in-flight here
            # is from the superseded submission — accepting it would
            # prepend h.resume to tokens that already contain it
            self.zombie_refused += 1
            return
        full = list(h.resume) + list(tokens)
        if reason == "expired":
            self._expire_locked(h, tokens=full)
            return
        self._done_rids.add(rid)
        self._in_flight[rep.index].pop(rid, None)
        self._taint_base[rep.index].pop(rid, None)
        self._canary_mark[rep.index].pop(rid, None)
        self._open.discard(rid)
        # prune the handle (the caller holds its own reference): a
        # long-lived front door must not retain every prompt + output
        # it ever served — _done_rids (ints) carries the dedupe
        self._handles.pop(rid, None)
        # ISSUE 16 handoff fence: an assignment that shipped a block
        # package MUST account for it at the done — verified import or
        # counted fallback, never silence (protocol_lint J011). An
        # engine that cannot report (scripted drills) gets the honest
        # default: nothing imported, re-prefill fallback.
        _tier, _wv, _ten, ho = self._journal.assigned_meta(rid)
        if ho is not None and outcome is None:
            outcome = {"imported": 0, "fallback": True}
            self.handoff_fallbacks_defaulted += 1
        self._pending_journal.append(self._journal.complete(
            rid, rep.name, rep.incarnation, h.generation, full,
            weights_version=rep.weights_version, tenant=h.tenant,
            handoff=outcome, defer=True))
        h.tokens = full
        h.replica = rep.name
        h.weights_version = rep.weights_version
        # stream tail + close: whatever the cursor has not delivered
        # yet (the final handshake's tokens ride the done record, not
        # a progress record) — concatenation lands bit-identical to
        # result()'s generated half
        self._stream_queue_locked(h, full[h._stream_sent:],
                                  closing=True)
        if h.tenant is not None and self._tenants is not None:
            # per-tenant O(1) accounting (ISSUE 12): completion,
            # tokens served, and the latency the tenant actually saw
            self._tenants.on_complete(
                h.tenant, len(full),
                queue_wait_s=(h.ttft_s if h.ttft_s is not None
                              else time.monotonic() - h._submit_t),
                batch=h.batch_fn is not None)
            if h.batch_fn is not None:
                self.batch_jobs_completed += 1
        # the event fires in _flush_journal, AFTER the done record is
        # on disk — result() observers get read-your-writes recovery
        self._pending_events.append(h)
        self.completed += 1
        self._cond.notify_all()

    def _expire_locked(self, h: FleetHandle, tokens=None):
        """Terminal `expired` verdict for an open request (caller holds
        `_cond`): the deadline died — journal it, fail the handle with
        `DeadlineExceeded`, stop spending anything on it. A verdict,
        never a silent hang (ISSUE 8)."""
        rid = h.rid
        if h.done or rid in self._done_rids:
            return
        toks = (list(tokens) if tokens is not None
                else self._journal.progress_of(rid))
        h.error = DeadlineExceeded(
            "request %d expired with %d/%d token(s) emitted "
            "(deadline_s=%r)" % (
                rid, len(toks), h.spec["max_new_tokens"],
                h.spec.get("deadline_s")),
            rid=rid, tokens=toks)
        self._done_rids.add(rid)
        self._open.discard(rid)
        self._handles.pop(rid, None)
        for fl in self._in_flight:
            fl.pop(rid, None)
        for tb in self._taint_base:
            tb.pop(rid, None)
        for cm in self._canary_mark:
            cm.pop(rid, None)
        self.expired += 1
        if h.tenant is not None and self._tenants is not None:
            self._tenants.on_expire(h.tenant)
        self._pending_journal.append(self._journal.expire(
            rid, toks, defer=True))
        # close (no tokens): the iterator reports DeadlineExceeded
        # after the delivered prefix, exactly like result()
        self._stream_queue_locked(h, [], closing=True)
        self._pending_events.append(h)
        self._cond.notify_all()

    def _on_crash(self, rep: _Replica, exc: BaseException):  # thread: replica
        # unwrap engine-latch wrappers: the FIRST failure decides the
        # recovery path — an IntegrityError (trap, fingerprint, spike)
        # takes the quarantine + taint route, anything else the plain
        # failover that trusts journaled progress (ISSUE 15)
        root = exc
        while isinstance(root, EngineFailed) and root.__cause__ is not None:
            root = root.__cause__
        # final stats snapshot, taken ON the dying replica's own thread
        # (the engine is confined here): without it, counters that
        # moved between the last handshake and the crash — an integrity
        # trip's fingerprint mismatch above all — would never fold into
        # the fleet totals
        try:
            final_stats = rep._stats()
        except Exception:
            final_stats = None
        with self._cond:
            if self._replicas[rep.index] is rep and final_stats is not None:
                self._rep_stats[rep.index] = final_stats
            if isinstance(root, IntegrityError):
                self._integrity_trip_locked(rep.index, rep, root)
            else:
                self._fail_over(rep.index, rep, exc)
        self._flush_journal()

    # -- failure handling ------------------------------------------------
    def _fold_stats_locked(self, i: int):
        """Fold an ending incarnation's last stats snapshot into the
        fleet-wide cumulative base (caller holds `_cond`): totals must
        not decrease on refill OR retirement. Gauges die with the
        incarnation. Shared by the death path (_fail_over), the
        autoscaler's retirement, and the rollout swap."""
        st = self._rep_stats[i]
        if st:
            for k, v in st.items():
                if k in _GAUGE_STATS:
                    continue  # gauges: die with the incarnation
                self._stats_base[k] = self._stats_base.get(k, 0) + v
        self._rep_stats[i] = None

    def _fail_over(self, i: int, rep: _Replica, exc: BaseException):
        """Declare replica `i` dead and resubmit its journal-recorded
        open requests to survivors (caller holds `_cond`). Idempotent
        per incarnation: the crash path and the heartbeat path can both
        land here."""
        if self._replicas[i] is not rep or self._state[i] == _DEAD:
            return
        self._state[i] = _DEAD
        self._summaries[i] = set()
        self.failovers += 1
        self._fold_stats_locked(i)
        # rapid-death accounting gates auto_refill AND the autoscaler's
        # spawn picker (exponential backoff, the Supervisor's
        # restart/backoff discipline — literally supervisor.py's
        # restart_backoff_s schedule): a deterministically-failing
        # replica must not crash/refill at monitor frequency forever
        rapid = time.monotonic() - self._spawned[i] < 2.0
        self._rapid[i] = self._rapid[i] + 1 if rapid else 0
        self._refill_at[i] = time.monotonic() + _backoff(
            self._rapid[i] + 1, base=0.05)
        self._inbox[i].clear()
        self._in_flight[i].clear()
        self._cancels[i].clear()
        self._slow_since[i] = None
        self._watermark[i] = None
        self._rate[i] = None
        self._stall_since[i] = None
        # an outstanding health probe dies with the replica (it was
        # never journaled — nothing to recover); release its handle so
        # repeated probe-interrupted deaths cannot accumulate them
        if self._probes[i] is not None:
            self._handles.pop(self._probes[i].rid, None)
            self._probes[i]._event.set()
            self._probes[i] = None
        self._probe_ok[i] = 0
        # ISSUE 15: the canary (never journaled) and the taint-base
        # marks die with the incarnation — the integrity trip path
        # already consumed the marks it needed BEFORE calling here
        self._drop_canary_locked(i)
        self._taint_base[i] = {}
        self._canary_mark[i] = {}
        self._want_summary[i] = False  # a fresh incarnation sends anew
        # the JOURNAL is the recovery source: every open request whose
        # latest assignment names this replica+incarnation, resumed
        # from its journaled progress — the survivor prefill-aliases
        # the emitted prefix and re-decodes NOTHING
        self._resubmit_lost(i, rep)
        self._cond.notify_all()

    @staticmethod
    def _finished_in_journal(spec: dict, toks: List[int]) -> bool:
        """True when a journaled emitted-token prefix already satisfies
        the request (budget reached, or `eos_id` emitted): completing
        it needs zero engine work."""
        if not toks:
            return False
        eos = spec["eos_id"]
        return (len(toks) >= int(spec["max_new_tokens"])
                or (eos is not None and toks[-1] == int(eos)))

    def _complete_from_progress(self, h: FleetHandle, toks: List[int],
                                replica: str, incarnation: int):
        """Terminal completion straight from journaled progress (caller
        holds `_cond`): a lost holder — a dead incarnation, or a
        crashed front door on restart — actually FINISHED the request
        and only its done record was lost. No engine steps are spent,
        no token is re-decoded."""
        rid = h.rid
        self._done_rids.add(rid)
        self._open.discard(rid)
        self._handles.pop(rid, None)
        # the version of the holder that actually produced the tokens
        # (read BEFORE complete() prunes the assignment side-band)
        _tier, wv, _ten, ho = self._journal.assigned_meta(rid)
        # the holder died before reporting whether it imported its
        # block package — the audit gets the conservative default, not
        # silence (J011: every shipped package accounts for itself)
        outcome = None
        if ho is not None:
            outcome = {"imported": 0, "fallback": True}
            self.handoff_fallbacks_defaulted += 1
        self._pending_journal.append(self._journal.complete(
            rid, replica, incarnation, h.generation, list(toks),
            weights_version=wv, tenant=h.tenant, handoff=outcome,
            defer=True))
        h.tokens = list(toks)
        h.emitted = len(toks)
        h.replica = replica
        h.weights_version = wv
        self._stream_queue_locked(h, toks[h._stream_sent:],
                                  closing=True)
        if h.tenant is not None and self._tenants is not None:
            self._tenants.on_complete(
                h.tenant, len(toks),
                queue_wait_s=(h.ttft_s if h.ttft_s is not None
                              else time.monotonic() - h._submit_t),
                batch=h.batch_fn is not None)
        self._pending_events.append(h)
        self.completed += 1

    def _resubmit_lost(self, i: int, rep: _Replica, lost=None):  # band-verb: resume
        """Hedge/recover every open request the journal assigns to
        (rep, incarnation) onto survivors, carrying the emitted-token
        prefix (caller holds `_cond`). `lost` lets a caller that
        already scanned the journal (demotion builds its cancel set
        from the same list) pass the result in instead of paying the
        O(open x emitted) copy twice under `_cond`."""
        if lost is None:
            lost = self._journal.lost(rep.name, rep.incarnation)
        for rid, _spec, _gen, toks in lost:
            h = self._handles.get(rid)
            if h is None or h.done:
                continue
            if h.deadline_at is not None \
                    and time.monotonic() >= h.deadline_at:
                # already out of budget: expiring NOW is the verdict —
                # resubmitting would spend survivor steps on a corpse
                self._expire_locked(h, tokens=toks)
                continue
            if self._finished_in_journal(h.spec, toks):
                self._complete_from_progress(
                    h, toks, rep.name, rep.incarnation)
                continue
            h.generation += 1
            h.resume = list(toks)  # replace wholesale, never mutate
            self.resubmitted += 1
            if toks:
                self.resumed_requests += 1
                self.resumed_tokens += len(toks)
            self._attach_handoff_locked(h, toks)
            try:
                self._route(h, exclude=i)
            except EngineFailed:
                pass  # no survivors: handle already failed by _route

    def _monitor_loop(self):  # thread: monitor
        if self._hook is not None:
            self._hook.thread_started("monitor", "mon")
        try:
            self._monitor_loop_body()
        finally:
            if self._hook is not None:
                self._hook.thread_exiting()

    def _monitor_loop_body(self):  # thread: monitor
        while True:
            if self._hook is not None:
                self._hook.yield_point("monitor:sweep")
            with self._cond:
                if self._closing:
                    return
                now = time.monotonic()
                for i, rep in enumerate(self._replicas):
                    if self._state[i] in (_LIVE, _DRAINING, _DEMOTED) \
                            and now - self._beats[i] > self.heartbeat_timeout_s:
                        # gray shades into black: a demoted replica
                        # that stops even heartbeating is plain dead
                        self._fail_over(
                            i, rep,
                            TimeoutError(
                                "replica %s missed heartbeat deadline "
                                "(%.2fs)" % (rep.name,
                                             self.heartbeat_timeout_s)))
                    elif self._state[i] == _DEAD and self.auto_refill \
                            and now >= self._refill_at[i]:
                        self._refill_locked(i)
                if self.slow_replica_factor is not None:
                    self._health_sweep(now)
                if self.canary_interval_s is not None:
                    self._canary_sweep(now)
                if self.min_replicas < self.max_replicas:
                    self._scale_sweep(now)
                if self._wfq is not None:
                    # an all-idle fleet must still drain the fair
                    # queue (deaths/refills change the window too)
                    self._dispatch_locked()
            self._flush_journal()  # fail-over resubmissions above
            time.sleep(self._monitor_interval_s)

    # -- gray-failure detection (ISSUE 8) --------------------------------
    def _live_ewmas(self) -> List[float]:  # holds: _cond
        out = []
        for i in range(self.max_replicas):
            st = self._rep_stats[i]
            if self._state[i] == _LIVE and st \
                    and st.get("step_ewma_s", 0.0) > 0.0:
                out.append(float(st["step_ewma_s"]))
        return out

    def _health_sweep(self, now: float):  # thread: monitor, holds: _cond
        """Score every live replica against the fleet. The health score
        combines BOTH ISSUE 8 signals, and demotion needs both to
        agree: (a) step-latency EWMA past `slow_replica_factor` x the
        live (lower) median — necessary but NOT sufficient, because a
        replica carrying more slots / prefill chunks / GIL contention
        has honestly longer steps; (b) the decode-progress WATERMARK
        (tokens emitted per wall-second, sampled over >= 0.15 s
        windows) below the live median by the same factor — a busy
        replica still emitting at fleet-comparable rate is never
        demoted, however long its steps look. A watermark FLAT for the
        whole hysteresis window while busy is gray on its own (the
        wedged-but-syncing shape). Sustained past `slow_min_duration_s`
        (one GC pause decays out of the EWMA in a few healthy steps
        and resets the clock), the replica is demoted: drained +
        probed, not killed. Demoted replicas are probed on
        `probe_interval_s` until healthy, then restored — same
        incarnation, warm pool."""
        ewmas = self._live_ewmas()
        median = _lower_median(ewmas)
        rate_window = max(0.15, 2.0 * self._monitor_interval_s)
        rates = [self._rate[i] for i in range(self.max_replicas)
                 if self._state[i] == _LIVE and self._rate[i] is not None]
        median_rate = _upper_median(rates)
        for i in range(self.max_replicas):
            st = self._rep_stats[i]
            if self._state[i] == _DEMOTED:
                if self._probes[i] is None and now >= self._probe_at[i]:
                    self._send_probe_locked(i)
                continue
            if self._state[i] != _LIVE or not st:
                continue
            # judge only FRESH evidence: _rep_stats is a snapshot from
            # the replica's last handshake. A replica silent inside one
            # long step (a first compile — the documented
            # false-demotion hazard) freezes busy/tokens/EWMA; scoring
            # that stale picture would demote it for compiling. A
            # replica that stays silent past the window here simply
            # isn't judged (the heartbeat deadline owns total silence);
            # a GRAY replica still syncs every (stalled) step, so it
            # keeps producing fresh evidence and IS judged. The window
            # is 2x the hysteresis duration: a gray step is the stall
            # PLUS real compute, and a gate at exactly
            # slow_min_duration_s would discard evidence from a gray
            # replica whose stalled steps run just past it — while a
            # compile (seconds) stays far beyond 2x.
            if now - self._beats[i] > 2.0 * self.slow_min_duration_s:
                self._slow_since[i] = None
                self._watermark[i] = None
                self._rate[i] = None
                self._stall_since[i] = None
                continue
            # the progress counter includes PREFILL work: a replica
            # grinding a long prompt through chunks emits no tokens
            # for a while but is making honest progress — counting
            # only emissions would read the prefill phase as a stall
            # (and bias the rate veto against prefill-heavy replicas)
            tokens = int(st.get("tokens_out", 0)) \
                + int(st.get("prefill_tokens_computed", 0))
            busy = bool(st.get("busy"))
            stalled = False
            if busy:
                wm = self._watermark[i]
                if wm is None:
                    self._watermark[i] = (now, tokens)
                elif now - wm[0] >= rate_window \
                        and self._beats[i] > wm[0]:
                    # sample only when the replica SYNCED since the
                    # last sample: flat progress across syncs is a
                    # stall; silence (one long step — a compile) is
                    # not evidence of anything, and when the sync
                    # finally lands the token jump clears the flag
                    self._rate[i] = (tokens - wm[1]) / (now - wm[0])
                    if tokens <= wm[1]:
                        if self._stall_since[i] is None:
                            self._stall_since[i] = wm[0]
                        stalled = (now - self._stall_since[i]
                                   >= self.slow_min_duration_s)
                    else:
                        self._stall_since[i] = None
                    self._watermark[i] = (now, tokens)
            else:
                self._watermark[i] = None
                self._rate[i] = None
                self._stall_since[i] = None
            ewma = float(st.get("step_ewma_s", 0.0))
            ewma_slow = (busy and median is not None and len(ewmas) >= 2
                         and ewma > self.slow_replica_factor * median)
            # rate agreement: a fleet-comparable emission rate VETOES
            # the latency signal (longer steps are honest when the
            # replica carries more slots / prefill chunks / host
            # contention). With fewer than two live samples there is
            # no reference — stay permissive and let the EWMA decide
            rate_poor = (len(rates) < 2 or self._rate[i] is None
                         or median_rate <= 0.0
                         or self._rate[i]
                         < median_rate / self.slow_replica_factor)
            if (ewma_slow and rate_poor) or stalled:
                if self._slow_since[i] is None:
                    self._slow_since[i] = now
                if now - self._slow_since[i] >= self.slow_min_duration_s \
                        or stalled:
                    self._demote_locked(i)
            else:
                self._slow_since[i] = None

    def _demote_locked(self, i: int):  # holds: _cond
        """Demote a gray replica: hedge its open requests to survivors
        (token-level resume — decode steps already spent are never
        re-spent), tell it to CANCEL the hedged work, keep it alive
        and warm, and start probing. Never demote the last live
        replica: slow beats dead."""
        survivors = [j for j in range(self.max_replicas)
                     if j != i and self._state[j] == _LIVE]
        if not survivors:
            self._slow_since[i] = None  # re-judged when the fleet heals
            return
        rep = self._replicas[i]
        self._state[i] = _DEMOTED
        self.demotions += 1
        self._summaries[i] = set()  # don't route by a parked pool
        self._slow_since[i] = None
        self._watermark[i] = None
        self._rate[i] = None
        self._stall_since[i] = None
        self._inbox[i].clear()
        # every open request the journal assigns here is hedged away;
        # the replica cancels them at its next handshake, and the
        # journal assignment fence refuses anything it still reports
        self._cancels[i].update(self._in_flight[i].keys())
        lost = self._journal.lost(rep.name, rep.incarnation)
        self._cancels[i].update(rid for rid, _s, _g, _t in lost)
        self._in_flight[i].clear()
        self._resubmit_lost(i, rep, lost=lost)
        # ISSUE 15: an outstanding canary would be cancelled with the
        # hedged work and never complete — release it so the restored
        # replica's sweep can send a fresh one
        self._drop_canary_locked(i)
        self._taint_base[i] = {}
        self._canary_mark[i] = {}
        self._probe_ok[i] = 0
        self._probe_at[i] = time.monotonic() + self.probe_interval_s
        self._cond.notify_all()

    def _send_probe_locked(self, i: int):  # holds: _cond
        """Ship a tiny internal generate request to a DEMOTED replica:
        its completion (and the step-latency EWMA that rides the same
        handshake) is the restore evidence. Probes use negative rids,
        are never journaled, and never touch the open-request set."""
        rid = self._next_probe_rid
        self._next_probe_rid -= 1
        prompt = np.zeros(1, np.int32)
        # the probe must pass THIS replica's engine admission rules:
        # a probe refused at admission is a failed probe, and sizing
        # from the base kw (or a hardcoded size) would permanently
        # fail on a replica whose engine_kw_for override shrinks the
        # context/pool below the fleet-wide default
        rep = self._replicas[i]
        L, bt, pb = self._limits_for(
            rep._engine_kw if rep is not None else self._engine_kw)
        max_new = max(1, min(6, L - 1, bt * pb - 1))
        spec = {"prompt": [0], "max_new_tokens": max_new,
                "temperature": 0.0,
                "eos_id": None, "seed": 0, "publish_len": 0,
                "slo": None, "deadline_s": None, "submit_unix": time.time()}
        h = FleetHandle(rid, prompt, spec, None, fleet=self)
        h._probe = True
        self._handles[rid] = h
        self._probes[i] = h
        self.probes_sent += 1
        self._inbox[i].append(h)
        self._cond.notify_all()

    def _probe_done(self, rep: _Replica, completed_ok: bool):  # holds: _cond
        """A probe came back: restore the replica if its step EWMA is
        back inside the healthy band (vs the live-fleet median), else
        schedule the next probe. `probe_ok_needed` consecutive healthy
        probes gate the restore (hysteresis on the way back too)."""
        i = rep.index
        h = self._probes[i]
        if h is None or self._replicas[i] is not rep \
                or self._state[i] != _DEMOTED:
            return
        self._probes[i] = None
        self._handles.pop(h.rid, None)
        h._event.set()  # nobody waits, but keep the future honest
        st = self._rep_stats[i] or {}
        ewma = float(st.get("step_ewma_s", 0.0))
        median = _lower_median(self._live_ewmas())
        healthy = completed_ok and (
            median is None  # no live peer to compare against: restore
            or ewma <= self.slow_replica_factor * median)
        if healthy:
            self._probe_ok[i] += 1
            if self._probe_ok[i] >= self.probe_ok_needed:
                # restored: SAME incarnation, engine + prefix pool warm
                self._state[i] = _LIVE
                self.restores += 1
                self._probe_ok[i] = 0
                self._beats[i] = time.monotonic()
                # demotion cleared the routing summary; the pool is
                # warm and UNCHANGED, so the replica's revision cache
                # would never resend it — ask for a refresh or the
                # warm-restore benefit is silently lost to routing
                self._want_summary[i] = True
                self._cond.notify_all()
                return
        else:
            self._probe_ok[i] = 0
        self._probe_at[i] = time.monotonic() + self.probe_interval_s

    # -- serving integrity (ISSUE 15) ------------------------------------
    def _golden_for(self, weights_version) -> Optional[List[int]]:  # holds: _cond
        """The golden canary trace for one weight version (computed at
        construction / rollout commit), or the explicit default."""
        g = self._canary_golden.get(
            weights_version if weights_version is None
            else int(weights_version))
        return g if g is not None else self._canary_golden_default

    def _drop_canary_locked(self, i: int):  # holds: _cond
        """Release slot i's outstanding canary handle (the replica is
        leaving LIVE service — death, demotion, drain, refill, close —
        so the canary's completion can no longer be judged fairly)."""
        ch = self._canaries[i]
        if ch is not None:
            self._handles.pop(ch.rid, None)
            for fl in self._in_flight:
                fl.pop(ch.rid, None)
            ch._event.set()
            self._canaries[i] = None
        if self.canary_interval_s is not None:
            self._canary_at[i] = time.monotonic() + self.canary_interval_s

    def _canary_sweep(self, now: float):  # thread: monitor, holds: _cond
        """Ship one known-answer canary per LIVE replica every
        `canary_interval_s` (PR 8's probe machinery extended past
        demoted-only): a tiny greedy request whose completion is
        judged against the per-weights_version golden trace. Sized
        like probes — within the REPLICA's own composed engine limits,
        so an engine_kw_for override can never wedge a canary at
        admission."""
        for i in range(self.max_replicas):
            if self._state[i] != _LIVE or self._canaries[i] is not None:
                continue
            if now < self._canary_at[i]:
                continue
            rep = self._replicas[i]
            golden = self._golden_for(rep.weights_version)
            if golden is None:
                # no golden for this version (mid-rollout window):
                # skip this round, never guess
                self._canary_at[i] = now + self.canary_interval_s
                continue
            L, bt, pb = self._limits_for(rep._engine_kw)
            P0 = len(self._canary_prompt)
            max_new = min(len(golden), L - P0, bt * pb - P0)
            if max_new < 1:
                self._canary_at[i] = now + self.canary_interval_s
                continue
            rid = self._next_probe_rid
            self._next_probe_rid -= 1
            spec = {"prompt": [int(t) for t in self._canary_prompt],
                    "max_new_tokens": int(max_new), "temperature": 0.0,
                    "eos_id": None, "seed": 0, "publish_len": 0,
                    "slo": None, "deadline_s": None,
                    "submit_unix": time.time()}
            h = FleetHandle(rid,
                            np.asarray(self._canary_prompt, np.int32),
                            spec, None, fleet=self)
            h._probe = True
            h._canary = True
            self._handles[rid] = h
            self._canaries[i] = h
            self.canaries_sent += 1
            self._inbox[i].append(h)
            self._cond.notify_all()

    def _canary_done(self, rep: _Replica, h: FleetHandle, tokens,
                     ok: bool):  # holds: _cond
        """A canary came back: a golden match is the CLEAN mark — every
        token this replica has journaled so far is vouched for, so the
        taint base of its in-flight rids advances to now. A mismatch
        is an integrity trip: quarantine + taint since the last clean
        mark. A fenced (zombie/superseded) completion is evidence of
        nothing and only reschedules."""
        i = rep.index
        if self._canaries[i] is not h or self._replicas[i] is not rep:
            self._handles.pop(h.rid, None)
            h._event.set()
            return  # stale canary: a newer incarnation owns the slot
        self._canaries[i] = None
        self._handles.pop(h.rid, None)
        h._event.set()
        if not ok:
            self._canary_at[i] = time.monotonic() + self.canary_interval_s
            return
        golden = self._golden_for(rep.weights_version) or []
        want = golden[:int(h.spec["max_new_tokens"])]
        if list(tokens) == list(want):
            self.canaries_ok += 1
            # the clean mark: sound because the canary's completion
            # and the progress it vouches for ride the SAME handshake
            # (the replica loop collects both in the iteration of the
            # step that finished the canary — nothing later can be
            # under the mark), and consumed only by canary-KIND trips
            # (engine-global corruption; a canary cannot vouch for
            # another request's KV blocks)
            for rid in self._in_flight[i]:
                if rid >= 0:
                    self._canary_mark[i][rid] = len(
                        self._journal.progress_of(rid))
            self._canary_at[i] = time.monotonic() + self.canary_interval_s
            return
        self.canary_mismatches += 1
        self._integrity_trip_locked(
            i, rep,
            IntegrityError(
                "canary mismatch on %s.i%d: got %r, want %r"
                % (rep.name, rep.incarnation, list(tokens), want),
                kind="canary", replica=rep.name))

    def _integrity_trip_locked(self, i: int, rep: _Replica,
                               exc: BaseException):  # holds: _cond
        """Quarantine a corrupt replica (caller holds `_cond`;
        exactly-once per incarnation): journal the TAINT side-band —
        every open rid assigned here whose journaled progress grew past
        its taint base gets a window [base, now) — which truncates the
        mirror to the verified prefix, then declare the replica dead
        through the normal failover path. The failover's resubmission
        therefore resumes each request from its last VERIFIED token
        index, and the taint window re-decodes on a healthy survivor:
        the one sanctioned exception to PR 8's zero-re-decode rule,
        journal-audited (J010) so ONLY tainted tokens ever re-decode.
        The fresh incarnation comes through the PR 11 supervisor
        backoff exactly like a crash (auto_refill / refill())."""
        if self._replicas[i] is not rep or self._state[i] == _DEAD:
            return  # already quarantined/failed over this incarnation
        self.integrity_trips += 1
        kind = getattr(exc, "kind", "unknown")
        self.integrity_trip_kinds[kind] = \
            self.integrity_trip_kinds.get(kind, 0) + 1
        lost = self._journal.lost(rep.name, rep.incarnation)
        # canary-kind trips may tighten the window to the last clean
        # canary's mark (engine-global corruption is exactly what the
        # canary vouches against); fingerprint/trap/spike trips taint
        # from the assignment base — a clean canary between a KV flip
        # and its detection must NOT launder the flipped block's
        # tokens past the window (review hardening: the canary never
        # attended through that block)
        use_marks = kind == "canary"
        taint: Dict[int, Tuple[int, int]] = {}
        for rid, _spec, _gen, toks in lost:
            base = self._taint_base[i].get(rid, 0)
            if use_marks:
                base = max(base, self._canary_mark[i].get(rid, 0))
            if len(toks) > base:
                taint[rid] = (base, len(toks))
        if taint:
            self.tainted_tokens += sum(u - f for f, u in taint.values())
            # mirror truncation happens HERE (synchronously, like every
            # assign/complete): _fail_over's journal scan an instant
            # later hands the survivor the verified prefix only
            self._pending_journal.append(self._journal.integrity(
                rep.name, rep.incarnation, taint, reason=str(exc),
                defer=True))
            for rid, (frm, _u) in taint.items():
                hh = self._handles.get(rid)
                if hh is not None:
                    hh.emitted = frm
        self._fail_over(i, rep, exc)

    # -- autoscaling (ISSUE 11) ------------------------------------------
    def _window_headroom_s(self) -> float:  # holds: _cond
        """Deadline enforcement granularity (ISSUE 19): the widest live
        replica's decode window in wall seconds — window size K times
        its PER-TOKEN step EWMA (the gauge is already normalized by
        K). 0.0 for a K=1 fleet, so the pre-window autoscaler behavior
        is untouched."""
        w = 0.0
        for i in range(self.max_replicas):
            rep = self._replicas[i]
            if self._state[i] != _LIVE or rep is None:
                continue
            k = int(rep._engine_kw.get("decode_window") or 1)
            if k <= 1:
                continue
            st = self._rep_stats[i] or {}
            w = max(w, k * float(st.get("step_ewma_s", 0.0)))
        return w

    def _scale_sweep(self, now: float):  # thread: monitor, holds: _cond
        """Queue-driven elasticity: spawn when open requests outrun
        live capacity (or deadline headroom shrinks under real
        queueing), retire after SUSTAINED low load. One cool-down gate
        (`scale_cooldown_s`) serializes both directions — a burst can
        trigger at most one scale op per window, so arrival noise
        cannot flap the fleet (hysteresis on the way down is
        additionally `scale_down_idle_s` of continuous low load).
        Paused during a rollout: drain→swap→refill must not race a
        retirement of the replica being swapped."""
        if self._rollout or self._closing:
            return
        live = [i for i in range(self.max_replicas)
                if self._state[i] == _LIVE]
        n_live = len(live)
        open_n = len(self._open)
        pressure = open_n > self.scale_up_open_per_replica \
            * max(1, n_live)
        if not pressure and self.scale_up_headroom_s is not None \
                and open_n > n_live:
            # deadline pressure counts only under real queueing (more
            # open requests than replicas): a single tight-deadline
            # request on an idle fleet needs routing, not capacity.
            # Headroom is clamped to at least one decode-window's wall
            # time (ISSUE 19): a decode_window=K engine enforces
            # deadlines every K tokens, so slack thinner than one
            # window is already unservable — spawning for it cannot
            # help, and waiting for it to shrink further would spawn
            # too late for the requests a new replica CAN still serve.
            headroom = max(self.scale_up_headroom_s,
                           self._window_headroom_s())
            for h in self._handles.values():
                if h.deadline_at is not None and not h._probe \
                        and h.deadline_at - now < headroom:
                    pressure = True
                    break
        if pressure:
            self._low_load_since = None
            if now < self._scale_gate_at or n_live >= self.max_replicas:
                return
            self._scale_up_locked(now)
            return
        if n_live > self.min_replicas and open_n < n_live:
            if self._low_load_since is None:
                self._low_load_since = now
            elif now - self._low_load_since >= self.scale_down_idle_s \
                    and now >= self._scale_gate_at:
                victim = self._scale_down_victim_locked(live)
                if victim is not None:
                    self._begin_retire_locked(victim)
                    self._scale_gate_at = now + self.scale_cooldown_s
                    self._low_load_since = None
        else:
            self._low_load_since = None

    def _scale_up_locked(self, now: float):  # holds: _cond
        """Bring one more replica up: a DRAINED slot resumes WARM (the
        refill() machinery's whole point — engine and prefix pool
        intact), else a retired/dead slot spawns a fresh incarnation,
        gated by the slot's supervisor-style restart backoff."""
        for want_warm in (True, False):
            for i in range(self.max_replicas):
                st = self._state[i]
                if want_warm and st == _DRAINED:
                    if self._rollout:
                        # never warm-resume during a rollout: the
                        # parked engine holds pre-rollout weights, and
                        # this slot may be mid-swap (see refill())
                        continue
                    self._state[i] = _LIVE
                    self._beats[i] = time.monotonic()
                    self._kill[i] = False
                elif not want_warm and st in (_RETIRED, _DEAD) \
                        and now >= self._refill_at[i]:
                    self._refill_locked(i)
                else:
                    continue
                self.replicas_spawned += 1
                self._scale_gate_at = now + self.scale_cooldown_s
                self._cond.notify_all()
                return

    def _scale_down_victim_locked(self, live: List[int]):  # holds: _cond
        """Least-loaded live replica whose retirement keeps every
        configured tier represented (retiring the last prefill-capable
        replica would break disaggregation harder than staying one
        replica over target); ties retire the HIGHEST index, keeping
        the low, initially-live slots stable."""
        best, best_key = None, None
        for i in live:
            t = self._replica_tier[i]
            if t is not None and not any(
                    self._replica_tier[j] in (t, None)
                    for j in live if j != i):
                continue
            load = len(self._inbox[i]) + len(self._in_flight[i])
            key = (load, -i)
            if best_key is None or key < best_key:
                best, best_key = i, key
        return best

    def _begin_retire_locked(self, i: int):  # holds: _cond
        """Graceful scale-down, started (finished by the replica's own
        handshake when it reaches DRAINED with the retire flag set):
        queued requests re-route NOW, in-flight work is hedged to
        survivors FROM THE JOURNAL with token-level resume (the
        demotion mechanism — no decode step re-spent), and the replica
        cancels the clawed-back work at its next handshake, goes idle,
        and retires."""
        self._begin_drain_locked(i, hedge=True, retire=True)

    def _begin_drain_locked(self, i: int, hedge: bool, retire: bool,
                            clear_summary: bool = True):  # holds: _cond
        """Start taking replica `i` out of routing (caller holds
        `_cond`): queued requests re-route now; with `hedge`, in-flight
        work is ALSO clawed back via the journal with token-level
        resume (otherwise it finishes here — the rollout's
        finish-on-old-version policy); with `retire`, the replica's
        own handshake retires the slot once idle instead of parking
        DRAINED. `clear_summary` drops the routing summary (retire and
        rollout: the engine is leaving, its pool must not attract
        traffic); an operator `drain()` keeps it — the pool parks WARM
        and a warm `refill()` must resume with its affinity state
        intact (the replica's revision cache would never resend an
        unchanged pool, the PR-8 restore bug class)."""
        if self._state[i] != _LIVE:
            return
        rep = self._replicas[i]
        self._retire_flag[i] = retire
        self._state[i] = _DRAINING
        if clear_summary:
            self._summaries[i] = set()
        queued = list(self._inbox[i])
        self._inbox[i].clear()
        for h in queued:
            h.generation += 1
            self.resubmitted += 1
            try:
                self._route(h, exclude=i)
            except EngineFailed:
                pass  # no other live replica: handle failed
        if hedge:
            self._cancels[i].update(self._in_flight[i].keys())
            lost = self._journal.lost(rep.name, rep.incarnation)
            self._cancels[i].update(rid for rid, _s, _g, _t in lost)
            self._in_flight[i].clear()
            self._resubmit_lost(i, rep, lost=lost)
            self._taint_base[i] = {}
            self._canary_mark[i] = {}
        self._canary_mark[i] = {}
        # a draining replica's canary would be cancelled (hedge) or
        # park with the engine (finish) — release it either way
        self._drop_canary_locked(i)
        self._cond.notify_all()

    def scale_up(self) -> bool:
        """Operator surface: bring one held-back slot live now (same
        path the autoscaler takes, without its pressure gate). Returns
        whether a slot was available to spawn."""
        with self._cond:
            before = sum(1 for s in self._state if s == _LIVE)
            self._scale_up_locked(time.monotonic())
            started = sum(1 for s in self._state if s == _LIVE) > before
        self._flush_journal()
        return started

    def scale_down(self, i: int) -> bool:
        """Operator surface: gracefully retire replica `i` (drain →
        hedge in-flight from the journal → retire when idle). Returns
        False when the replica is not LIVE. Unlike the autoscaler this
        does not enforce `min_replicas` — the operator asked."""
        with self._cond:
            if self._state[i] != _LIVE:
                return False
            self._begin_retire_locked(i)
        self._flush_journal()
        return True

    # -- operator surface ------------------------------------------------
    def kill_replica(self, i: int):
        """Drill: the replica's next scheduler handshake raises, its
        thread dies, and the normal crash→failover path runs. (The
        subprocess mode SIGKILLs for real via PADDLE_FAULT=kill@N.)"""
        with self._cond:
            self._kill[i] = True
            self._cond.notify_all()

    def drain(self, i: int, wait: bool = False,
              timeout: Optional[float] = None) -> bool:
        """Stop admitting to replica `i`, re-route its queued (not yet
        started) requests, let in-flight work finish and publish its
        prefixes, then park the replica DRAINED (engine and prefix
        pool stay warm for `refill`). With `wait=True`, block until
        drained; returns whether the replica is drained."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            # operator drain: no hedge (in-flight finishes here), no
            # retire, and the routing summary SURVIVES the park (the
            # pool stays warm for refill())
            self._begin_drain_locked(i, hedge=False, retire=False,
                                     clear_summary=False)
        self._flush_journal()  # re-assignments above, before any wait
        with self._cond:
            if not wait:
                return self._state[i] == _DRAINED
            while self._state[i] == _DRAINING:
                t = (None if deadline is None
                     else deadline - time.monotonic())
                if t is not None and t <= 0.0:
                    break
                self._cond.wait(timeout=t if t is not None else 0.5)
            return self._state[i] == _DRAINED

    def refill(self, i: int):
        """Bring replica `i` back: a DRAINED replica resumes with its
        engine (and hot prefix pool) intact; a DEAD or RETIRED one is
        replaced by a fresh incarnation (cold engine, built against
        the fleet's CURRENT weights version) — the restart half of the
        supervisor's restart/backoff story."""
        with self._cond:
            if self._state[i] == _DRAINED:
                if self._rollout:
                    # the warm engine holds PRE-rollout weights — and
                    # this may be the very replica _swap_replica is
                    # draining: reviving it warm would let the swap
                    # loop skip it and leave old weights serving past
                    # a "completed" rollout. A fresh incarnation
                    # builds against the committed new params instead
                    self._refill_locked(i)
                    return
                self._state[i] = _LIVE
                self._beats[i] = time.monotonic()
                self._cond.notify_all()
            elif self._state[i] in (_DEAD, _RETIRED):
                self._refill_locked(i)

    def _refill_locked(self, i: int):
        self._incarnations[i] += 1
        rep = self._make_replica(i, self._incarnations[i])
        self._replicas[i] = rep
        self._state[i] = _LIVE
        self._beats[i] = time.monotonic()
        # a kill_replica() drill aimed at the DEAD predecessor (it
        # crashed before consuming the flag) must not assassinate the
        # fresh incarnation at its first handshake
        self._kill[i] = False
        self._summaries[i] = set()
        self._rep_stats[i] = None
        self._spawned[i] = time.monotonic()
        self._retire_flag[i] = False
        # health/probe state is the PREDECESSOR's verdict, not the
        # fresh incarnation's (the death path cleared it; the rollout
        # swap of a DEMOTED replica comes through here directly)
        self._slow_since[i] = None
        self._watermark[i] = None
        self._rate[i] = None
        self._stall_since[i] = None
        if self._probes[i] is not None:
            self._handles.pop(self._probes[i].rid, None)
            for fl in self._in_flight:
                fl.pop(self._probes[i].rid, None)
            self._probes[i]._event.set()
            self._probes[i] = None
        self._probe_ok[i] = 0
        self._drop_canary_locked(i)
        self._taint_base[i] = {}
        self._canary_mark[i] = {}
        # starting the thread under the lock is safe: its first _sync
        # blocks on the condition until we release. A controlling
        # scheduler learns the name NOW, synchronously (thread_spawning
        # is non-blocking by contract) — the new thread's own
        # registration happens asynchronously, and an unannounced
        # spawn would race the controller's enabled-set snapshots
        if self._hook is not None:
            self._hook.thread_spawning(
                "r%d.i%d" % (i, self._incarnations[i]))
        rep.start()
        self._cond.notify_all()

    # -- live weight rollout (ISSUE 11) ----------------------------------
    def roll_weights(self, ckpt_step=None, params=None, version=None,
                     policy=None, timeout: float = 120.0,
                     canary_golden=None) -> dict:
        """Roll the whole fleet onto a new weight version with zero
        downtime: rolling drain → swap → refill, one replica at a
        time, the rest keep serving throughout. The pserver push/pull
        cycle recast as checkpoint promotion — training saves
        (`save_weights` / `save_checkpoint`), the sentinel promotes a
        known-good step, serving rolls onto it.

        Candidate selection: `ckpt_step` names a step under the
        fleet's `ckpt_dir` — a weight-publish dir written by
        `save_weights` (a raw training save_checkpoint scope is
        refused at load: its entry names are not serving leaf names).
        The default step is the promoted known-good one from
        `<ckpt_dir>/sentinel.json` (write or copy it into the publish
        dir, or pass the step explicitly — e.g.
        `sentinel.known_good_step(training_dir)`). `params=` bypasses
        disk (tests / in-process handoff) with `version=` tagging it
        (default: previous + 1, resolved inside the rollout latch). A disk candidate is
        CRC-verified with `resume_or_init`'s per-step walk machinery
        BEFORE any replica is touched — a failed verify (or a
        leaf-count/shape mismatch at load) raises `RolloutAborted`
        with the fleet untouched: no replica drained, every replica
        still serving the old version.

        Version fence: the fleet's current version is bumped first, so
        every replica spawned from here on serves the NEW weights;
        each swap is a fresh incarnation (never an in-place mutation),
        every assign/done journal record carries the holder's version,
        and the journal DFA's J009 rejects any done whose version
        differs from its latest assignment's. `policy` pins what
        happens to a swapped replica's in-flight requests: "finish"
        (default) lets them complete on the old version (the drain
        waits — a response's tokens all come from one version);
        "migrate" hedges them to survivors from the journal with
        token-level resume (faster swap; the completion records the
        final holder's version). Returns a summary dict.

        Canary fleets (ISSUE 15): the new version's golden trace is
        computed here for generate()-derivable fleets; an
        explicit-golden fleet (quantized/scripted) must pass the new
        version's known answer via `canary_golden=` — refused
        (RolloutAborted, fleet untouched) otherwise, because judging
        post-rollout canaries against the old answer would quarantine
        healthy replicas in an endless refill loop."""
        policy = policy or self.rollout_policy
        if policy not in ("finish", "migrate"):
            raise ValueError("rollout policy must be 'finish' or "
                             "'migrate', got %r" % (policy,))
        if self.canary_interval_s is not None and not self._canary_auto \
                and canary_golden is None:
            # an explicit-golden fleet (quantized / scripted) cannot
            # have its new version's known answer derived here: without
            # a fresh golden every post-rollout canary would mismatch
            # against the OLD answer and quarantine healthy replicas in
            # an endless refill loop — refuse BEFORE touching anything
            with self._cond:
                self.rollout_aborts += 1
            raise RolloutAborted(
                "this fleet's canaries use an explicit canary_golden "
                "(quantized/scripted engines are not generate()-"
                "derivable): roll_weights needs the NEW version's "
                "golden via canary_golden= — rollout aborted, fleet "
                "untouched")
        if params is not None:
            new_params = params
            # default version (previous + 1) is resolved INSIDE the
            # rollout latch below: reading _weights_version here would
            # let two concurrent roll_weights(params=...) calls both
            # compute the same successor and tag two different weight
            # sets with one version — exactly what the fence forbids
            new_version = None if version is None else int(version)
        else:
            try:
                if self.ckpt_dir is None:
                    raise ValueError(
                        "roll_weights needs the fleet's ckpt_dir knob "
                        "(or explicit params=)")
                step = ckpt_step
                if step is None:
                    from ..distributed.sentinel import known_good_step
                    step = known_good_step(self.ckpt_dir)
                    if step is None:
                        raise RolloutAborted(
                            "no known-good checkpoint step promoted "
                            "under %s — nothing safe to roll to"
                            % self.ckpt_dir)
                from ..distributed.checkpoint import verify_step
                ok, problems = verify_step(self.ckpt_dir, int(step))
                if not ok:
                    raise RolloutAborted(
                        "candidate checkpoint step %d failed "
                        "verification (%s) — rollout aborted, fleet "
                        "untouched" % (int(step), "; ".join(problems)),
                        problems=problems)
                new_params = self._load_weights(int(step))
            except RolloutAborted:
                with self._cond:
                    self.rollout_aborts += 1
                raise
            new_version = (int(version) if version is not None
                           else int(step))
        with self._cond:
            if self._closing:
                raise RuntimeError("fleet is closed")
            if self._rollout:
                raise RuntimeError("a weight rollout is already in "
                                   "progress")
            self._rollout = True  # pauses the autoscaler too
            old_version = self._weights_version
            if new_version is None:
                new_version = old_version + 1
            # committed FIRST: every refill/spawn from here on builds
            # against the new weights — the rollout can only move
            # forward, a mid-rollout death refills onto the new version
            self._params = new_params
            self._weights_version = new_version
            targets = [i for i in range(self.max_replicas)
                       if self._state[i] in (_LIVE, _DEMOTED,
                                             _DRAINING, _DRAINED)]
        # known-answer canaries (ISSUE 15): the golden trace is per
        # weights_version, computed at rollout COMMIT — a canary
        # completing on an old-version replica mid-rollout is judged
        # against ITS version's golden (the replica carries the
        # version; _golden_for keys on it), never the new one's.
        # Computed OUTSIDE the lock (a generate() compile must not
        # stall handshakes); explicit-golden fleets passed the new
        # answer in (validated up top — refused otherwise)
        if self.canary_interval_s is not None:
            golden = ([int(t) for t in canary_golden]
                      if canary_golden is not None
                      else golden_trace(new_params, self._cfg,
                                        self._canary_prompt,
                                        self.canary_max_new))
            with self._cond:
                self._canary_golden[int(new_version)] = golden
        try:
            for i in targets:
                self._swap_replica(i, policy, timeout)
        finally:
            with self._cond:
                self._rollout = False
                self._cond.notify_all()
            self._flush_journal()
        with self._cond:
            self.rollouts_completed += 1
        return {"version": new_version, "previous_version": old_version,
                "replicas_swapped": len(targets), "policy": policy}

    def _swap_replica(self, i: int, policy: str, timeout: float):
        """One rolling-swap step: drain replica `i` (policy-dependent:
        wait for in-flight on "finish", hedge it away on "migrate"),
        then replace it with a fresh incarnation built against the
        fleet's new current weights. DEMOTED/DRAINED replicas carry no
        work and swap immediately; a replica that DIES mid-drain is
        refilled the same way (failover already rescued its work)."""
        deadline = time.monotonic() + timeout
        hook = self._hook
        if hook is not None:
            # schedule-exploration seam (ISSUE 9/11): the swap of each
            # replica is a yield point, so the explorer can interleave
            # replica handshakes, migrations, and the rollout
            hook.yield_point("rollout:swap:%d" % i)
        with self._cond:
            if self._closing:
                raise RuntimeError(
                    "fleet closed during rollout: replica %d left "
                    "unswapped" % i)
            if self._state[i] == _LIVE:
                self._begin_drain_locked(i, hedge=(policy == "migrate"),
                                         retire=False)
        self._flush_journal()  # re-assignments from the drain begin
        while True:
            with self._cond:
                if self._closing:
                    # close() strands a DRAINING replica (its handshake
                    # stops without transitioning the state, and the
                    # monitor exits): waiting out the timeout here —
                    # or refilling a fresh thread on a closed fleet —
                    # would be worse than the honest error
                    raise RuntimeError(
                        "fleet closed during rollout: replica %d left "
                        "unswapped" % i)
                st = self._state[i]
                if st != _DRAINING:
                    if st in (_DRAINED, _DEMOTED, _DEAD):
                        self._refill_locked(i)
                    break
                t = deadline - time.monotonic()
                if t <= 0.0:
                    raise RuntimeError(
                        "rollout: replica %d failed to drain within "
                        "%.1fs (in-flight work still running on the "
                        "old version; policy='migrate' hedges it away "
                        "instead of waiting)" % (i, timeout))
                if hook is None:
                    self._cond.wait(timeout=min(t, 0.5))
            if hook is not None:
                # park OUTSIDE the lock: a controlled scheduler must be
                # able to run the draining replica's handshakes while
                # the rollout waits (and replay the interleaving)
                hook.yield_point("rollout:wait:%d" % i)
        self._flush_journal()

    def _load_weights(self, step: int):
        """Load one VERIFIED checkpoint step into a fresh params
        pytree shaped exactly like the construction params. Positional
        leaf naming (`save_weights` is the writer); a checkpoint whose
        leaf count or shapes disagree is a `RolloutAborted`, never a
        silent misload."""
        import jax

        from ..distributed.checkpoint import load_checkpoint

        names, leaves, treedef = _flat_names(self._params)
        arrays: Dict[str, Any] = {}
        load_checkpoint(_FlatScope(arrays), self.ckpt_dir, step=int(step))
        if sorted(arrays) != names:
            foreign = sorted(set(arrays) - set(names))
            if foreign:
                # entry names are not save_weights' positional leaf
                # names: this is some other checkpoint (e.g. a raw
                # training save_checkpoint scope) — name the REAL
                # mismatch, not a leaf count that may coincide
                raise RolloutAborted(
                    "checkpoint step %d was not written by "
                    "save_weights (entries like %r, expected "
                    "positional leaf names w00000...w%05d) — publish "
                    "serving weight sets with save_weights(params, "
                    "ckpt_dir, step)" % (int(step), foreign[0],
                                         len(names) - 1))
            raise RolloutAborted(
                "checkpoint step %d holds %d weight leaf(s), the "
                "serving model has %d — not a weight set for this "
                "model" % (int(step), len(arrays), len(names)))
        new_leaves = []
        for n, old in zip(names, leaves):
            new = arrays[n]
            if tuple(np.shape(new)) != tuple(np.shape(old)):
                raise RolloutAborted(
                    "checkpoint step %d leaf %s has shape %r, the "
                    "serving model expects %r" % (int(step), n,
                                                  tuple(np.shape(new)),
                                                  tuple(np.shape(old))))
            new_leaves.append(new)
        return jax.tree_util.tree_unflatten(treedef, new_leaves)

    def _describe(self, rid: int) -> dict:
        """Operator context for one request (FleetTimeout satellite):
        journal state (queued / assigned / decoding / terminal), the
        replica holding the latest assignment, and tokens emitted."""
        with self._cond:
            emitted = len(self._journal.progress_of(rid))
            a = self._journal.assigned_to(rid)
            replica = a[0] if a else None
            if rid in self._cancelled_rids:
                state = "cancelled"
            elif rid in self._done_rids:
                state = "terminal"
            elif any(h.rid == rid for q in self._inbox for h in q):
                state = "queued"
            elif any(rid in fl for fl in self._in_flight):
                state = "decoding" if emitted else "assigned"
            elif rid in self._open:
                state = "open"
            else:
                state = "unknown"
            rep_state = None
            if a is not None:
                for i, rep in enumerate(self._replicas):
                    if rep.name == a[0]:
                        rep_state = self._state[i]
                        break
            desc = "journal state: %s" % state
            if replica is not None:
                desc += ", assigned to %s (incarnation %d, gen %d%s)" % (
                    a[0], a[1], a[2],
                    "" if rep_state is None else ", replica %s" % rep_state)
            # wire side-band (ISSUE 18 small fix): name the connection
            # and stream cursor so a wire-level FleetTimeout is
            # debuggable from the CLIENT side — which socket owns the
            # stalled request, and how much of the stream it already
            # has (a delivered-vs-journaled gap points at the wire,
            # an emitted-vs-budget gap at the fleet)
            h = self._handles.get(rid)
            conn = None if h is None else h.conn
            streaming = bool(h is not None and h.streaming)
            stream_sent = 0 if h is None else h._stream_sent
            if conn is not None:
                desc += ", wire conn %s" % conn
            if streaming:
                desc += (", streaming (%d of %d journaled token(s) "
                         "delivered)" % (stream_sent, emitted))
            return {"state": state, "replica": replica,
                    "tokens_emitted": emitted, "conn": conn,
                    "streaming": streaming, "stream_sent": stream_sent,
                    "describe": desc}

    def wait_all(self, timeout: Optional[float] = None) -> bool:
        """Block until no request is open (completed, rejected, or
        failed). Returns False on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._open:
                t = (None if deadline is None
                     else max(0.0, deadline - time.monotonic()))
                if t is not None and t <= 0.0:
                    return False
                self._cond.wait(timeout=t if t is not None else 0.5)
            return True

    def stats(self) -> dict:
        with self._cond:
            base = self._stats_base
            hits = base.get("prefix_hits", 0)
            misses = base.get("prefix_misses", 0)
            saved = base.get("prefix_tokens_saved", 0)
            tokens_out = base.get("tokens_out", 0)
            prefill_tok = base.get("prefill_tokens_computed", 0)
            blocks_in_use = 0  # gauge: live replicas only
            cow = base.get("cow_blocks", 0)
            spec_drafted = base.get("spec_drafted", 0)
            spec_accepted = base.get("spec_accepted", 0)
            ad_hits = base.get("adapter_hits", 0)
            ad_misses = base.get("adapter_misses", 0)
            ad_evictions = base.get("adapter_evictions", 0)
            ad_uploads = base.get("adapter_uploads", 0)
            fp_committed = base.get("fp_committed", 0)
            fp_verified = base.get("fp_verified", 0)
            fp_mismatches = base.get("fp_mismatches", 0)
            # durable-KV counters (ISSUE 16): same fold discipline
            ho_keys = ("tokens_recomputed_at_migration",
                       "handoff_imports", "handoff_blocks_imported",
                       "handoff_tokens_imported", "handoff_fallbacks",
                       "store_spilled_blocks", "store_warm_blocks",
                       "store_quarantined")
            ho_sums = {k: base.get(k, 0) for k in ho_keys}
            reps = []
            for i, rep in enumerate(self._replicas):
                st = self._rep_stats[i] or {}
                hits += st.get("prefix_hits", 0)
                misses += st.get("prefix_misses", 0)
                saved += st.get("prefix_tokens_saved", 0)
                tokens_out += st.get("tokens_out", 0)
                prefill_tok += st.get("prefill_tokens_computed", 0)
                if self._state[i] == _LIVE:
                    blocks_in_use += st.get("kv_blocks_in_use", 0)
                cow += st.get("cow_blocks", 0)
                spec_drafted += st.get("spec_drafted", 0)
                spec_accepted += st.get("spec_accepted", 0)
                ad_hits += st.get("adapter_hits", 0)
                ad_misses += st.get("adapter_misses", 0)
                ad_evictions += st.get("adapter_evictions", 0)
                ad_uploads += st.get("adapter_uploads", 0)
                fp_committed += st.get("fp_committed", 0)
                fp_verified += st.get("fp_verified", 0)
                fp_mismatches += st.get("fp_mismatches", 0)
                for k in ho_keys:
                    ho_sums[k] += st.get(k, 0)
                reps.append({
                    "name": rep.name, "slo": rep.slo,
                    "tier": rep.tier,
                    "state": self._state[i],
                    "incarnation": rep.incarnation,
                    # gauge (ISSUE 11 satellite): which weight version
                    # this incarnation serves
                    "weights_version": rep.weights_version,
                    # gauge (ISSUE 13 satellite): which paged-attention
                    # kernel this incarnation's compiled steps attend
                    # with (from the engine's own metrics snapshot)
                    "paged_kernel": st.get("paged_kernel"),
                    # gauges (ISSUE 14 satellite): the replica's KV
                    # and weight storage dtypes — uniform across the
                    # fleet by construction (mixed quant is refused at
                    # spawn), surfaced per row as the audit trail
                    "kv_quant": st.get("kv_quant"),
                    "weight_quant": st.get("weight_quant"),
                    "load": len(self._inbox[i]) + len(self._in_flight[i]),
                    "stats": st,
                })
            total = hits + misses
            return {
                "submitted": self.submitted,
                "completed": self.completed,
                "shed": self.shed,
                "rejected": self.rejected,
                "expired": self.expired,
                "expired_on_arrival": self.expired_on_arrival,
                "quota_shed": self.quota_shed,
                "batch_jobs_completed": self.batch_jobs_completed,
                "wfq_depth": 0 if self._wfq is None else len(self._wfq),
                "resubmitted": self.resubmitted,
                "failovers": self.failovers,
                "zombie_refused": self.zombie_refused,
                "duplicate_refused": self.duplicate_refused,
                "demotions": self.demotions,
                "restores": self.restores,
                "probes_sent": self.probes_sent,
                "resumed_requests": self.resumed_requests,
                "resumed_tokens": self.resumed_tokens,
                # elastic lifecycle (ISSUE 11): fleet-scope monotonic
                # counters (they never fold or reset — a retired
                # replica's history is already in _stats_base)
                "replicas_spawned": self.replicas_spawned,
                "replicas_retired": self.replicas_retired,
                "migrations": self.migrations,
                "rollouts_completed": self.rollouts_completed,
                "rollout_aborts": self.rollout_aborts,
                # serving-integrity counters (ISSUE 15)
                "integrity_trips": self.integrity_trips,
                "integrity_trip_kinds": dict(self.integrity_trip_kinds),
                "canaries_sent": self.canaries_sent,
                "canaries_ok": self.canaries_ok,
                "canary_mismatches": self.canary_mismatches,
                "tainted_tokens": self.tainted_tokens,
                "fp_committed": fp_committed,
                "fp_verified": fp_verified,
                "fp_mismatches": fp_mismatches,
                # durable-KV tier (ISSUE 16): fleet-scope package
                # counters plus the per-replica sums folded above; the
                # shared store reports its own record/byte counters
                "handoff_packages": self.handoff_packages,
                "handoff_fallbacks_defaulted":
                    self.handoff_fallbacks_defaulted,
                "tokens_recomputed_at_migration":
                    ho_sums["tokens_recomputed_at_migration"],
                "handoff_imports": ho_sums["handoff_imports"],
                "handoff_blocks_imported":
                    ho_sums["handoff_blocks_imported"],
                "handoff_tokens_imported":
                    ho_sums["handoff_tokens_imported"],
                "handoff_fallbacks": ho_sums["handoff_fallbacks"],
                "store_spilled_blocks": ho_sums["store_spilled_blocks"],
                "store_warm_blocks": ho_sums["store_warm_blocks"],
                "store_quarantined": ho_sums["store_quarantined"],
                "kv_store": (None if self.kv_store is None
                             else self.kv_store.stats()),
                "weights_version": self._weights_version,
                "replicas_live": sum(
                    1 for s in self._state if s == _LIVE),
                "open": len(self._open),
                # client cancels are terminal verdicts too (ISSUE 18):
                # folded in so lost==0 stays the exactly-once bar
                # under disconnect drills
                "cancelled": self.cancelled,
                "cancel_late_refused": self.cancel_late_refused,
                "lost": self.submitted - self.completed - self.rejected
                - self.expired - self.cancelled - len(self._open),
                "tokens_out": tokens_out,
                "prefill_tokens_computed": prefill_tok,
                "prefix_hit_rate": round(hits / total, 4) if total else None,
                "prefix_tokens_saved": saved,
                "kv_blocks_in_use": blocks_in_use,
                "cow_blocks": cow,
                "spec_drafted": spec_drafted,
                "spec_accepted": spec_accepted,
                "spec_accept_rate": round(spec_accepted / spec_drafted, 4)
                if spec_drafted else None,
                "adapter_hits": ad_hits,
                "adapter_misses": ad_misses,
                "adapter_evictions": ad_evictions,
                "adapter_uploads": ad_uploads,
                # per-tenant O(1) metrics (ISSUE 12): quota buckets,
                # shed counts, completions, tokens served per tenant
                "tenants": (None if self._tenants is None
                            else self._tenants.snapshot()),
                "replicas": reps,
            }

    def close(self, timeout: float = 10.0):
        """Stop every replica and the monitor; fail any still-open
        handle with `EngineFailed` (their waiters must not block on a
        dead fleet) and write it a TERMINAL journal record — the
        journal invariant (ISSUE 8): after close, every journaled rid
        is done, rejected, or expired; none is ever silently open."""
        with self._cond:
            if self._closing:
                return
            self._closing = True
            for rid in list(self._open):
                h = self._reject_locked(
                    rid, "fleet closed",
                    error=EngineFailed(
                        "fleet closed with request %d pending" % rid,
                        replica=None))
                if h is not None and not h.done:
                    h._event.set()  # waiters must not block on a dead fleet
                    # stream iterators must not either: close directly
                    # (idempotent — the deferred close the reject
                    # queued is a no-op at the final flush)
                    h._stream_feed([], True)
            self._open.clear()
            if self._wfq is not None:
                # queued-but-undispatched entries: their rids were in
                # _open, so the sweep above already rejected them —
                # drop the stale heap entries
                self._wfq.clear()
            for i, ph in enumerate(self._probes):
                if ph is not None:  # outstanding probes die unjournaled
                    self._handles.pop(ph.rid, None)
                    ph._event.set()
                    self._probes[i] = None
            for i, ch in enumerate(self._canaries):
                if ch is not None:  # outstanding canaries likewise
                    self._handles.pop(ch.rid, None)
                    ch._event.set()
                    self._canaries[i] = None
            self._cond.notify_all()
        self._monitor.join(timeout=timeout)
        for rep in list(self._replicas):
            # a held-back slot's replica thread may never have started
            if rep.thread.ident is not None:
                rep.thread.join(timeout=timeout)
        self._flush_journal()  # stragglers from the final syncs
        self._journal.close()
        if self._kv_store_owned and self.kv_store is not None:
            # a store the fleet BUILT (kv_store_dir/kv_store_bytes
            # knobs) closes with the fleet; a caller-provided store is
            # the caller's to close — it may warm the next fleet
            self.kv_store.close()
        # opt-in self-audit (ISSUE 9): replay the journal file through
        # the protocol DFA so every fleet test / bench run that sets
        # the env var double-checks its own history for free. A journal
        # this fleet OPENED pre-existing keeps its predecessor's open
        # rids (a restarted front door resubmits them under new rids),
        # so only a journal born in this process asserts the
        # everything-terminal close() invariant
        if self._journal.path and os.environ.get(
                "PADDLE_TPU_AUDIT_JOURNAL") == "1":
            from ..analysis.protocol_lint import (JournalViolation,
                                                  verify_journal)
            diags = verify_journal(
                self._journal.path,
                expect_closed=not self._journal.preexisting)
            if diags:
                raise JournalViolation(self._journal.path, diags)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# subprocess mode: real-process kill drills through the PR-1 control plane
# ---------------------------------------------------------------------------

def run_fleet_subprocess(argv_for, worker_ids, requests,
                         lease_timeout_s=15.0, heartbeat_timeout_s=15.0,
                         env_for=None, deadline_s=240.0,
                         supervisor_kw=None):
    """Serve `requests` (journal-form spec dicts) through N worker
    SUBPROCESSES (tests/fleet_worker.py is the reference worker): the
    requests become Coordinator task leases, the workers run a real
    `ServingEngine` each (`step()` ticks PADDLE_FAULT, so `kill@N`
    SIGKILLs mid-decode), and `distributed/supervisor.py` restarts
    casualties. Fault tolerance is exactly the PR-1 story: a dead
    worker's leases time out and requeue to survivors (no request
    lost), lease GENERATIONS fence a zombie's late `task_finished` (no
    request acked twice), and results are written atomically per rid.

    `argv_for(worker_id, coordinator_address)` builds one worker's
    command line; result files land wherever the caller's argv points
    the workers. Returns {"report": supervisor report, "coordinator":
    queue counts} — `coordinator["done"] == len(requests)` with
    `discarded == 0` is the no-lost-request check, and lease fencing
    means each rid was acked exactly once.
    """
    from ..distributed.coordinator import Coordinator, CoordinatorServer
    from ..distributed.supervisor import Supervisor

    coord = Coordinator(timeout_s=lease_timeout_s, failure_max=10,
                        heartbeat_timeout_s=heartbeat_timeout_s)
    coord.set_dataset([dict(spec, rid=i)
                       for i, spec in enumerate(requests)])
    server = CoordinatorServer(coord).start()
    try:
        sup = Supervisor(
            lambda wid: argv_for(wid, server.address), worker_ids,
            env_for=env_for, coordinator=coord,
            **(supervisor_kw or {}))
        report = sup.run(deadline_s=deadline_s)
    finally:
        server.stop()
    return {
        "report": report,
        "coordinator": {
            "done": len(coord.done), "todo": len(coord.todo),
            "pending": len(coord.pending),
            "discarded": len(coord.discarded),
        },
    }
