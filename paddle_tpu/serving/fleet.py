"""Fault-tolerant serving fleet: N supervised `ServingEngine` replicas
behind one front door (ISSUE 6; ROADMAP item 3).

The reference's cloud layer exists so that *training* survives any
single process dying: the Go master leases tasks with timeouts and
fencing, etcd TTL keys detect dead trainers, and the cluster controller
respawns them (go/master/service.go, go/pserver/etcd_client.go). PR 1
rebuilt those primitives for trainers — coordinator heartbeats,
incarnation-fenced membership, lease generations, supervisor
restart/backoff. This module points the same control plane at
*inference*: one `ServingFleet` owns N engine replicas (in-process
threads here; a subprocess mode through `distributed/supervisor.py`
below for kill drills), and a crash mid-decode loses nothing.

Guarantees (the PR-1 drills' falsifiability bar, recast for serving):

  * No request lost — every `submit()` lands in a durable REQUEST
    JOURNAL before it is routed; when a replica dies (crash, hang past
    the heartbeat deadline, or drill kill), its queued + in-flight
    requests are recovered FROM THE JOURNAL and resubmitted to
    survivors. Outputs are token-identical to sequential `generate()`
    no matter which replica (or how many replicas, in sequence) ran
    the request: the engine's per-request sampling keys depend only on
    (seed, token index), never on slot or replica assignment.
  * No request answered twice — completions are deduplicated by
    request id, and a result reported by a replica that has been
    declared dead is REFUSED (incarnation fencing: the registered
    replica object + its incarnation are the liveness lease, exactly
    the zombie-holder rule the coordinator's task leases enforce). A
    stalled replica that wakes after failover cannot overwrite the
    survivor's answer.
  * Bounded admission — at most `max_pending` requests may be open
    (queued + in-flight) fleet-wide; past that `submit()` raises
    `FleetSaturated` instead of growing an unbounded queue. Explicit
    load-shed is the backpressure contract: the CALLER decides what to
    drop, the fleet never hides an hour of queue wait.
  * Prefix-affinity routing — each replica's engine publishes a
    host-side SUMMARY of its prefix pool (chained-crc block keys,
    `prefix_cache.chain_keys`); routing sends a prompt to the replica
    whose pool holds its longest cached prefix (ties: least loaded),
    so shared-header families keep hitting the replica whose blocks
    are hot and PR 4's prefill deletion becomes a fleet-wide number
    (RadixAttention-style reuse, now across replicas).
  * Drain/refill — `drain(i)` stops admitting to a replica, finishes
    its in-flight work (publishing prefixes back to its pool as every
    completed prefill does), then parks it; `refill(i)` brings a
    DRAINED replica back with its engine — and prefix pool — warm, or
    replaces a DEAD one with a fresh incarnation. Planned restarts
    lose neither requests nor the hot prefix working set.
  * SLO classes — `replica_slo` maps each replica to a class
    ("interactive"/"batch"), and `slo_classes` maps the class onto the
    engine's `max_prefills_per_step` (interactive = 1: flattest decode
    latency; batch = None: maximum prefill throughput). `submit(slo=)`
    routes within the class, falling back to any live replica before
    failing — SLO is a preference, survival is a guarantee.

Threading: all shared scheduler state lives on `ServingFleet` and is
guarded by ONE condition's lock (`_cond`); replica threads and the
monitor thread touch it only through fleet methods that take it.
Engines (and their prefix tries) are confined to their replica's
thread — the router sees pools only through the immutable summary sets
handed over under the lock.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from .engine import EngineFailed, ServingEngine
from .prefix_cache import chain_keys

__all__ = [
    "ServingFleet", "FleetHandle", "FleetSaturated", "RequestJournal",
    "run_fleet_subprocess",
]

# replica lifecycle states
_LIVE, _DRAINING, _DRAINED, _DEAD = "live", "draining", "drained", "dead"

_DEFAULT_SLO_CLASSES = {
    # interactive: one prefill chunk per step fleet-wide per replica —
    # the flattest decode latency for that replica's neighbors (TTFT of
    # long prompts pays); batch: every pending slot advances (highest
    # prefill throughput, decode latency of neighbors pays)
    "interactive": {"max_prefills_per_step": 1},
    "batch": {"max_prefills_per_step": None},
}


class FleetSaturated(RuntimeError):
    """`submit()` refused: the fleet already holds `max_pending` open
    requests. Explicit load-shed — retry later or scale out; the fleet
    never grows an unbounded admission queue."""


class _KillDrill(RuntimeError):
    """Injected replica death (ServingFleet.kill_replica)."""


class FleetHandle(object):
    """Per-request future filled in by whichever replica completes the
    request (possibly a survivor after failover). Thread-safe: waiters
    block on an event, never by driving an engine."""

    def __init__(self, rid: int, prompt: np.ndarray, spec: dict,
                 slo: Optional[str]):
        self.rid = rid
        self.prompt = prompt  # np.int32 [T0]
        self.spec = spec      # JSON-able request record (journal form)
        self.slo = slo
        self.generation = 0   # bumped on every resubmission
        self.tokens: Optional[List[int]] = None
        self.replica: Optional[str] = None  # who answered
        self.error: Optional[BaseException] = None
        self.chain: List[int] = []  # affinity keys (set by the fleet)
        self._event = threading.Event()

    @property
    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block until the request completes somewhere in the fleet;
        returns prompt + generated tokens. Raises `EngineFailed` if the
        fleet lost every replica (or was closed) with this request
        pending, `TimeoutError` on timeout."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                "request %d not completed within %r s" % (self.rid, timeout))
        if self.error is not None:
            raise self.error
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)])


class RequestJournal(object):
    """Durable request table: every submit/assign/done/rejected
    transition is appended (JSON lines) BEFORE the fleet acts on it,
    and mirrored in memory as the authoritative OPEN-request index
    (terminal records prune their mirror entries, so memory is bounded
    by in-flight work, not lifetime traffic). Failover reads the
    journal mirror — `lost(replica, incarnation)` — not scheduler
    guesswork. Opening an EXISTING journal replays it: the mirror
    resumes the open set and `next_rid()` continues past every rid
    ever issued, so a restarted front door appending to the same file
    can never collide with (and thereby corrupt) the history.
    `path=None` keeps the mirror only (tests); `recover(path)` is the
    read-only restart helper.

    Durability: records are flushed per append (they survive any
    process death — the failure mode the fleet handles). `fsync=True`
    additionally fsyncs each record for OS-crash/power-loss
    durability, at per-request disk latency cost."""

    def __init__(self, path: Optional[str] = None, fsync: bool = False):
        self._lock = threading.Lock()
        self.path = path
        self.fsync = bool(fsync)
        self._open_specs: Dict[int, dict] = {}       # guarded-by: _lock
        self._assign: Dict[int, Tuple[str, int, int]] = {}  # guarded-by: _lock
        self._done: Set[int] = set()                 # guarded-by: _lock
        self._max_rid = -1                           # guarded-by: _lock
        if path and os.path.exists(path):
            self._replay_and_heal(path)
        self._f = open(path, "a") if path else None  # guarded-by: _lock

    @staticmethod
    def _read(path: str):
        """Parse a journal file, tolerating a TORN FINAL line (the
        process died mid-append — the crash this journal exists to
        survive must not make it unreadable). A malformed line
        followed by valid records is real corruption and raises."""
        pending_error = None
        with open(path) as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                if pending_error is not None:
                    raise ValueError(
                        "corrupt journal %s: unparseable line %d is "
                        "not a torn tail" % (path, pending_error))
                try:
                    rec = json.loads(line)
                except ValueError:
                    pending_error = lineno  # torn IF nothing follows
                    continue
                yield rec

    def _replay_and_heal(self, path: str):
        """Replay an existing journal into the mirror and TRUNCATE a
        torn final line: reopening in append mode would otherwise glue
        the next record onto the partial text, turning a tolerated
        torn tail into mid-file corruption for every later reader."""
        good_end = 0
        torn_at = None
        with open(path, "rb") as f:
            for lineno, raw in enumerate(f.readlines(), 1):
                line = raw.decode("utf-8", "replace").strip()
                if not line:
                    if torn_at is None:
                        good_end += len(raw)
                    continue
                if torn_at is not None:
                    raise ValueError(
                        "corrupt journal %s: unparseable line %d is "
                        "not a torn tail" % (path, torn_at))
                try:
                    rec = json.loads(line)
                except ValueError:
                    torn_at = lineno
                    continue
                self._replay(rec)
                good_end += len(raw)
        if torn_at is not None:
            with open(path, "r+b") as f:
                f.truncate(good_end)

    def _replay(self, rec: dict):
        rid = rec["rid"]
        self._max_rid = max(self._max_rid, rid)
        if rec["kind"] == "submit":
            self._open_specs[rid] = rec["spec"]
        elif rec["kind"] == "assign":
            self._assign[rid] = (rec["replica"], rec["incarnation"],
                                 rec["gen"])
        elif rec["kind"] in ("done", "rejected"):
            self._done.add(rid)
            self._open_specs.pop(rid, None)
            self._assign.pop(rid, None)

    def _append(self, rec: dict):
        if self._f is not None:
            self._f.write(json.dumps(rec) + "\n")
            self._f.flush()
            if self.fsync:
                os.fsync(self._f.fileno())

    def next_rid(self) -> int:
        """First rid safe to issue: past everything this journal file
        has ever seen (restart-collision guard)."""
        with self._lock:
            return self._max_rid + 1

    def submit(self, rid: int, spec: dict):
        with self._lock:
            self._open_specs[rid] = spec
            self._max_rid = max(self._max_rid, rid)
            self._append({"kind": "submit", "rid": rid, "spec": spec})

    def assign(self, rid: int, replica: str, incarnation: int, gen: int,
               defer: bool = False) -> Optional[dict]:
        """Record an assignment. The MIRROR updates synchronously (a
        failover consulting `lost()` an instant later must see it);
        with `defer=True` the file append is returned as a record for
        the caller to `write()` later — the fleet defers file I/O
        until it has released its scheduler lock."""
        rec = {"kind": "assign", "rid": rid, "replica": replica,
               "incarnation": incarnation, "gen": gen}
        with self._lock:
            self._assign[rid] = (replica, incarnation, gen)
            if defer:
                return rec
            self._append(rec)
        return None

    def complete(self, rid: int, replica: str, incarnation: int,
                 gen: int, tokens: List[int],
                 defer: bool = False) -> Optional[dict]:
        rec = {"kind": "done", "rid": rid, "replica": replica,
               "incarnation": incarnation, "gen": gen,
               "tokens": list(tokens)}
        with self._lock:
            self._done.add(rid)
            self._open_specs.pop(rid, None)
            self._assign.pop(rid, None)
            if defer:
                return rec
            self._append(rec)
        return None

    def write(self, recs: List[dict]):
        """File-append records whose mirror updates already happened
        (the deferred half of assign/complete)."""
        with self._lock:
            for rec in recs:
                self._append(rec)

    def reject(self, rid: int, reason: str,
               defer: bool = False) -> Optional[dict]:
        """Terminal record for a request that can never complete (a
        malformed spec an engine refused, or no live replica to serve
        it): without it the rid would stay open forever and every
        future recover() would resubmit an unservable request."""
        rec = {"kind": "rejected", "rid": rid, "reason": reason}
        with self._lock:
            self._done.add(rid)
            self._open_specs.pop(rid, None)
            self._assign.pop(rid, None)
            if defer:
                return rec
            self._append(rec)
        return None

    def lost(self, replica: str, incarnation: int) -> List[Tuple[int, dict, int]]:
        """(rid, spec, gen) of every OPEN request whose latest
        assignment is (replica, incarnation) — the set a failover must
        resubmit."""
        with self._lock:
            out = []
            for rid, (rep, inc, gen) in sorted(self._assign.items()):
                if rep == replica and inc == incarnation \
                        and rid in self._open_specs:
                    out.append((rid, self._open_specs[rid], gen))
            return out

    def open_count(self) -> int:
        with self._lock:
            return len(self._open_specs)

    def is_done(self, rid: int) -> bool:
        with self._lock:
            return rid in self._done

    def close(self):
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    @staticmethod
    def recover(path: str) -> List[Tuple[int, dict]]:
        """Rebuild the incomplete-request list from a journal file:
        (rid, spec) for every submitted rid with no terminal
        (done/rejected) record, in submission order. A restarted front
        door resubmits exactly these — requests survive even a full
        fleet-process crash."""
        specs: Dict[int, dict] = {}
        done: Set[int] = set()
        for rec in RequestJournal._read(path):
            if rec["kind"] == "submit":
                specs[rec["rid"]] = rec["spec"]
            elif rec["kind"] in ("done", "rejected"):
                done.add(rec["rid"])
        return [(rid, specs[rid]) for rid in sorted(specs)
                if rid not in done]


class _Replica(object):
    """One engine replica: a thread that builds and exclusively owns a
    `ServingEngine`, pulls work from the fleet, steps, and reports
    completions. Identity (object + incarnation) IS the liveness lease
    the fleet fences on. Everything here is confined to the replica
    thread; the fleet reads only the immutable fields (name, index,
    incarnation, slo)."""

    def __init__(self, fleet: "ServingFleet", index: int, incarnation: int,
                 slo: Optional[str], engine_kw: dict):
        self.index = index
        self.incarnation = incarnation
        self.slo = slo
        self.name = "r%d" % index
        self._fleet = fleet
        self._engine_kw = engine_kw
        self.engine: Optional[ServingEngine] = None  # guarded-by: replica
        self._serving: Dict[int, Any] = {}           # guarded-by: replica
        self._pool_rev = (0, 0)                      # guarded-by: replica
        self.thread = threading.Thread(
            target=self._loop, name="fleet-%s-i%d" % (self.name, incarnation),
            daemon=True)

    def start(self):
        self.thread.start()
        return self

    def _idle(self) -> bool:  # thread: replica
        e = self.engine
        return (not self._serving and e is not None
                and not e.live_slots and not e.queue_depth
                and not e.prefilling_slots)

    def _pool_summary(self):  # thread: replica
        """Rebuild the routing summary only when the pool changed (the
        trie is thread-confined here; the summary set handed to the
        fleet is immutable)."""
        pc = self.engine.prefix_cache
        if pc is None:
            return None
        rev = (pc.inserted_blocks, pc.evictions)
        if rev == self._pool_rev:
            return None
        self._pool_rev = rev
        return pc.summary()

    def _loop(self):  # thread: replica
        fleet = self._fleet
        try:
            self.engine = ServingEngine(
                fleet._params, fleet._cfg, replica_id=self.name,
                **self._engine_kw)
            completed: List[Tuple[int, List[int]]] = []
            while True:
                cmd, work = fleet._sync(
                    self, completed, idle=self._idle(),
                    summary=self._pool_summary(), stats=self._stats())
                completed = []
                if cmd == "stop":
                    return
                for h in work:
                    try:
                        sh = self.engine.submit(
                            h.prompt, h.spec["max_new_tokens"],
                            temperature=h.spec["temperature"],
                            eos_id=h.spec["eos_id"], seed=h.spec["seed"],
                            publish_len=h.spec["publish_len"])
                    except ValueError as exc:
                        # a malformed request must fail ITSELF, not
                        # crash-loop the replica through failover
                        fleet._reject(h.rid, exc)
                        continue
                    self._serving[h.rid] = sh
                if not self._idle():
                    self.engine.step()
                for rid, sh in list(self._serving.items()):
                    if sh.done:
                        completed.append((rid, list(sh.tokens)))
                        del self._serving[rid]
        except Exception as exc:  # crash -> failover (incl. _KillDrill)
            if self.engine is not None:
                self.engine.abort(exc)
            self._fleet._on_crash(self, exc)

    def _stats(self) -> Optional[dict]:  # thread: replica
        e = self.engine
        if e is None:
            return None
        m = e.metrics
        out = {
            "tokens_out": m.tokens_out,
            "decode_steps": m.decode_steps,
            "prefills": m.prefills,
            "prefill_tokens_computed": m.prefill_tokens_computed,
            # ISSUE 7 block-pool / spec counters: the cumulative ones
            # fold into the fleet's _stats_base on replica death like
            # every other int here; kv_blocks_in_use is a GAUGE (a dead
            # replica's pool is gone), summed over LIVE snapshots only
            "kv_blocks_in_use": m.kv_blocks_in_use,
            "kv_blocks_freed_at_retire": m.kv_blocks_freed_at_retire,
            "kv_tail_blocks_freed": m.kv_tail_blocks_freed,
            "cow_blocks": m.cow_blocks,
            "spec_drafted": m.spec_drafted,
            "spec_accepted": m.spec_accepted,
        }
        if e.prefix_cache is not None:
            out["prefix_hits"] = e.prefix_cache.hits
            out["prefix_misses"] = e.prefix_cache.misses
            out["prefix_tokens_saved"] = e.prefix_cache.tokens_saved
        return out


class ServingFleet(object):
    """Front door over N `ServingEngine` replica threads. Knobs:

      n_replicas           engine replicas (threads; one engine each)
      journal_path         durable request journal (None = in-memory
                           mirror only — failover still exact, but a
                           whole-process crash loses the table); an
                           existing file is replayed, so a restarted
                           front door resumes rids past its history
      journal_fsync        fsync every journal record (OS-crash
                           durability) instead of flush-only
                           (process-crash durability, the default —
                           fsync costs per-request disk latency)
      max_pending          fleet-wide bound on OPEN requests; past it
                           submit() raises FleetSaturated (load-shed)
      heartbeat_timeout_s  replica declared dead after this long
                           without a scheduler-loop heartbeat; size it
                           a few times the worst single engine step
                           (first-compile included!) or a busy replica
                           reads as dead (README sizing rule)
      affinity             prefix-affinity routing on/off (off =
                           least-loaded only)
      replica_slo          per-replica SLO class name list
                           ("interactive"/"batch"; None entry = serves
                           any class); default: all wildcard
      slo_classes          class -> engine-kw overrides (default maps
                           interactive/batch onto max_prefills_per_step
                           1/None)
      engine_kw            base kwargs for every replica engine
                           (max_slots, prefill_chunk_tokens,
                           prefix_cache_tokens, ...)
      engine_kw_for        optional fn(index) -> extra kwargs for one
                           replica (drills inject per-replica
                           FaultInjectors through this)
      auto_refill          monitor replaces DEAD replicas with a fresh
                           incarnation automatically (default False:
                           drills and operators call refill())
    """

    def __init__(self, params, cfg, n_replicas=2, journal_path=None,
                 journal_fsync=False, max_pending=64,
                 heartbeat_timeout_s=30.0, monitor_interval_s=None,
                 affinity=True, replica_slo=None, slo_classes=None,
                 engine_kw=None, engine_kw_for=None, auto_refill=False):
        if int(n_replicas) < 1:
            raise ValueError("n_replicas must be >= 1")
        if int(max_pending) < 1:
            raise ValueError("max_pending must be >= 1")
        self._params = params
        self._cfg = cfg
        self.n_replicas = int(n_replicas)
        self.max_pending = int(max_pending)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.affinity = bool(affinity)
        self.auto_refill = bool(auto_refill)
        self.slo_classes = dict(_DEFAULT_SLO_CLASSES)
        if slo_classes:
            self.slo_classes.update(slo_classes)
        if replica_slo is not None and len(replica_slo) != self.n_replicas:
            raise ValueError("replica_slo must name a class per replica")
        self._replica_slo = list(replica_slo or [None] * self.n_replicas)
        for c in self._replica_slo:
            if c is not None and c not in self.slo_classes:
                raise ValueError("unknown SLO class %r" % c)
        self._engine_kw = dict(engine_kw or {})
        self._engine_kw_for = engine_kw_for
        # ONE block granularity: the engine's paged KV pool and the
        # prefix trie share it (kv_block_tokens is the ISSUE 7 name,
        # prefix_block_tokens the pre-paging alias the engine accepts).
        # `is None` defaulting, like the engine: an explicit invalid 0
        # must raise HERE, not as a replica-thread crash loop later
        _bt = self._engine_kw.get("kv_block_tokens")
        if _bt is None:
            _bt = self._engine_kw.get("prefix_block_tokens")
        self.block_tokens = 16 if _bt is None else int(_bt)
        if self.block_tokens < 1:
            raise ValueError("kv_block_tokens must be >= 1")
        # per-replica pool capacity for the submit() precheck: a
        # request whose worst case exceeds a WHOLE replica pool can
        # never be admitted anywhere — fail in the caller (the engine's
        # own rule; a merely saturated pool queues instead)
        _L = min(int(self._engine_kw.get("max_len") or cfg.max_len),
                 int(params["pos"].shape[0]))
        _pb = self._engine_kw.get("kv_pool_blocks")
        self._pool_blocks = (
            int(self._engine_kw.get("max_slots", 8))
            * (-(-_L // self.block_tokens))
            if _pb is None else int(_pb))
        if self._pool_blocks < 1:
            raise ValueError("kv_pool_blocks must be >= 1")
        # chain keys only pay off when there is a pool to match: with
        # no base prefix_cache_tokens every summary stays empty, so
        # skip the per-submit O(T0) crc work entirely
        self._chain_prompts = bool(affinity) and bool(
            self._engine_kw.get("prefix_cache_tokens"))

        # ONE lock for all fleet scheduler state (the condition owns
        # it); replica + monitor threads mutate ONLY under it
        self._cond = threading.Condition()
        self._journal = RequestJournal(journal_path, fsync=journal_fsync)
        self._replicas: List[_Replica] = []            # guarded-by: _cond
        self._state: List[str] = []                    # guarded-by: _cond
        self._beats: List[float] = []                  # guarded-by: _cond
        self._kill: List[bool] = []                    # guarded-by: _cond
        self._inbox: List[collections.deque] = []      # guarded-by: _cond
        self._in_flight: List[Dict[int, FleetHandle]] = []  # guarded-by: _cond
        self._summaries: List[Set[int]] = []           # guarded-by: _cond
        self._rep_stats: List[Optional[dict]] = []     # guarded-by: _cond
        # dead incarnations' last stats snapshots fold in here so
        # fleet totals stay monotonic across failover/refill
        self._stats_base: Dict[str, int] = {}          # guarded-by: _cond
        self._spawned: List[float] = []                # guarded-by: _cond
        self._rapid: List[int] = []                    # guarded-by: _cond
        self._refill_at: List[float] = []              # guarded-by: _cond
        self._incarnations: List[int] = []             # guarded-by: _cond
        self._handles: Dict[int, FleetHandle] = {}     # guarded-by: _cond
        self._open: Set[int] = set()                   # guarded-by: _cond
        self._done_rids: Set[int] = set()              # guarded-by: _cond
        # journal FILE records produced under the lock (mirror updates
        # are synchronous); flushed by _flush_journal() after release
        # so disk latency never stalls handshakes or the monitor.
        # Completion events fire AFTER the flush: a caller observing a
        # result implies its done record is already written
        self._pending_journal: List[dict] = []         # guarded-by: _cond
        self._pending_events: List[FleetHandle] = []   # guarded-by: _cond
        # continue past an existing journal's history: a restarted
        # front door appending to the same file must never reuse a rid
        self._next_rid = self._journal.next_rid()      # guarded-by: _cond
        self._closing = False                          # guarded-by: _cond
        # O(1) counters (the ServingMetrics discipline)
        self.submitted = 0                             # guarded-by: _cond
        self.completed = 0                             # guarded-by: _cond
        self.shed = 0                                  # guarded-by: _cond
        self.rejected = 0                              # guarded-by: _cond
        self.resubmitted = 0                           # guarded-by: _cond
        self.failovers = 0                             # guarded-by: _cond
        self.zombie_refused = 0                        # guarded-by: _cond
        self.duplicate_refused = 0                     # guarded-by: _cond

        self._idle_wait_s = min(0.02, self.heartbeat_timeout_s / 10.0)
        self._monitor_interval_s = (
            monitor_interval_s if monitor_interval_s is not None
            else max(0.01, min(0.2, self.heartbeat_timeout_s / 5.0)))
        with self._cond:
            for i in range(self.n_replicas):
                self._incarnations.append(1)
                self._state.append(_LIVE)
                self._beats.append(time.monotonic())
                self._kill.append(False)
                self._inbox.append(collections.deque())
                self._in_flight.append({})
                self._summaries.append(set())
                self._rep_stats.append(None)
                self._spawned.append(time.monotonic())
                self._rapid.append(0)
                self._refill_at.append(0.0)
                self._replicas.append(self._make_replica(i, 1))
        for r in self._replicas:
            r.start()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="fleet-monitor", daemon=True)
        self._monitor.start()

    # -- construction helpers -------------------------------------------
    def _make_replica(self, index: int, incarnation: int) -> _Replica:
        kw = dict(self._engine_kw)
        slo = self._replica_slo[index]
        if slo is not None:
            kw.update(self.slo_classes[slo])
        if self._engine_kw_for is not None:
            kw.update(self._engine_kw_for(index) or {})
        rep_bt = kw.get("kv_block_tokens")
        if rep_bt is None:
            rep_bt = kw.get("prefix_block_tokens")
        rep_bt = self.block_tokens if rep_bt is None else int(rep_bt)
        if self.affinity and rep_bt != self.block_tokens:
            # chain keys are computed at the FLEET's block size; a
            # replica caching at a different granularity would never
            # match them and affinity would silently degrade to
            # least-loaded — refuse loudly instead
            raise ValueError(
                "affinity routing requires a uniform block granularity "
                "across replicas (fleet %d, replica %d override %r)"
                % (self.block_tokens, index, rep_bt))
        return _Replica(self, index, incarnation, slo, kw)

    # -- admission -------------------------------------------------------
    def submit(self, prompt, max_new_tokens, temperature=0.0,
               eos_id=None, seed=0, publish_len=None,
               slo="interactive") -> FleetHandle:
        """Journal the request durably, then route it (prefix affinity
        within the SLO class). Raises `FleetSaturated` when
        `max_pending` requests are already open — the shed request is
        NOT journaled, so backpressure never grows the durable table
        either."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.shape[0] < 1:
            raise ValueError("empty prompt")
        if int(max_new_tokens) < 1:
            raise ValueError("max_new_tokens must be >= 1")
        # fail fast HERE with the engine's admission rule (including a
        # base engine_kw max_len override): a request that cannot fit
        # must error in the caller, not asynchronously at result()
        L = min(int(self._engine_kw.get("max_len") or self._cfg.max_len),
                int(self._params["pos"].shape[0]))
        if prompt.shape[0] + int(max_new_tokens) > L:
            raise ValueError(
                "request needs T0+max_new <= max_len (%d + %d > %d)"
                % (prompt.shape[0], int(max_new_tokens), L))
        need = -(-(prompt.shape[0] + int(max_new_tokens))
                 // self.block_tokens)
        if need > self._pool_blocks:
            raise ValueError(
                "request worst case (%d blocks) exceeds a whole replica "
                "KV pool (%d blocks of %d tokens)"
                % (need, self._pool_blocks, self.block_tokens))
        if publish_len is not None and publish_len < 0:
            raise ValueError("publish_len must be >= 0 or None")
        if slo is not None and slo not in self.slo_classes:
            raise ValueError("unknown SLO class %r" % slo)
        spec = {
            "prompt": [int(t) for t in prompt],
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature),
            "eos_id": None if eos_id is None else int(eos_id),
            "seed": int(seed),
            "publish_len": None if publish_len is None else int(publish_len),
            "slo": slo,
        }
        with self._cond:
            if self._closing:
                raise RuntimeError("fleet is closed")
            if len(self._open) >= self.max_pending:
                self.shed += 1
                raise FleetSaturated(
                    "fleet saturated: %d open requests (max_pending=%d)"
                    % (len(self._open), self.max_pending))
            rid = self._next_rid
            self._next_rid += 1
            h = FleetHandle(rid, prompt, spec, slo)
            if self._chain_prompts:  # keys feed ONLY affinity routing
                h.chain = chain_keys(prompt, self.block_tokens)
            self._handles[rid] = h
            self._open.add(rid)
            self.submitted += 1
        # durable BEFORE routing — and OUTSIDE the fleet lock, so the
        # journal's write+flush never stalls replica handshakes or the
        # monitor behind disk latency
        self._journal.submit(rid, spec)
        try:
            with self._cond:
                if self._closing:
                    # close() raced the journal write: it already
                    # failed this handle (it was in _open). Terminal
                    # record, or the journaled rid stays open and
                    # every future recover() resubmits a request
                    # whose caller was told it failed
                    self._open.discard(rid)
                    self._handles.pop(rid, None)
                    self._done_rids.add(rid)
                    self.rejected += 1
                    self._pending_journal.append(self._journal.reject(
                        rid, "fleet closed", defer=True))
                    raise RuntimeError("fleet is closed")
                self._route(h, exclude=None)
        finally:
            # also on the raises above: the terminal reject record
            # must be on disk before the caller sees the error
            self._flush_journal()
        return h

    def _route(self, h: FleetHandle, exclude: Optional[int]):
        """Pick a replica for `h` (caller holds `_cond`): longest
        cached-prefix match against the pool summaries, ties broken by
        load; SLO class first, any live replica as fallback; no live
        replica at all fails the handle."""
        live = [i for i in range(self.n_replicas)
                if self._state[i] == _LIVE and i != exclude]
        cands = [i for i in live if self._replica_slo[i] in (None, h.slo)]
        if not cands:
            cands = live  # survival beats SLO placement
        if not cands:
            # terminal: the caller gets the error NOW, so the request
            # must not stay open (journal-wise) to be resubmitted by
            # every future recover(); prune like _accept does
            h.error = EngineFailed(
                "no live replica for request %d" % h.rid, replica=None)
            self._open.discard(h.rid)
            self._handles.pop(h.rid, None)
            self._done_rids.add(h.rid)
            self.rejected += 1
            self._pending_journal.append(self._journal.reject(
                h.rid, "no live replica", defer=True))
            # event fires at flush, AFTER the reject record is on disk
            # (submit's caller still gets the raise synchronously)
            self._pending_events.append(h)
            raise h.error
        best, best_key = None, None
        for i in cands:
            depth = 0
            if self.affinity and h.chain:
                s = self._summaries[i]
                for key in h.chain:
                    if key not in s:
                        break
                    depth += 1
            load = len(self._inbox[i]) + len(self._in_flight[i])
            key = (-depth, load, i)
            if best_key is None or key < best_key:
                best, best_key = i, key
        rep = self._replicas[best]
        self._inbox[best].append(h)
        # mirror updates NOW (a failover consulting lost() must see
        # this assignment); the file record flushes after the lock
        self._pending_journal.append(self._journal.assign(
            h.rid, rep.name, rep.incarnation, h.generation, defer=True))
        self._cond.notify_all()

    def _flush_journal(self):
        """Write journal records produced under the lock, THEN release
        the waiters whose completions those records describe — called
        by every entry point after dropping the lock (submit, replica
        syncs, monitor sweeps, drain, close). The ordering makes the
        journal read-your-writes for anyone a result just unblocked."""
        with self._cond:
            if not self._pending_journal and not self._pending_events:
                return
            pending, self._pending_journal = self._pending_journal, []
            fired, self._pending_events = self._pending_events, []
        if pending:
            self._journal.write(pending)
        for h in fired:
            h._event.set()

    def _reject(self, rid: int, exc: Exception):
        """A single malformed request failed engine admission: fail it
        alone (called from replica threads), with a TERMINAL journal
        record — an unservable request must not stay open forever and
        be resubmitted by every future recover()."""
        with self._cond:
            h = self._handles.pop(rid, None)
            if h is None or h.done:
                return
            h.error = exc
            self._open.discard(rid)
            self._done_rids.add(rid)
            for fl in self._in_flight:
                fl.pop(rid, None)
            self.rejected += 1
            self._pending_journal.append(self._journal.reject(
                rid, repr(exc), defer=True))
            self._pending_events.append(h)
            self._cond.notify_all()
        self._flush_journal()

    # -- replica protocol ------------------------------------------------
    def _sync(self, rep: _Replica, completed, idle: bool,
              summary: Optional[Set[int]],
              stats: Optional[dict]):  # thread: replica
        """One replica scheduler handshake: report completions (fenced
        + deduped), heartbeat, absorb the pool summary, pick up new
        work. Returns ("stop", []) when this replica object is no
        longer the registered incarnation (fenced zombie, closing
        fleet) — the loop must exit. May raise `_KillDrill`."""
        ret = self._sync_locked(rep, completed, idle, summary, stats)
        self._flush_journal()
        return ret

    def _sync_locked(self, rep: _Replica, completed, idle: bool,
                     summary: Optional[Set[int]],
                     stats: Optional[dict]):  # thread: replica
        with self._cond:
            i = rep.index
            current = (self._replicas[i] is rep
                       and self._state[i] != _DEAD)
            for rid, tokens in completed:
                self._accept(rid, tokens, rep, accepted=current)
            if not current or self._closing:
                return "stop", []
            self._beats[i] = time.monotonic()
            if stats is not None:
                self._rep_stats[i] = stats
            if summary is not None:
                self._summaries[i] = summary
            if self._kill[i]:
                self._kill[i] = False
                raise _KillDrill("replica %s killed by drill" % rep.name)
            if self._state[i] == _DRAINING and idle \
                    and not self._inbox[i] and not self._in_flight[i]:
                self._state[i] = _DRAINED
                self._cond.notify_all()
            if self._state[i] == _DRAINED:
                # parked: wait for refill/close; the monitor exempts
                # DRAINED replicas from the heartbeat deadline
                self._cond.wait(timeout=self._idle_wait_s)
                return "park", []
            work: List[FleetHandle] = []
            q = self._inbox[i]
            while q:
                h = q.popleft()
                self._in_flight[i][h.rid] = h
                work.append(h)
            if not work and idle:
                # nothing to do: sleep on the condition (bounded, so
                # heartbeats keep flowing) instead of spinning
                self._cond.wait(timeout=self._idle_wait_s)
            return "run", work

    def _accept(self, rid: int, tokens: List[int], rep: _Replica,
                accepted: bool):
        """Completion fence + dedupe (caller holds `_cond`): refuse a
        dead/superseded replica's late result, refuse a second answer
        for an already-done rid."""
        if not accepted:
            self.zombie_refused += 1
            return
        if rid in self._done_rids:
            self.duplicate_refused += 1
            return
        h = self._handles.get(rid)
        if h is None or h.done:
            self.duplicate_refused += 1
            return
        self._done_rids.add(rid)
        self._in_flight[rep.index].pop(rid, None)
        self._open.discard(rid)
        # prune the handle (the caller holds its own reference): a
        # long-lived front door must not retain every prompt + output
        # it ever served — _done_rids (ints) carries the dedupe
        self._handles.pop(rid, None)
        self._pending_journal.append(self._journal.complete(
            rid, rep.name, rep.incarnation, h.generation, tokens,
            defer=True))
        h.tokens = list(tokens)
        h.replica = rep.name
        # the event fires in _flush_journal, AFTER the done record is
        # on disk — result() observers get read-your-writes recovery
        self._pending_events.append(h)
        self.completed += 1
        self._cond.notify_all()

    def _on_crash(self, rep: _Replica, exc: BaseException):  # thread: replica
        with self._cond:
            self._fail_over(rep.index, rep, exc)
        self._flush_journal()

    # -- failure handling ------------------------------------------------
    def _fail_over(self, i: int, rep: _Replica, exc: BaseException):
        """Declare replica `i` dead and resubmit its journal-recorded
        open requests to survivors (caller holds `_cond`). Idempotent
        per incarnation: the crash path and the heartbeat path can both
        land here."""
        if self._replicas[i] is not rep or self._state[i] == _DEAD:
            return
        self._state[i] = _DEAD
        self._summaries[i] = set()
        self.failovers += 1
        # fold the dead incarnation's last stats snapshot into the
        # fleet-wide base: totals must not decrease on refill
        st = self._rep_stats[i]
        if st:
            for k, v in st.items():
                if k == "kv_blocks_in_use":
                    continue  # gauge: a dead replica's pool is gone
                self._stats_base[k] = self._stats_base.get(k, 0) + v
        self._rep_stats[i] = None
        # rapid-death accounting gates auto_refill (exponential
        # backoff, the Supervisor's restart/backoff discipline): a
        # deterministically-failing replica must not crash/refill at
        # monitor frequency forever
        rapid = time.monotonic() - self._spawned[i] < 2.0
        self._rapid[i] = self._rapid[i] + 1 if rapid else 0
        self._refill_at[i] = time.monotonic() + min(
            5.0, 0.05 * (2 ** self._rapid[i]))
        self._inbox[i].clear()
        self._in_flight[i].clear()
        # the JOURNAL is the recovery source: every open request whose
        # latest assignment names this replica+incarnation
        for rid, _spec, _gen in self._journal.lost(rep.name, rep.incarnation):
            h = self._handles.get(rid)
            if h is None or h.done:
                continue
            h.generation += 1
            self.resubmitted += 1
            try:
                self._route(h, exclude=i)
            except EngineFailed:
                pass  # no survivors: handle already failed by _route
        self._cond.notify_all()

    def _monitor_loop(self):  # thread: monitor
        while True:
            with self._cond:
                if self._closing:
                    return
                now = time.monotonic()
                for i, rep in enumerate(self._replicas):
                    if self._state[i] in (_LIVE, _DRAINING) \
                            and now - self._beats[i] > self.heartbeat_timeout_s:
                        self._fail_over(
                            i, rep,
                            TimeoutError(
                                "replica %s missed heartbeat deadline "
                                "(%.2fs)" % (rep.name,
                                             self.heartbeat_timeout_s)))
                    elif self._state[i] == _DEAD and self.auto_refill \
                            and now >= self._refill_at[i]:
                        self._refill_locked(i)
            self._flush_journal()  # fail-over resubmissions above
            time.sleep(self._monitor_interval_s)

    # -- operator surface ------------------------------------------------
    def kill_replica(self, i: int):
        """Drill: the replica's next scheduler handshake raises, its
        thread dies, and the normal crash→failover path runs. (The
        subprocess mode SIGKILLs for real via PADDLE_FAULT=kill@N.)"""
        with self._cond:
            self._kill[i] = True
            self._cond.notify_all()

    def drain(self, i: int, wait: bool = False,
              timeout: Optional[float] = None) -> bool:
        """Stop admitting to replica `i`, re-route its queued (not yet
        started) requests, let in-flight work finish and publish its
        prefixes, then park the replica DRAINED (engine and prefix
        pool stay warm for `refill`). With `wait=True`, block until
        drained; returns whether the replica is drained."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            if self._state[i] == _LIVE:
                self._state[i] = _DRAINING
                queued = list(self._inbox[i])
                self._inbox[i].clear()
                for h in queued:
                    h.generation += 1
                    self.resubmitted += 1
                    try:
                        self._route(h, exclude=i)
                    except EngineFailed:
                        pass  # no other live replica: handle failed
                self._cond.notify_all()
        self._flush_journal()  # re-assignments above, before any wait
        with self._cond:
            if not wait:
                return self._state[i] == _DRAINED
            while self._state[i] == _DRAINING:
                t = (None if deadline is None
                     else deadline - time.monotonic())
                if t is not None and t <= 0.0:
                    break
                self._cond.wait(timeout=t if t is not None else 0.5)
            return self._state[i] == _DRAINED

    def refill(self, i: int):
        """Bring replica `i` back: a DRAINED replica resumes with its
        engine (and hot prefix pool) intact; a DEAD one is replaced by
        a fresh incarnation (cold engine) — the restart half of the
        supervisor's restart/backoff story."""
        with self._cond:
            if self._state[i] == _DRAINED:
                self._state[i] = _LIVE
                self._beats[i] = time.monotonic()
                self._cond.notify_all()
            elif self._state[i] == _DEAD:
                self._refill_locked(i)

    def _refill_locked(self, i: int):
        self._incarnations[i] += 1
        rep = self._make_replica(i, self._incarnations[i])
        self._replicas[i] = rep
        self._state[i] = _LIVE
        self._beats[i] = time.monotonic()
        # a kill_replica() drill aimed at the DEAD predecessor (it
        # crashed before consuming the flag) must not assassinate the
        # fresh incarnation at its first handshake
        self._kill[i] = False
        self._summaries[i] = set()
        self._rep_stats[i] = None
        self._spawned[i] = time.monotonic()
        # starting the thread under the lock is safe: its first _sync
        # blocks on the condition until we release
        rep.start()
        self._cond.notify_all()

    def wait_all(self, timeout: Optional[float] = None) -> bool:
        """Block until no request is open (completed, rejected, or
        failed). Returns False on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._open:
                t = (None if deadline is None
                     else max(0.0, deadline - time.monotonic()))
                if t is not None and t <= 0.0:
                    return False
                self._cond.wait(timeout=t if t is not None else 0.5)
            return True

    def stats(self) -> dict:
        with self._cond:
            base = self._stats_base
            hits = base.get("prefix_hits", 0)
            misses = base.get("prefix_misses", 0)
            saved = base.get("prefix_tokens_saved", 0)
            tokens_out = base.get("tokens_out", 0)
            prefill_tok = base.get("prefill_tokens_computed", 0)
            blocks_in_use = 0  # gauge: live replicas only
            cow = base.get("cow_blocks", 0)
            spec_drafted = base.get("spec_drafted", 0)
            spec_accepted = base.get("spec_accepted", 0)
            reps = []
            for i, rep in enumerate(self._replicas):
                st = self._rep_stats[i] or {}
                hits += st.get("prefix_hits", 0)
                misses += st.get("prefix_misses", 0)
                saved += st.get("prefix_tokens_saved", 0)
                tokens_out += st.get("tokens_out", 0)
                prefill_tok += st.get("prefill_tokens_computed", 0)
                if self._state[i] == _LIVE:
                    blocks_in_use += st.get("kv_blocks_in_use", 0)
                cow += st.get("cow_blocks", 0)
                spec_drafted += st.get("spec_drafted", 0)
                spec_accepted += st.get("spec_accepted", 0)
                reps.append({
                    "name": rep.name, "slo": rep.slo,
                    "state": self._state[i],
                    "incarnation": rep.incarnation,
                    "load": len(self._inbox[i]) + len(self._in_flight[i]),
                    "stats": st,
                })
            total = hits + misses
            return {
                "submitted": self.submitted,
                "completed": self.completed,
                "shed": self.shed,
                "rejected": self.rejected,
                "resubmitted": self.resubmitted,
                "failovers": self.failovers,
                "zombie_refused": self.zombie_refused,
                "duplicate_refused": self.duplicate_refused,
                "open": len(self._open),
                "lost": self.submitted - self.completed - self.rejected
                - len(self._open),
                "tokens_out": tokens_out,
                "prefill_tokens_computed": prefill_tok,
                "prefix_hit_rate": round(hits / total, 4) if total else None,
                "prefix_tokens_saved": saved,
                "kv_blocks_in_use": blocks_in_use,
                "cow_blocks": cow,
                "spec_drafted": spec_drafted,
                "spec_accepted": spec_accepted,
                "spec_accept_rate": round(spec_accepted / spec_drafted, 4)
                if spec_drafted else None,
                "replicas": reps,
            }

    def close(self, timeout: float = 10.0):
        """Stop every replica and the monitor; fail any still-open
        handle with `EngineFailed` (their waiters must not block on a
        dead fleet)."""
        with self._cond:
            if self._closing:
                return
            self._closing = True
            for rid in list(self._open):
                h = self._handles.get(rid)
                if h is not None and not h.done:
                    h.error = EngineFailed(
                        "fleet closed with request %d pending" % rid,
                        replica=None)
                    h._event.set()
            self._open.clear()
            self._cond.notify_all()
        self._monitor.join(timeout=timeout)
        for rep in list(self._replicas):
            rep.thread.join(timeout=timeout)
        self._flush_journal()  # stragglers from the final syncs
        self._journal.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# subprocess mode: real-process kill drills through the PR-1 control plane
# ---------------------------------------------------------------------------

def run_fleet_subprocess(argv_for, worker_ids, requests,
                         lease_timeout_s=15.0, heartbeat_timeout_s=15.0,
                         env_for=None, deadline_s=240.0,
                         supervisor_kw=None):
    """Serve `requests` (journal-form spec dicts) through N worker
    SUBPROCESSES (tests/fleet_worker.py is the reference worker): the
    requests become Coordinator task leases, the workers run a real
    `ServingEngine` each (`step()` ticks PADDLE_FAULT, so `kill@N`
    SIGKILLs mid-decode), and `distributed/supervisor.py` restarts
    casualties. Fault tolerance is exactly the PR-1 story: a dead
    worker's leases time out and requeue to survivors (no request
    lost), lease GENERATIONS fence a zombie's late `task_finished` (no
    request acked twice), and results are written atomically per rid.

    `argv_for(worker_id, coordinator_address)` builds one worker's
    command line; result files land wherever the caller's argv points
    the workers. Returns {"report": supervisor report, "coordinator":
    queue counts} — `coordinator["done"] == len(requests)` with
    `discarded == 0` is the no-lost-request check, and lease fencing
    means each rid was acked exactly once.
    """
    from ..distributed.coordinator import Coordinator, CoordinatorServer
    from ..distributed.supervisor import Supervisor

    coord = Coordinator(timeout_s=lease_timeout_s, failure_max=10,
                        heartbeat_timeout_s=heartbeat_timeout_s)
    coord.set_dataset([dict(spec, rid=i)
                       for i, spec in enumerate(requests)])
    server = CoordinatorServer(coord).start()
    try:
        sup = Supervisor(
            lambda wid: argv_for(wid, server.address), worker_ids,
            env_for=env_for, coordinator=coord,
            **(supervisor_kw or {}))
        report = sup.run(deadline_s=deadline_s)
    finally:
        server.stop()
    return {
        "report": report,
        "coordinator": {
            "done": len(coord.done), "todo": len(coord.todo),
            "pending": len(coord.pending),
            "discarded": len(coord.discarded),
        },
    }
