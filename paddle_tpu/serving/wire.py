"""Wire protocol for the serving front door (ISSUE 18): newline-
delimited JSON over TCP — the reference's pserver RPC / go-master
service surface recast for inference, with the dumbest framing that
can possibly work so every drill stays byte-inspectable (`nc` is a
valid client).

Framing: one UTF-8 JSON object per ``\\n``-terminated line, at most
`MAX_FRAME_BYTES` per line. Client frames carry an ``op`` and — for
request-scoped ops — a caller-chosen ``id`` string echoed on every
response frame, so one connection multiplexes any number of
outstanding requests.

Client -> server ops::

    {"op": "hello", "token": "<auth token>"}
    {"op": "generate", "id": "r1", "prompt": [1, 2, 3],
     "max_new_tokens": 8,
     # optional: "temperature", "eos_id", "seed", "deadline_s",
     #           "stream": true, "slo", "adapter"
    }
    {"op": "cancel", "id": "r1"}
    {"op": "ping"}

Server -> client frames::

    {"op": "welcome", "proto": 1, "tenant": "alice" | null}
    {"op": "accepted", "id": "r1", "rid": 7}
    {"op": "tokens", "id": "r1", "index": 0, "tokens": [5, 9]}
    {"op": "done", "id": "r1", "tokens": [5, 9, 4], "n": 3,
     "replica": "r0" | null}
    {"op": "error", "id": "r1" | null, "code": "DEADLINE_EXCEEDED",
     "message": "...", "retry_after_s": 0.5}   # retry_after optional
    {"op": "pong"}

``tokens`` frames stream the journal's batched-flush progress chunks
(``index`` is the cumulative generated-token count before the chunk);
``done.tokens`` is ALWAYS the full generated sequence, so a streaming
client can verify bit-identity between the concatenated chunks and
the final answer — the invariant the fleet guarantees across
failover/migration. Errors are TYPED, stable codes from
`ERROR_CODES`; a stack trace never crosses the wire."""

import json
import socket
import threading
from typing import Optional

from .engine import EngineFailed
from .fleet import (DeadlineExceeded, FleetSaturated, FleetTimeout,
                    RequestCancelled)
from .tenancy import TenantQuotaExceeded

PROTO_VERSION = 1

# one line of NDJSON may not exceed this (a 4k-token prompt of 7-digit
# ids is ~32 KiB; 1 MiB leaves an order of magnitude of headroom while
# bounding what one rogue client can make the server buffer)
MAX_FRAME_BYTES = 1 << 20

# the stable wire-level rejection vocabulary: every fleet verdict an
# operator can see maps to exactly one of these — clients dispatch on
# the CODE, the message is human context only and carries no contract
ERROR_CODES = {
    "FLEET_SATURATED": "max_pending open requests: shed, retry later",
    "TENANT_QUOTA_EXCEEDED": "the tenant's token bucket is spent "
                             "(retry_after_s rides along)",
    "DEADLINE_EXCEEDED": "the request's deadline_s budget expired",
    "ENGINE_FAILED": "the fleet lost every replica (or was closed) "
                     "with the request pending",
    "CANCELLED": "the request was cancelled client-side "
                 "(cancel frame or dropped connection)",
    "BAD_REQUEST": "malformed frame or unservable request parameters",
    "UNAUTHORIZED": "missing or unknown auth token",
    "SERVER_DRAINING": "the front door is draining: no new requests",
    "TIMEOUT": "the server-side wait budget ran out with the "
               "request still open",
    "INTERNAL": "unexpected server-side failure (never a stack trace)",
}


class WireError(RuntimeError):
    """A typed wire-level rejection (either side). `code` is one of
    `ERROR_CODES`; `retry_after_s` rides quota sheds."""

    def __init__(self, code: str, message: str,
                 retry_after_s: Optional[float] = None):
        super().__init__("%s: %s" % (code, message))
        self.code = code
        self.message = message
        self.retry_after_s = retry_after_s


def error_code_for(exc: BaseException):
    """Map a fleet/tenancy exception to its (code, retry_after_s)
    wire rejection — the ONE place the mapping lives, so the server
    and any in-process test agree on the vocabulary. Unknown
    exceptions become INTERNAL: typed, message-only, never a
    traceback."""
    if isinstance(exc, WireError):  # already typed: pass through
        return exc.code, exc.retry_after_s
    if isinstance(exc, TenantQuotaExceeded):
        return "TENANT_QUOTA_EXCEEDED", getattr(exc, "retry_after_s",
                                                None)
    if isinstance(exc, FleetSaturated):
        return "FLEET_SATURATED", None
    if isinstance(exc, DeadlineExceeded):
        return "DEADLINE_EXCEEDED", None
    if isinstance(exc, RequestCancelled):
        return "CANCELLED", None
    if isinstance(exc, FleetTimeout):
        return "TIMEOUT", None
    if isinstance(exc, EngineFailed):
        return "ENGINE_FAILED", None
    if isinstance(exc, (ValueError, KeyError, TypeError)):
        return "BAD_REQUEST", None
    return "INTERNAL", None


def error_frame(exc: BaseException, req_id=None) -> dict:
    """The error frame for an exception: stable code + the first line
    of the message (stack traces never cross the wire)."""
    code, retry = error_code_for(exc)
    msg = str(exc).splitlines()[0] if str(exc) else type(exc).__name__
    frame = {"op": "error", "id": req_id, "code": code, "message": msg}
    if retry is not None:
        frame["retry_after_s"] = float(retry)
    return frame


def encode_frame(obj: dict) -> bytes:
    data = (json.dumps(obj, separators=(",", ":")) + "\n").encode()
    if len(data) > MAX_FRAME_BYTES:
        raise WireError("BAD_REQUEST",
                        "frame of %d bytes exceeds MAX_FRAME_BYTES "
                        "(%d)" % (len(data), MAX_FRAME_BYTES))
    return data


def send_frame(sock: socket.socket, obj: dict,
               lock: Optional[threading.Lock] = None):
    """Serialize + send one frame; `lock` serializes concurrent
    writers (a connection's reader thread and its per-request pump
    threads share one socket)."""
    data = encode_frame(obj)
    if lock is not None:
        with lock:
            sock.sendall(data)
    else:
        sock.sendall(data)


def read_frame(rfile) -> Optional[dict]:
    """Read one frame from a buffered file object (sock.makefile).
    Returns None on clean EOF; raises WireError on an oversized or
    malformed line (the server answers BAD_REQUEST and drops the
    connection — resynchronizing inside a corrupt NDJSON stream is
    guesswork)."""
    line = rfile.readline(MAX_FRAME_BYTES + 1)
    if not line:
        return None
    if len(line) > MAX_FRAME_BYTES:
        raise WireError("BAD_REQUEST", "frame exceeds %d bytes"
                        % MAX_FRAME_BYTES)
    line = line.strip()
    if not line:
        return {}
    try:
        obj = json.loads(line.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        raise WireError("BAD_REQUEST", "unparseable frame")
    if not isinstance(obj, dict):
        raise WireError("BAD_REQUEST", "frame must be a JSON object")
    return obj


class WireClient(object):
    """Minimal blocking client for tests and the load generator: one
    socket, explicit frames. NOT thread-safe for concurrent `recv` —
    multiplexing callers (loadgen) run one reader thread per
    connection and use `send` only."""

    def __init__(self, address, token: Optional[str] = None,
                 timeout: Optional[float] = None):
        self.sock = socket.create_connection(address, timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._rf = self.sock.makefile("rb")
        self._wlock = threading.Lock()
        self.tenant = None
        if token is not None:
            self.send({"op": "hello", "token": token})
            w = self.recv()
            if w is None or w.get("op") == "error":
                raise WireError(
                    (w or {}).get("code", "INTERNAL"),
                    (w or {}).get("message", "connection closed"))
            self.tenant = w.get("tenant")

    def send(self, frame: dict):
        send_frame(self.sock, frame, lock=self._wlock)

    def recv(self) -> Optional[dict]:
        return read_frame(self._rf)

    def generate(self, req_id: str, prompt, max_new_tokens: int,
                 **kw) -> dict:
        """Send one generate frame (non-blocking beyond the send)."""
        frame = {"op": "generate", "id": req_id,
                 "prompt": [int(t) for t in prompt],
                 "max_new_tokens": int(max_new_tokens)}
        frame.update(kw)
        self.send(frame)
        return frame

    def generate_blocking(self, req_id: str, prompt,
                          max_new_tokens: int, **kw) -> dict:
        """Send one generate and read frames until ITS done/error
        (single-outstanding-request convenience). Returns {"tokens",
        "chunks", "rid", "replica"}; raises WireError on a typed
        rejection. The bit-identity check is the caller's: for a
        streamed request, sum(chunks, []) must equal tokens."""
        self.generate(req_id, prompt, max_new_tokens, **kw)
        chunks, rid = [], None
        while True:
            f = self.recv()
            if f is None:
                raise WireError("INTERNAL",
                                "connection closed mid-request")
            if f.get("id") != req_id:
                continue  # a stale frame from a prior cancel/timeout
            op = f.get("op")
            if op == "accepted":
                rid = f.get("rid")
            elif op == "tokens":
                chunks.append(list(f["tokens"]))
            elif op == "done":
                return {"tokens": list(f["tokens"]), "chunks": chunks,
                        "rid": rid, "replica": f.get("replica")}
            elif op == "error":
                raise WireError(f["code"], f.get("message", ""),
                                f.get("retry_after_s"))

    def cancel(self, req_id: str):
        self.send({"op": "cancel", "id": req_id})

    def close(self):
        # shutdown FIRST: a reader thread parked in readline() holds
        # the BufferedReader lock that _rf.close() needs — shutdown
        # EOFs the read and releases it (the close-vs-recv deadlock)
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        try:
            self._rf.close()
        except (OSError, ValueError):
            pass
