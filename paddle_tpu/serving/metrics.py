"""Serving metrics: tokens/s, slot occupancy, queue wait, time-to-first-
token, and compile (trace) counts for the continuous-batching engine.

Reporting rides the existing fluid/profiler.py machinery: wall-clock
spans land in an OpCostCollector (the same rows `with profiler(...)`
prints — Event/Calls/Total/Min/Max/Ave in ms) and `print_report()`
renders through profiler._print_table, so serving output reads exactly
like a training profile. Aggregates (`report()`) carry the
offline-measurable numbers the PERF.md serving section cites: mean slot
occupancy and per-bucket compile counts are deterministic on any
backend; tokens/s is only meaningful on-chip.
"""

from __future__ import annotations

import time
from typing import Dict

__all__ = ["ServingMetrics"]


# A long-lived engine records one value per decode step / per request
# forever — growing a Python float list without bound is the same trap
# the executor's CompileCache closes for compiled entries, so aggregates
# are running sums, not history. The accumulator lives in utils.stat
# (shared with data.DataMetrics); the underscore alias is the
# backward-compatible name.
from ..utils.stat import RunningStat as _RunningStat


class ServingMetrics(object):
    def __init__(self, max_slots: int):
        from ..fluid.profiler import OpCostCollector

        self.max_slots = int(max_slots)
        self.ops = OpCostCollector()  # wall-clock spans, profiler rows
        # fn-name -> times TRACED (a retrace == a recompile; the static
        # shape discipline the engine depends on makes these O(1))
        self.trace_counts: Dict[str, int] = {}
        self.prefills = 0
        self.decode_steps = 0
        self.tokens_out = 0
        self.occupancy = _RunningStat()  # live slots / max_slots per decode
        self.queue_wait_s = _RunningStat()  # submit -> admission
        self.ttft_s = _RunningStat()  # submit -> first token
        # PR 4 counters — same O(1) discipline (ints + RunningStat, no
        # per-request lists): chunked-prefill work actually computed,
        # prefix-pool reuse per admission, and side-band h2d uploads
        # (the steady decode loop must not grow this)
        self.prefill_chunks = 0
        self.prefill_tokens_computed = 0
        self.band_uploads = 0
        self.prefix_hit_tokens = _RunningStat()  # cached tokens/admission
        self.prefix_cache = None  # set by the engine when reuse is on
        # PR 12: set by the engine when the paged LoRA adapter pool is
        # on — report() surfaces its O(1) hit/miss/eviction/upload
        # counters (serving/adapters.py)
        self.adapter_pool = None
        # PR 15: set by the engine when KV block fingerprints are on —
        # report() surfaces the commit/verify/mismatch counters
        # (serving/integrity.py BlockFingerprints)
        self.block_fp = None
        # PR 7 counters — paged KV block pool + speculative decoding,
        # same O(1) discipline. Gauges (set by the engine each step or
        # scheduler event) vs cumulative ints are marked below.
        self.kv_blocks_total = 0          # gauge: pool size in blocks
        self.kv_blocks_in_use = 0         # gauge: physical blocks live
        self.kv_frag_tokens = 0           # gauge: allocated - resident
        self.kv_blocks_freed_at_retire = 0  # cumulative physical frees
        self.kv_tail_blocks_freed = 0     # cumulative: reserved, never
        #                                   reached (early EOS tails)
        self.cow_blocks = 0               # cumulative copy-on-writes
        self.spec_windows = 0             # cumulative verify rows run
        self.spec_drafted = 0             # cumulative drafted tokens
        self.spec_accepted = 0            # cumulative drafts emitted
        # PR 8 counters — request-SLO layer (deadlines, gray-failure
        # demotion, token-level resume), same O(1) discipline.
        self.expired = 0                  # cumulative deadline verdicts
        self.cancelled = 0                # cumulative fleet cancels
        self.resumed_requests = 0         # cumulative token-level resumes
        self.resume_tokens_reused = 0     # cumulative tokens NOT re-decoded
        # EWMA of ServingEngine.step() wall time (gauge; includes the
        # injector tick, so an injected gray stall is visible here —
        # that is the point: this gauge feeds the fleet's slow-replica
        # health score). 0.0 until the first step.
        self.step_ewma_s = 0.0
        # PR 13 gauge — which paged-attention kernel the engine's
        # compiled steps were traced with ("fused" Pallas table-walk or
        # "gather" XLA view; set once at engine construction)
        self.paged_kernel = None
        # PR 14 gauges — the KV pool's storage dtype ("none" | "int8"
        # | "fp8") and the weight storage ("int8" | None), both fixed
        # at engine construction; the fleet's per-replica stats rows
        # surface them (a mixed-quant fleet is refused at spawn, so
        # these also double as the audit trail for that invariant)
        self.kv_quant = None
        self.weight_quant = None
        # PR 11 gauge — the weight version this engine serves (the
        # fleet's live-rollout version fence stamps it at engine
        # construction; None outside a versioned fleet). A gauge like
        # kv_blocks_in_use: a dead incarnation's version says nothing
        # about its replacement.
        self.weights_version = None
        # PR 16 counters — durable KV tier, same O(1) discipline.
        # Cumulative ints; the fleet's per-replica stats rows sum them
        # across incarnations like the fingerprint counters.
        self.tokens_recomputed_at_migration = 0  # cumulative: closed-
        #                                   block prompt tokens a
        #                                   resumed admission re-
        #                                   prefilled (0 == clean path)
        self.handoff_imports = 0          # cumulative clean imports
        self.handoff_blocks_imported = 0  # cumulative blocks imported
        self.handoff_tokens_imported = 0  # cumulative tokens imported
        self.handoff_fallbacks = 0        # cumulative re-prefill falls
        self.store_spilled_blocks = 0     # cumulative publish spills
        self.store_warm_blocks = 0        # cumulative warm-start loads
        self.store_quarantined = 0        # cumulative fp-reject loads
        # PR 16: set by the engine when a durable KV store is attached
        # — report() surfaces its record/byte/quarantine counters
        # (serving/kv_store.py KVBlockStore)
        self.kv_store = None
        # PR 19 — device-busy accumulator: wall time with at least one
        # compiled step in flight (dispatch -> sync), folded as a UNION
        # of intervals via a last-end watermark so overlapping async
        # windows never double count. host-overhead fraction =
        # (wall - device_busy_s) / wall is the serving_megabatch
        # bench's headline column.
        self.device_busy_s = 0.0
        self._busy_last_end = 0.0
        self._t0 = None
        self._t1 = None

    STEP_EWMA_ALPHA = 0.5  # fast decay: ~3 healthy steps erase a spike

    def observe_step(self, seconds: float, tokens: int = 1):
        """Fold one engine-step wall time into the step-latency EWMA,
        normalized PER TOKEN (ISSUE 19): a decode_window=K engine's
        step legitimately covers K tokens of work, and the fleet's
        gray-failure score compares this gauge across replicas that
        may run different K. `tokens` is the step's token capacity
        (the static window size), so K=1 keeps the original per-step
        semantics exactly."""
        a = self.STEP_EWMA_ALPHA
        seconds = seconds / max(1, int(tokens))
        if self.step_ewma_s == 0.0:
            self.step_ewma_s = seconds
        else:
            self.step_ewma_s = a * seconds + (1.0 - a) * self.step_ewma_s

    def observe_device_interval(self, start: float, end: float):
        """Fold one dispatch->sync span into the device-busy union.
        Spans arrive in sync order; overlap with an earlier span (an
        async window chained before its predecessor synced) counts
        once — only time past the watermark accrues."""
        lo = max(start, self._busy_last_end)
        if end > lo:
            self.device_busy_s += end - lo
            self._busy_last_end = end

    # -- recording ------------------------------------------------------
    def count_trace(self, name: str):
        """Called from INSIDE the traced functions: runs once per trace
        (== once per compile signature), never per execution."""
        self.trace_counts[name] = self.trace_counts.get(name, 0) + 1

    def span(self, name: str, seconds: float):
        self.ops.record(name, seconds)
        now = time.monotonic()
        if self._t0 is None:
            self._t0 = now - seconds
        self._t1 = now

    # -- derived --------------------------------------------------------
    @property
    def wall_s(self) -> float:
        if self._t0 is None:
            return 0.0
        return (self._t1 or self._t0) - self._t0

    def prefill_trace_count(self) -> int:
        return sum(
            n for k, n in self.trace_counts.items() if k.startswith("prefill")
        )

    def decode_trace_count(self) -> int:
        return self.trace_counts.get("decode_step", 0)

    def report(self) -> dict:
        def _mean(st):
            return round(st.mean, 6) if st.count else None

        wall = self.wall_s
        rep = {
            "tokens_out": self.tokens_out,
            "tokens_per_sec": round(self.tokens_out / wall, 2) if wall else None,
            "decode_steps": self.decode_steps,
            "prefills": self.prefills,
            "mean_occupancy": _mean(self.occupancy),
            "mean_queue_wait_s": _mean(self.queue_wait_s),
            "max_queue_wait_s": round(self.queue_wait_s.max, 6)
            if self.queue_wait_s.count else None,
            "mean_ttft_s": _mean(self.ttft_s),
            "compile_counts": dict(self.trace_counts),
            "prefill_traces": self.prefill_trace_count(),
            "decode_traces": self.decode_trace_count(),
            "wall_s": round(wall, 4),
            "prefill_chunks": self.prefill_chunks,
            "prefill_tokens_computed": self.prefill_tokens_computed,
            "band_uploads": self.band_uploads,
            "mean_prefix_hit_tokens": _mean(self.prefix_hit_tokens),
            "kv_blocks_total": self.kv_blocks_total,
            "kv_blocks_in_use": self.kv_blocks_in_use,
            "kv_frag_tokens": self.kv_frag_tokens,
            "kv_blocks_freed_at_retire": self.kv_blocks_freed_at_retire,
            "kv_tail_blocks_freed": self.kv_tail_blocks_freed,
            "cow_blocks": self.cow_blocks,
            "spec_windows": self.spec_windows,
            "spec_drafted": self.spec_drafted,
            "spec_accepted": self.spec_accepted,
            "spec_accept_rate": round(
                self.spec_accepted / self.spec_drafted, 4)
            if self.spec_drafted else None,
            "expired": self.expired,
            "cancelled": self.cancelled,
            "resumed_requests": self.resumed_requests,
            "resume_tokens_reused": self.resume_tokens_reused,
            "step_ewma_s": round(self.step_ewma_s, 6),
            "device_busy_s": round(self.device_busy_s, 4),
            "host_overhead_frac": round(
                max(0.0, wall - self.device_busy_s) / wall, 4)
            if wall else None,
            "paged_kernel": self.paged_kernel,
            "kv_quant": self.kv_quant,
            "weight_quant": self.weight_quant,
            "weights_version": self.weights_version,
            "tokens_recomputed_at_migration":
                self.tokens_recomputed_at_migration,
            "handoff_imports": self.handoff_imports,
            "handoff_blocks_imported": self.handoff_blocks_imported,
            "handoff_tokens_imported": self.handoff_tokens_imported,
            "handoff_fallbacks": self.handoff_fallbacks,
            "store_spilled_blocks": self.store_spilled_blocks,
            "store_warm_blocks": self.store_warm_blocks,
            "store_quarantined": self.store_quarantined,
        }
        if self.prefix_cache is not None:
            rep["prefix_cache"] = self.prefix_cache.stats()
        if self.adapter_pool is not None:
            rep["adapter_pool"] = self.adapter_pool.stats()
        if self.block_fp is not None:
            rep["block_fingerprints"] = self.block_fp.stats()
        if self.kv_store is not None:
            rep["kv_store"] = self.kv_store.stats()
        return rep

    def table(self, sorted_key="total"):
        return self.ops.table(sorted_key)

    def print_report(self):
        from ..fluid.profiler import _print_table

        _print_table(self.table(), self.wall_s)
