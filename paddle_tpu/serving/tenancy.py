"""Multi-tenant serving: tenant registry, token-bucket admission
quotas, weighted fair queueing, and the batch (model-zoo) lane
(ISSUE 12 tentpole, half 2 of 2 — adapters.py is the weight half).

The fleet so far serves one anonymous caller; the reference's
`save_inference_model` story serves a whole model zoo to many
consumers. The multi-consumer contract this module adds to the front
door (serving/fleet.py wires it in):

  * `TenantRegistry` — named tenants, each with a TOKEN-BUCKET
    admission quota (`rate` requests/s refill, `burst` bucket
    capacity), a weighted-fair-queueing `weight`, an optional default
    adapter (adapters.py — the tenant's LoRA delta rides every
    request unless overridden), and O(1) per-tenant metrics
    (submitted/completed/shed/expired/rejected/tokens, mean queue
    wait). A submit past the bucket raises `TenantQuotaExceeded` —
    which, like `FleetSaturated`, is NEVER journaled: the durable
    table only holds requests the fleet accepted, so quota shed can
    never be replayed by a recovery.
  * `WFQueue` — classic virtual-time weighted fair queueing (the
    packet-scheduling WFQ/SFQ algorithm applied to requests): each
    request's finish tag is max(virtual time, tenant's last tag) +
    cost/weight, the queue pops the smallest tag, and virtual time
    advances to the popped tag. `cost` is the request's estimated
    service (prompt + budget tokens for LM work, the caller's
    estimate for batch jobs), so a tenant's share of the fleet is
    proportional to its weight in TOKENS, not request count — a
    tenant of long prompts cannot starve a tenant of short ones by
    counting. The fleet holds requests here when every replica's
    dispatch window is full and drains in tag order at every
    scheduler handshake; under no contention WFQ degenerates to FCFS
    (tags pop in arrival order) and costs one heap push/pop.
  * Batch (zoo) lane — a tenant whose work is batched image/CTR
    inference submits host callables (`ServingFleet.submit_batch`,
    e.g. one `Executor.run` micro-batch built by
    `executor_batch_fn`). Batch jobs ride the SAME quota buckets,
    the SAME weighted fair queue (cost-weighted against LM tokens),
    the SAME journal (assign/done with the typed `tenant` side-band,
    protocol_lint J008), and the SAME replica scheduler loop — at
    most ONE zoo micro-batch per scheduler handshake, interleaved
    with the engine's batched decode steps exactly like prefill
    chunks are (the Sarathi rule applied across workload kinds), so
    zoo throughput can never starve decode latency.

Host-only admission bookkeeping: no jax anywhere. The registry takes
its own lock (`_lock`) because replica threads update tenant metrics
at completion while the caller's thread sheds in submit; the fleet's
`_cond` is always acquired FIRST when both are held (one direction —
no inversion for lock_lint's L002 to find). `WFQueue` itself is
confined to the fleet's scheduler state like the inboxes it feeds.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..utils.stat import RunningStat

__all__ = ["Tenant", "TenantRegistry", "TenantQuotaExceeded",
           "WFQueue", "executor_batch_fn"]


class TenantQuotaExceeded(RuntimeError):
    """`submit()` refused: the tenant's token bucket is empty. Like
    `FleetSaturated` this is an explicit, NEVER-journaled shed — but
    scoped to one tenant: a bursting tenant exhausts its own bucket
    and is told so, while the fleet (and every other tenant's
    admission) stays untouched. Carries the tenant and the seconds
    until one credit refills."""

    def __init__(self, msg: str, tenant=None, retry_after_s=None):
        super().__init__(msg)
        self.tenant = tenant
        self.retry_after_s = retry_after_s


class Tenant(object):
    """One registered consumer: quota bucket + fair-share weight +
    default adapter/SLO + O(1) metrics. All mutable state is
    guarded by the owning registry's lock."""

    def __init__(self, name: str, rate: float, burst: float,
                 weight: float = 1.0, adapter: Optional[str] = None,
                 slo: Optional[str] = "interactive"):
        if float(rate) <= 0.0:
            raise ValueError("tenant rate must be > 0 requests/s")
        if float(burst) < 1.0:
            raise ValueError("tenant burst must be >= 1 request")
        if float(weight) <= 0.0:
            raise ValueError("tenant weight must be > 0")
        self.name = name
        self.rate = float(rate)      # bucket refill, requests/second
        self.burst = float(burst)    # bucket capacity, requests
        self.weight = float(weight)  # WFQ share
        self.adapter = adapter       # default adapters.py name (None = base)
        self.slo = slo               # default SLO class for its requests
        # token bucket: starts FULL (a fresh tenant may burst to its
        # capacity immediately — that is what burst means)
        self._tokens = float(burst)            # guarded-by: _lock
        self._refill_at: Optional[float] = None  # guarded-by: _lock
        # O(1) metrics (the ServingMetrics discipline)
        self.submitted = 0                     # guarded-by: _lock
        self.completed = 0                     # guarded-by: _lock
        self.shed_quota = 0                    # guarded-by: _lock
        self.expired = 0                       # guarded-by: _lock
        self.rejected = 0                      # guarded-by: _lock
        self.tokens_out = 0                    # guarded-by: _lock
        self.batch_jobs = 0                    # guarded-by: _lock
        self.queue_wait_s = RunningStat()      # guarded-by: _lock

    def snapshot(self) -> dict:  # holds: _lock (via registry)
        return {
            "submitted": self.submitted,
            "completed": self.completed,
            "shed_quota": self.shed_quota,
            "expired": self.expired,
            "rejected": self.rejected,
            "tokens_out": self.tokens_out,
            "batch_jobs": self.batch_jobs,
            "mean_queue_wait_s": (round(self.queue_wait_s.mean, 6)
                                  if self.queue_wait_s.count else None),
            "weight": self.weight,
            "rate": self.rate,
            "burst": self.burst,
            "adapter": self.adapter,
            "slo": self.slo,
        }


class TenantRegistry(object):
    """Tenant table + quota admission. One lock guards every bucket
    and metric; the fleet calls in under its own `_cond` (always
    outer), replica threads via the completion/expiry accounting."""

    def __init__(self):
        self._lock = threading.Lock()
        self._tenants: Dict[str, Tenant] = {}  # guarded-by: _lock

    def add(self, name: str, rate: float = 100.0, burst: float = 100.0,
            weight: float = 1.0, adapter: Optional[str] = None,
            slo: Optional[str] = "interactive") -> Tenant:
        t = Tenant(name, rate, burst, weight=weight, adapter=adapter,
                   slo=slo)
        with self._lock:
            if name in self._tenants:
                raise ValueError("tenant %r already registered" % name)
            self._tenants[name] = t
        return t

    def get(self, name: str) -> Tenant:
        with self._lock:
            t = self._tenants.get(name)
        if t is None:
            raise KeyError("unknown tenant %r (registered: %r)"
                           % (name, self.names()))
        return t

    def has(self, name: str) -> bool:
        with self._lock:
            return name in self._tenants

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._tenants)

    # -- quota admission ------------------------------------------------
    def check_quota(self, name: str, cost: float = 1.0,
                    now: Optional[float] = None):
        """Refill the tenant's token bucket and raise
        `TenantQuotaExceeded` (counting the shed) when it cannot cover
        `cost` — WITHOUT consuming anything. The fleet calls this
        BEFORE its own saturation shed and `consume()` only once the
        request is actually accepted: a request refused for fleet
        overload must not drain the tenant's bucket or count against
        its submissions (quota punished for overload would be exactly
        the blur the check ordering exists to prevent). The bucket
        refills continuously at `rate`, capped at `burst` — the
        standard token bucket, so a tenant may burst to its capacity
        and then sustains exactly its rate. Never journaled by the
        caller: shed requests were never accepted."""
        t = self.get(name)
        now = time.monotonic() if now is None else now
        with self._lock:
            if t._refill_at is not None:
                t._tokens = min(
                    t.burst, t._tokens + (now - t._refill_at) * t.rate)
            t._refill_at = now
            if t._tokens < cost:
                t.shed_quota += 1
                retry = (cost - t._tokens) / t.rate
                raise TenantQuotaExceeded(
                    "tenant %r over admission quota: %.2f credit(s) in "
                    "bucket, %.2f needed (rate %g/s, burst %g) — retry "
                    "in %.3fs" % (name, t._tokens, cost, t.rate,
                                  t.burst, retry),
                    tenant=name, retry_after_s=retry)

    def consume(self, name: str, cost: float = 1.0):
        """The accept half: charge the bucket and count the
        submission. Clamped at zero for robustness, but under the
        fleet's lock a `check_quota` that just passed guarantees the
        credit is there."""
        t = self.get(name)
        with self._lock:
            t._tokens = max(0.0, t._tokens - cost)
            t.submitted += 1

    def admit(self, name: str, cost: float = 1.0,
              now: Optional[float] = None):
        """check_quota + consume as one call (tests / callers without
        an intervening accept gate)."""
        self.check_quota(name, cost=cost, now=now)
        self.consume(name, cost=cost)

    # -- completion accounting (called under the fleet's _cond) ---------
    def on_complete(self, name: str, n_tokens: int,
                    queue_wait_s: Optional[float] = None,
                    batch: bool = False):
        t = self.get(name)
        with self._lock:
            t.completed += 1
            t.tokens_out += int(n_tokens)
            if batch:
                t.batch_jobs += 1
            if queue_wait_s is not None:
                t.queue_wait_s.append(queue_wait_s)

    def on_expire(self, name: str):
        t = self.get(name)
        with self._lock:
            t.expired += 1

    def on_reject(self, name: str):
        t = self.get(name)
        with self._lock:
            t.rejected += 1

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            return {n: t.snapshot() for n, t in self._tenants.items()}


class WFQueue(object):
    """Virtual-time weighted fair queue of fleet handles. Confined to
    the fleet's scheduler state (mutated only under its `_cond`, like
    the replica inboxes this drains into)."""

    def __init__(self):
        # heap of (finish_tag, seq, handle); seq breaks ties FCFS
        self._heap: List[Tuple[float, int, object]] = []  # guarded-by: fleet
        self._seq = 0                                     # guarded-by: fleet
        self._vtime = 0.0                                 # guarded-by: fleet
        self._last_tag: Dict[str, float] = {}             # guarded-by: fleet

    def push(self, tenant: str, weight: float, cost: float, handle):
        """Stamp the request's virtual finish tag and enqueue. A
        tenant with backlog accumulates tags `cost/weight` apart; an
        idle tenant re-enters at the current virtual time (it is not
        owed credit for time it had nothing queued — the WFQ
        freshness rule)."""
        tag = max(self._vtime, self._last_tag.get(tenant, 0.0)) \
            + float(cost) / float(weight)
        self._last_tag[tenant] = tag
        heapq.heappush(self._heap, (tag, self._seq, handle))
        self._seq += 1

    def pop(self):
        tag, _seq, h = heapq.heappop(self._heap)
        self._vtime = tag
        return h

    def entries(self):
        """The waiting handles, unordered (the fleet's deadline sweep:
        a verdict must not wait for dispatch-window capacity)."""
        return [h for _tag, _seq, h in self._heap]

    def clear(self):
        self._heap = []

    def __len__(self):
        return len(self._heap)

    def __bool__(self):
        return bool(self._heap)


def executor_batch_fn(exe, program, feed: dict, fetch_list,
                      scope=None):
    """One model-zoo micro-batch as a batch-lane job: a closure over
    the EXISTING `fluid.Executor` path (the reference's
    `save_inference_model` serving story), runnable by
    `ServingFleet.submit_batch`. The replica scheduler runs it between
    engine steps; its return value lands on the handle's
    `batch_result`. Pass the `scope` the program's parameters live in
    when it is not the executor's default."""
    def run():
        if scope is not None:
            from ..fluid.executor import scope_guard

            with scope_guard(scope):
                return exe.run(program, feed=feed,
                               fetch_list=fetch_list)
        return exe.run(program, feed=feed, fetch_list=fetch_list)

    return run
