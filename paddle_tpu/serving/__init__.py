"""Continuous-batching inference serving (ISSUE 2 tentpole + ISSUE 4
prefix reuse + ISSUE 6 fleet): slotted KV cache + prefix-cached chunked
prefill + one compiled decode step over models/transformer.py's
cached-decode primitives, replicated behind a fault-tolerant front
door. See engine.py for the engine design story, prefix_cache.py for
the trie-keyed KV pool, fleet.py for the supervised replica fleet
(durable request journal, incarnation-fenced failover, prefix-affinity
routing, backpressure), and tests/test_serving_engine.py +
tests/test_serving_fleet.py for the correctness bars (token identity
vs sequential generate(); zero requests lost or answered twice under
kill drills)."""

from .engine import EngineFailed, ServingEngine, ServingHandle
from .fleet import (
    FleetHandle,
    FleetSaturated,
    RequestJournal,
    ServingFleet,
)
from .metrics import ServingMetrics
from .prefix_cache import PrefixCache, PrefixMatch, chain_keys

__all__ = ["ServingEngine", "ServingHandle", "ServingMetrics",
           "PrefixCache", "PrefixMatch", "chain_keys", "EngineFailed",
           "ServingFleet", "FleetHandle", "FleetSaturated",
           "RequestJournal"]
