"""Continuous-batching inference serving (ISSUE 2 tentpole + ISSUE 4
prefix reuse + ISSUE 6 fleet + ISSUE 7 paged KV + ISSUE 14
quantization): a paged KV block pool with per-slot block tables (f32,
or int8/fp8 codes with per-block absmax scales — quantization.py +
the kv_quant engine knob) + prefix reuse by ref-counted block
aliasing + chunked prefill + one compiled decode (or speculative
verify) step over models/transformer.py's paged primitives, replicated
behind a fault-tolerant front door. See engine.py for the engine
design story, kv_blocks.py for the pool allocator
(reservation/ref-count discipline), prefix_cache.py for the trie-keyed
prefix pool, fleet.py for the supervised replica fleet (durable
request journal, incarnation-fenced failover, prefix-affinity routing,
backpressure), and tests/test_serving_engine.py +
tests/test_serving_fleet.py for the correctness bars (token identity
vs sequential generate() across paging/speculation/failover; zero
requests lost or answered twice under kill drills)."""

from .adapters import AdapterPool, AdapterRegistry, make_adapter
from .engine import EngineFailed, ServingEngine, ServingHandle
from .integrity import (
    BlockFingerprints,
    IntegrityError,
    ServingSentinel,
    fp_digest,
    golden_trace,
)
from .fleet import (
    DeadlineExceeded,
    FleetHandle,
    FleetSaturated,
    FleetTimeout,
    RequestCancelled,
    RequestJournal,
    RolloutAborted,
    ServingFleet,
    save_weights,
)
from .frontdoor import FrontDoor
from .loadgen import LoadReport, find_knee, run_open_loop, sweep
from .wire import (
    ERROR_CODES,
    MAX_FRAME_BYTES,
    WireClient,
    WireError,
    error_code_for,
    read_frame,
    send_frame,
)
from .kv_blocks import KVBlockAllocator
from .kv_store import KVBlockStore, make_block_record
from .metrics import ServingMetrics
from .prefix_cache import PrefixCache, PrefixMatch, chain_keys, fold_key
from .quantization import (
    QuantTensor,
    dequantize_params,
    params_bytes,
    quantize_params,
)
from .tenancy import (
    Tenant,
    TenantQuotaExceeded,
    TenantRegistry,
    WFQueue,
    executor_batch_fn,
)

__all__ = ["ServingEngine", "ServingHandle", "ServingMetrics",
           "PrefixCache", "PrefixMatch", "chain_keys", "EngineFailed",
           "ServingFleet", "FleetHandle", "FleetSaturated",
           "RequestJournal", "KVBlockAllocator", "DeadlineExceeded",
           "FleetTimeout", "RolloutAborted", "save_weights",
           "AdapterPool", "AdapterRegistry", "make_adapter",
           "Tenant", "TenantRegistry", "TenantQuotaExceeded",
           "WFQueue", "executor_batch_fn", "QuantTensor",
           "quantize_params", "dequantize_params", "params_bytes",
           "IntegrityError", "BlockFingerprints", "ServingSentinel",
           "golden_trace", "KVBlockStore", "fold_key", "fp_digest",
           "make_block_record", "RequestCancelled", "FrontDoor",
           "WireClient", "WireError", "ERROR_CODES", "MAX_FRAME_BYTES",
           "error_code_for", "read_frame", "send_frame", "LoadReport",
           "run_open_loop", "sweep", "find_knee"]
