"""Continuous-batching inference serving (ISSUE 2 tentpole): slotted KV
cache + bucketed prefill + one compiled decode step over
models/transformer.py's cached-decode primitives. See engine.py for the
design story and tests/test_serving_engine.py for the correctness bar
(greedy outputs bit-identical to sequential generate())."""

from .engine import ServingEngine, ServingHandle
from .metrics import ServingMetrics

__all__ = ["ServingEngine", "ServingHandle", "ServingMetrics"]
