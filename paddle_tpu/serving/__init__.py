"""Continuous-batching inference serving (ISSUE 2 tentpole + ISSUE 4
prefix reuse): slotted KV cache + prefix-cached chunked prefill + one
compiled decode step over models/transformer.py's cached-decode
primitives. See engine.py for the design story, prefix_cache.py for the
trie-keyed KV pool, and tests/test_serving_engine.py for the
correctness bar (greedy outputs bit-identical to sequential
generate() on every hit/miss/partial-hit/eviction path)."""

from .engine import ServingEngine, ServingHandle
from .metrics import ServingMetrics
from .prefix_cache import PrefixCache, PrefixMatch

__all__ = ["ServingEngine", "ServingHandle", "ServingMetrics",
           "PrefixCache", "PrefixMatch"]
