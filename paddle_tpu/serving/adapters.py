"""Paged LoRA-style adapter serving: many per-tenant weight deltas
batched over ONE base model in the engine's single compiled step
(ISSUE 12 tentpole, half 1 of 2 — tenancy.py is the admission half).

The multi-tenant problem Punica (Chen et al.) and S-LoRA (Sheng et
al.) solve: N tenants each want the base model plus a small low-rank
delta (A @ B on the q/v projections), and serving N separately wastes
N-1 copies of the base weights AND retraces the decode step per
tenant. The winning shape is ONE resident base model, ONE compiled
step, and a per-slot ADAPTER INDEX side-band: slot s gathers its
delta out of a device-resident adapter pool exactly like its KV rows
gather through the block table. This module is that shape in the
repo's paging idiom:

  * `AdapterRegistry` — the host-side store of named adapters
    (per-layer stacked A/B arrays for the q and v projections, plus a
    scalar scale). Read-mostly; its own lock makes registration safe
    against serving threads.
  * `AdapterPool` — the engine-side residency manager. The device
    pool is [P, layers, ...] stacked arrays; WHICH adapters are
    resident is run through the SAME `KVBlockAllocator` discipline
    the KV blocks use (kv_blocks.py: free list + ref-counts —
    one pool slot is one "block"): admission `acquire()`s the
    request's adapter (refcount = residency + live users), retirement
    `release()`s it, and a cold miss allocates a slot, evicting the
    least-recently-used RESIDENT-BUT-IDLE adapter (refcount exactly
    the residency ref) when the pool is full — LRU over idle entries
    only, exactly the prefix trie's leaf-eviction rule. A pool whose
    every slot is pinned by live requests returns None: the request
    stays QUEUED (the block-pool backpressure discipline), never a
    raise.
  * Slot 0 is the permanently resident ZERO adapter (A = B = 0,
    scale = 0): requests with no adapter ride index 0 and the
    compiled delta contributes exact zeros — greedy outputs are
    token-identical to the base model with no adapter math at all
    (transformer._adapter_delta docstring).

Attach/detach is BAND TRAFFIC, not a retrace: the pool arrays keep
their [P, layers, ...] shapes forever, an attach is an eager
`.at[slot].set()` dispatch plus a dirty flag on the engine's
adapter-index band, and the decode/verify/prefill-chunk steps stay
traced exactly once across any number of adapter swaps (the
compile-count regression tests pin this).

Host bookkeeping only — the compiled gather lives in
models/transformer.py; the band wiring in serving/engine.py. All pool
state is confined to the engine's scheduler thread, like the block
allocator it wraps.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

from .kv_blocks import KVBlockAllocator

__all__ = ["AdapterRegistry", "AdapterPool", "make_adapter"]


def make_adapter(cfg, rank: int, seed: int = 0, scale: float = 1.0,
                 stddev: float = 0.25) -> dict:
    """Random-init one LoRA-style adapter for `cfg` (tests/bench
    helper): per-layer A [layers, d, r] Gaussian, B [layers, r, d]
    Gaussian — both non-zero AND large enough that the delta moves
    argmaxes on toy models, so a wrong adapter index actually changes
    tokens (a B = 0 init, the training convention, would make every
    adapter behave like the zero adapter and hide routing bugs)."""
    rng = np.random.RandomState(seed)
    L, d, r = int(cfg.layers), int(cfg.dim), int(rank)

    def g(shape):
        return (stddev * rng.standard_normal(shape)).astype(np.float32)

    return {
        "a_q": g((L, d, r)), "b_q": g((L, r, d)),
        "a_v": g((L, d, r)), "b_v": g((L, r, d)),
        "scale": float(scale),
    }


class AdapterRegistry(object):
    """Named adapter store shared by every replica's `AdapterPool`.
    Register before (or during) serving; reads are lock-protected so a
    replica thread paging an adapter in never races a registration."""

    def __init__(self, rank: Optional[int] = None):
        self._lock = threading.Lock()
        # name -> {"a_q","b_q","a_v","b_v" np arrays, "scale" float}
        self._adapters: Dict[str, dict] = {}  # guarded-by: _lock
        self.rank = None if rank is None else int(rank)

    def register(self, name: str, adapter: dict):
        """Add (or replace) one adapter. Arrays must share one rank
        across the registry — the device pool is one stacked tensor,
        so ragged ranks would need per-adapter padding nobody asked
        for; refuse loudly instead."""
        a_q = np.asarray(adapter["a_q"], np.float32)
        r = int(a_q.shape[-1])
        with self._lock:
            if self.rank is None:
                self.rank = r
            elif r != self.rank:
                raise ValueError(
                    "adapter %r has rank %d, the registry is rank %d "
                    "(one stacked device pool = one rank)"
                    % (name, r, self.rank))
            self._adapters[name] = {
                "a_q": a_q,
                "b_q": np.asarray(adapter["b_q"], np.float32),
                "a_v": np.asarray(adapter["a_v"], np.float32),
                "b_v": np.asarray(adapter["b_v"], np.float32),
                "scale": float(adapter.get("scale", 1.0)),
            }

    def get(self, name: str) -> dict:
        with self._lock:
            if name not in self._adapters:
                raise KeyError("unknown adapter %r (registered: %r)"
                               % (name, sorted(self._adapters)))
            return self._adapters[name]

    def has(self, name: str) -> bool:
        with self._lock:
            return name in self._adapters

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._adapters)


class AdapterPool(object):
    """Device-resident adapter pool for ONE engine: stacked
    [P, layers, ...] arrays the compiled steps gather from by per-slot
    index, plus the residency bookkeeping (a `KVBlockAllocator` over P
    one-token "blocks": ref-counts, free list) and an LRU over
    resident-but-idle adapters. Slot 0 is the pinned zero adapter."""

    def __init__(self, cfg, registry: AdapterRegistry, slots: int,
                 rank: Optional[int] = None):
        import jax.numpy as jnp

        P = int(slots)
        if P < 2:
            raise ValueError(
                "adapter_slots must be >= 2 (slot 0 is the pinned "
                "zero adapter)")
        r = registry.rank if rank is None else int(rank)
        if r is None:
            raise ValueError(
                "adapter rank unknown: register an adapter first or "
                "pass adapter_rank")
        self.registry = registry
        self.slots = P
        self.rank = r
        L, d = int(cfg.layers), int(cfg.dim)
        # the device pool: zeros everywhere, so every slot starts as
        # (and an evicted slot decays back to, without a scrub — its
        # scale is zeroed) a delta nothing distinguishes from absent
        self._a_q = jnp.zeros((P, L, d, r), jnp.float32)  # guarded-by: scheduler
        self._b_q = jnp.zeros((P, L, r, d), jnp.float32)  # guarded-by: scheduler
        self._a_v = jnp.zeros((P, L, d, r), jnp.float32)  # guarded-by: scheduler
        self._b_v = jnp.zeros((P, L, r, d), jnp.float32)  # guarded-by: scheduler
        self._scale = jnp.zeros((P,), jnp.float32)        # guarded-by: scheduler
        # residency accounting IS a block allocator: one slot = one
        # block, refcount 1 = resident only (evictable), > 1 = pinned
        # by live requests
        self._alloc = KVBlockAllocator(P, 1)              # guarded-by: scheduler
        self._alloc.reserve(1)
        zero = self._alloc.alloc_reserved()
        assert zero == 0  # the allocator pops ascending ids
        self._resident: Dict[str, int] = {}               # guarded-by: scheduler
        self._slot_name: Dict[int, str] = {}              # guarded-by: scheduler
        self._lru: List[str] = []  # oldest first               # guarded-by: scheduler
        # O(1) counters (the ServingMetrics discipline)
        self.hits = 0                                     # guarded-by: scheduler
        self.misses = 0                                   # guarded-by: scheduler
        self.evictions = 0                                # guarded-by: scheduler
        self.uploads = 0                                  # guarded-by: scheduler

    # -- device side ----------------------------------------------------
    def device_arrays(self) -> dict:
        """The stacked pool arrays the compiled steps gather from, in
        transformer._adapter_delta's key shape."""
        return {"a_q": self._a_q, "b_q": self._b_q,
                "a_v": self._a_v, "b_v": self._b_v,
                "scale": self._scale}

    def _upload(self, slot: int, ad: dict):
        import jax.numpy as jnp

        # eager dispatches, NOT a retrace: shapes never change
        self._a_q = self._a_q.at[slot].set(jnp.asarray(ad["a_q"]))
        self._b_q = self._b_q.at[slot].set(jnp.asarray(ad["b_q"]))
        self._a_v = self._a_v.at[slot].set(jnp.asarray(ad["a_v"]))
        self._b_v = self._b_v.at[slot].set(jnp.asarray(ad["b_v"]))
        self._scale = self._scale.at[slot].set(ad["scale"])
        self.uploads += 1

    # -- residency ------------------------------------------------------
    def _touch(self, name: str):
        self._lru.remove(name)
        self._lru.append(name)

    def _evict_idle(self) -> bool:
        """Evict the least-recently-used resident adapter nobody holds
        (refcount == the residency ref alone). False when every
        resident adapter is pinned by a live request."""
        for name in self._lru:
            slot = self._resident[name]
            if self._alloc.refcount(slot) == 1:
                self._alloc.decref(slot)  # frees: residency was last
                del self._resident[name]
                del self._slot_name[slot]
                self._lru.remove(name)
                # the stale weights may stay in HBM, but the slot is
                # unreachable until re-uploaded (scale stays until the
                # next tenant's attach overwrites it; no index can name
                # a freed slot — the engine clears bands at retire)
                self.evictions += 1
                return True
        return False

    def acquire(self, name: Optional[str]) -> Optional[int]:
        """Pin one adapter for a request being admitted and return its
        pool slot. None (no adapter) is the zero slot and always
        succeeds. A cold miss pages the adapter in (allocating a slot,
        LRU-evicting an idle resident one if the pool is full); when
        every slot is pinned by live requests, returns None — the
        caller leaves the request QUEUED, the block-pool backpressure
        rule."""
        if name is None:
            self._alloc.incref(0)
            return 0
        slot = self._resident.get(name)
        if slot is not None:
            self._alloc.incref(slot)
            self._touch(name)
            self.hits += 1
            return slot
        ad = self.registry.get(name)  # raises on unknown: caller's bug
        if self._alloc.available < 1 and not self._evict_idle():
            return None  # saturated: every resident adapter is live
        self.misses += 1
        self._alloc.reserve(1)
        slot = self._alloc.alloc_reserved()
        self._upload(slot, ad)
        self._resident[name] = slot
        self._slot_name[slot] = name
        self._lru.append(name)
        self._alloc.incref(slot)  # the request's pin, over residency's
        return slot

    def release(self, slot: int):
        """Drop one request's pin (retirement). The residency ref
        keeps the adapter warm for the next request; eviction happens
        only under a cold miss with a full pool."""
        self._alloc.decref(slot)

    def detach(self, name: str) -> bool:
        """Operator surface: evict one adapter now. False when it is
        not resident or pinned by a live request."""
        slot = self._resident.get(name)
        if slot is None or self._alloc.refcount(slot) != 1:
            return False
        self._alloc.decref(slot)
        del self._resident[name]
        del self._slot_name[slot]
        self._lru.remove(name)
        self.evictions += 1
        return True

    def resident(self) -> List[str]:
        return sorted(self._resident)

    def refcount(self, name: str) -> int:
        slot = self._resident.get(name)
        return 0 if slot is None else self._alloc.refcount(slot)

    def stats(self) -> dict:
        return {
            "slots": self.slots,
            "rank": self.rank,
            "resident": len(self._resident),
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "uploads": self.uploads,
        }
