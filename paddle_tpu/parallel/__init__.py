"""Distributed training over the device mesh.

This module replaces four reference communication stacks with one
XLA-collectives layer (SURVEY.md §2.3):
  - MultiGradientMachine ring allreduce (MultiGradientMachine.h:61-84)
  - NCCL ops (fluid/operators/nccl_op.cu.cc)
  - C++ sync pserver (paddle/pserver/ParameterServer2.h)
  - fluid gRPC send/recv + DistributeTranspiler

Design: programs keep *global-batch* semantics. The executor jits the
step with `in_shardings` — feeds split on the mesh 'data' axis, params
placed per `program.shardings` (replicated by default; any PartitionSpec
for tensor parallelism) — and XLA's SPMD partitioner inserts the psum /
all-gather collectives over ICI. Gradients are therefore *exactly* the
global-batch gradients, unlike the reference's per-worker average.
"""

from .mesh import (
    DistributedContext,
    data_sharding,
    get_default_mesh,
    make_hybrid_mesh,
    make_mesh,
    replicated,
    set_default_mesh,
    shard_parameter,
    shard_parameters_fsdp,
)
from .attention import (
    reference_attention,
    ring_attention,
    sequence_parallel_attention,
    ulysses_attention,
    zigzag_ring_attention,
    zigzag_permutation,
)
from .embedding import ShardedEmbedding, sharded_lookup
from .moe import expert_parallel_moe, moe_capacity, reference_moe
from .pipeline import gpipe_pipeline, reference_pipeline
from .flash_attention import flash_attention
from .paged_attention import (
    paged_decode_attention,
    paged_prefill_attention,
    paged_verify_attention,
)

__all__ = [
    "flash_attention",
    "paged_decode_attention",
    "paged_prefill_attention",
    "paged_verify_attention",
    "gpipe_pipeline",
    "reference_pipeline",
    "expert_parallel_moe",
    "moe_capacity",
    "reference_moe",
    "make_mesh",
    "make_hybrid_mesh",
    "get_default_mesh",
    "set_default_mesh",
    "shard_parameter",
    "shard_parameters_fsdp",
    "data_sharding",
    "replicated",
    "DistributedContext",
    "ring_attention",
    "ulysses_attention",
    "zigzag_ring_attention",
    "zigzag_permutation",
    "sequence_parallel_attention",
    "reference_attention",
    "sharded_lookup",
    "ShardedEmbedding",
]
