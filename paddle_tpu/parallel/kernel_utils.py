"""Shared Pallas kernel utilities (ISSUE 13 satellite): the
backend-detection and masking helpers that every Mosaic kernel in
parallel/ needs, hoisted out of flash_attention.py so the paged
attention kernels (paged_attention.py) consume ONE copy of the
CPU/TPU interpret logic instead of re-deriving it.

Everything here is numerics-bearing: `NEG_INF` is the finite mask fill
that the online-softmax guards compare against (a fully-masked tile
must be an EXACT no-op on the running (max, sum, acc) state — see
flash_attention._fa_kernel), and `causal_fill` is shared between the
flash forward and backward so the probability tiles they build can
never disagree.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["NEG_INF", "resolve_interpret", "causal_fill"]

# Finite -inf stand-in for score masking. Finite on purpose: the
# online-softmax no-op guards (`p = where(s <= NEG_INF, 0, p)`,
# `alpha = where(m_prev <= NEG_INF, 0, alpha)`) need exact comparisons,
# and exp(-1e30 - m) underflows to exactly 0.0 for any finite m.
NEG_INF = -1e30


def resolve_interpret(interpret):
    """None -> interpret on the CPU backend (CI), compile Mosaic
    elsewhere. AOT lowering for a TPU topology from a CPU host must
    pass an explicit False — the host backend is the wrong signal
    there (bench_offline's ulysses workload does)."""
    if interpret is not None:
        return interpret
    return jax.default_backend() == "cpu"


def causal_fill(s, qi, kj, block_q, block_k):
    """Mask the upper triangle of one [block_q, block_k] score tile to
    NEG_INF. Shared by the flash forward online-softmax and the
    backward probability reconstruction so the two can never
    disagree."""
    q_idx = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_idx = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    return jnp.where(q_idx >= k_idx, s, NEG_INF)
