"""Sequence/context-parallel attention: ring attention + Ulysses.

These are NEW capabilities beyond the 2018 reference (SURVEY.md §2.2: the
reference's long-sequence story was LoD ragged tensors + chunked RNNs;
attention-era sequence parallelism did not exist). They are first-class
here because they shape the core design for long-context models on TPU:

* ring_attention — blockwise-softmax attention where each 'seq' shard
  holds a [T/n] slice of Q locally and K/V blocks rotate around the mesh
  axis via `lax.ppermute` (one ICI hop per step, n steps). Memory per chip
  is O(T/n), compute overlaps the collective, and the online-softmax
  accumulation makes the result EXACTLY equal to full attention.
* ulysses_attention — all-to-all alternative: heads are exchanged for
  sequence (`lax.all_to_all`), each shard computes full-sequence attention
  for H/n heads, then the transpose all-to-all restores layout. Cheaper
  when H >= n and T is moderate; ring wins at very long T.

Both run inside `shard_map` over the mesh's 'seq' axis and are fully
differentiable (ppermute/all_to_all have transpose rules, the ring loop is
a lax.scan).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

__all__ = [
    "ring_attention",
    "ulysses_attention",
    "sequence_parallel_attention",
    "reference_attention",
]

_NEG_INF = -1e30


def reference_attention(q, k, v, causal: bool = False, scale=None):
    """Plain full attention [B, T, H, D] — the correctness oracle and the
    single-device fallback."""
    B, T, H, D = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    s = jnp.einsum("bthd,bshd->bhts", q * scale, k)
    if causal:
        mask = jnp.tril(jnp.ones((T, k.shape[1]), bool), k.shape[1] - T)
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", p, v)


def _varying(x, axis_name):
    """Mark a scan-carry constant as device-varying over the ring axis
    (shard_map's vma type system; constants start out unvarying)."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, (axis_name,), to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, (axis_name,))
    return x


def _online_softmax_update(o, l, m, s, vs):
    """One online-softmax accumulation over a pre-masked f32 score tile
    `s`: rescale the running (o, l) by the max shift and fold in this
    tile's contribution. The _NEG_INF guards keep fully-masked rows at
    exact zero (exp never sees inf - inf). Shared by both ring
    layouts so the numerics can never diverge."""
    m_new = jnp.maximum(m, s.max(axis=-1))
    safe = jnp.where(m_new <= _NEG_INF, 0.0, m_new)
    p = jnp.exp(s - safe[..., None])
    p = jnp.where(s <= _NEG_INF, 0.0, p)
    corr = jnp.where(m <= _NEG_INF, 0.0, jnp.exp(m - safe))
    l_new = l * corr + p.sum(axis=-1)
    o_new = o * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhts,bshd->bthd", p.astype(vs.dtype), vs,
        preferred_element_type=jnp.float32,
    )
    return o_new, l_new, m_new


def ring_attention(q, k, v, axis_name: str, causal: bool = False, scale=None):
    """Blockwise ring attention; call inside shard_map with q/k/v sharded
    [B, T/n, H, D] on the sequence axis."""
    n = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    B, T, H, D = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    # keep the MXU matmuls in the input dtype (bf16 stays bf16) with f32
    # accumulation via preferred_element_type; softmax stats are f32 and
    # the scale multiplies the f32 scores post-matmul (folding it into
    # bf16 q would round it — same rule as the flash kernel)
    qf = q

    q_pos = me * T + jnp.arange(T)  # global row ids of the local queries
    perm = [(i, (i + 1) % n) for i in range(n)]

    o0 = _varying(jnp.zeros((B, T, H, D), jnp.float32), axis_name)
    l0 = _varying(jnp.zeros((B, H, T), jnp.float32), axis_name)
    m0 = _varying(jnp.full((B, H, T), _NEG_INF, jnp.float32), axis_name)

    def step(carry, i):
        o, l, m, kb, vb = carry
        src = (me - i) % n  # which shard's K/V block we hold this step

        def accumulate(o, l, m, kb, vb):
            k_pos = src * T + jnp.arange(T)
            s = jnp.einsum("bthd,bshd->bhts", qf, kb,
                           preferred_element_type=jnp.float32) * scale
            if causal:
                mask = q_pos[:, None] >= k_pos[None, :]
                s = jnp.where(mask[None, None], s, _NEG_INF)
            return _online_softmax_update(o, l, m, s, vb)

        if causal:
            # a source chunk strictly to the right of this shard's rows
            # is fully masked: skip both matmuls. NOTE: the ring is
            # lock-step (every device reaches the ppermute each step),
            # so this frees compute/energy on the skipping devices but
            # does NOT shorten the critical path — the last shard
            # accumulates on every step. The latency fix is striped
            # (zigzag) row assignment so all shards do ~half a block
            # per step; future work.
            o, l, m = lax.cond(
                src > me,
                lambda o, l, m, kb, vb: (o, l, m),
                accumulate,
                o, l, m, kb, vb,
            )
        else:
            o, l, m = accumulate(o, l, m, kb, vb)
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return (o, l, m, kb, vb), None

    (o, l, _, _, _), _ = lax.scan(step, (o0, l0, m0, k, v), jnp.arange(n))
    out = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def zigzag_ring_attention(q, k, v, axis_name: str, causal: bool = True,
                          scale=None):
    """Causal ring attention with ZIGZAG (striped) row assignment —
    the load-balance fix ring_attention's causal path documents as
    future work: with contiguous rows, the last shard's rows see every
    source block, so the lock-step ring's critical path never benefits
    from the causal skip. Striped, shard i holds stripe i (rows
    [iC, (i+1)C), C = T_local/2) and its mirror stripe 2n-1-i; each
    ring step then costs every shard ~2 stripe-matmuls instead of the
    tail shard's 4 (per-step work is the max over shards — lock-step).

    Because stripes are aligned, visibility per (q-stripe, k-stripe)
    pair is decided at stripe granularity: mirror-vs-front is always
    visible, front-vs-mirror never, equal indices are the tril
    diagonal — no global position arrays needed. Call inside shard_map
    with the STRIPED layout (sequence_parallel_attention permutes);
    causal only (the balance problem does not exist otherwise)."""
    if not causal:
        raise ValueError("zigzag ring attention is causal-only; use "
                         "ring_attention for the non-causal case")
    n = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    B, T, H, D = q.shape
    C = T // 2
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    perm = [(i, (i + 1) % n) for i in range(n)]
    tril = jnp.tril(jnp.ones((C, C), bool))

    def accum(qs, ks, vs, masked):
        def f(o, l, m):
            s = jnp.einsum("bthd,bshd->bhts", qs, ks,
                           preferred_element_type=jnp.float32) * scale
            if masked:
                s = jnp.where(tril[None, None], s, _NEG_INF)
            return _online_softmax_update(o, l, m, s, vs)

        return f

    def attend(carry_half, qs, ks, vs, mode):
        """Online-softmax update of one q stripe against one k stripe.
        mode: 0 skip (fully masked), 1 diagonal (tril), 2 fully
        visible."""
        o, l, m = carry_half
        return lax.switch(
            mode,
            [lambda o, l, m: (o, l, m), accum(qs, ks, vs, True),
             accum(qs, ks, vs, False)],
            o, l, m,
        )

    def half_init():
        return (
            _varying(jnp.zeros((B, C, H, D), jnp.float32), axis_name),
            _varying(jnp.zeros((B, H, C), jnp.float32), axis_name),
            _varying(jnp.full((B, H, C), _NEG_INF, jnp.float32),
                     axis_name),
        )

    def step(carry, i):
        f_half, b_half, kb, vb = carry
        src = (me - i) % n
        kf, km = kb[:, :C], kb[:, C:]
        vf, vm = vb[:, :C], vb[:, C:]
        # front q stripe (index me) vs source front stripe (index src):
        # strictly later stripe sees all of an earlier one
        mode_ff = jnp.where(me > src, 2, jnp.where(me == src, 1, 0))
        f_half = attend(f_half, q[:, :C], kf, vf, mode_ff)
        # mirror q stripe (index 2n-1-me) vs source front: ALWAYS later
        # — unconditional accumulate, no branch to obscure the matmul
        b_half = accum(q[:, C:], kf, vf, False)(*b_half)
        # mirror q vs source mirror (index 2n-1-src): inverted order
        mode_bm = jnp.where(me < src, 2, jnp.where(me == src, 1, 0))
        b_half = attend(b_half, q[:, C:], km, vm, mode_bm)
        # front q vs source mirror: a front stripe never sees a mirror
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return (f_half, b_half, kb, vb), None

    (f_half, b_half, _, _), _ = lax.scan(
        step, (half_init(), half_init(), k, v), jnp.arange(n)
    )

    def finish(half):
        o, l, _ = half
        return o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]

    return jnp.concatenate(
        [finish(f_half), finish(b_half)], axis=1
    ).astype(q.dtype)


def _zigzag_entry(q, k, v, mesh, axis, causal, scale):
    """Global-view zigzag dispatch: permute rows to the striped layout,
    run the balanced causal ring under shard_map, un-permute.

    Convenience form — it pays the stripe gather/scatter per call. A
    transformer stack should instead keep activations in the striped
    layout end-to-end (position-free layers are layout-oblivious):
    apply zigzag_permutation once at the embedding, call
    zigzag_ring_attention directly inside the model's shard_map region,
    and invert once at the head."""
    if not causal:
        raise ValueError("impl='zigzag' is causal-only")
    n = mesh.shape[axis]
    T = q.shape[1]
    if T % (2 * n) != 0:
        raise ValueError(
            "zigzag needs the sequence length (%d) divisible by 2*axis "
            "size (%d)" % (T, 2 * n)
        )
    perm, inv = zigzag_permutation(T, n)
    qz, kz, vz = (jnp.take(x, perm, axis=1) for x in (q, k, v))
    spec = P(None, axis, None, None)
    mapped = shard_map(
        functools.partial(zigzag_ring_attention, axis_name=axis,
                          causal=True, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    return jnp.take(mapped(qz, kz, vz), inv, axis=1)


def zigzag_permutation(T_global: int, n: int):
    """Row permutation taking the natural sequence order to the zigzag
    shard layout: shard i's contiguous slot holds stripe i then stripe
    2n-1-i. Returns (perm, inverse) index arrays of length T_global."""
    import numpy as _np

    C = T_global // (2 * n)
    order = []
    for i in range(n):
        order.append(_np.arange(i * C, (i + 1) * C))
        j = 2 * n - 1 - i
        order.append(_np.arange(j * C, (j + 1) * C))
    perm = _np.concatenate(order)
    inv = _np.argsort(perm)
    return perm, inv


def ulysses_attention(q, k, v, axis_name: str, causal: bool = False,
                      scale=None, impl: str = "reference",
                      interpret: Optional[bool] = None):
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism; call
    inside shard_map with [B, T/n, H, D] shards. Requires H % n == 0.

    After the head<->sequence exchange each shard holds its heads' FULL
    sequence, so the local attention is exactly the single-chip problem
    — impl="flash" runs the pallas flash kernel per shard (O(T) memory,
    pallas backward; the enclosing shard_map needs check_vma=False for
    the interpret-mode CI path — sequence_parallel_attention arranges
    that); the default impl="reference" materialises the [T, T] scores
    (oracle path, and the pre-r5 behavior for direct callers).
    `interpret` follows flash_attention.resolve_interpret."""
    # exchange: split heads across the axis, gather the full sequence
    qg = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kg = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vg = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    if impl == "flash":
        from .flash_attention import flash_attention, resolve_interpret

        og = flash_attention(
            qg, kg, vg, causal=causal, scale=scale,
            interpret=resolve_interpret(interpret),
        )
    else:
        og = reference_attention(qg, kg, vg, causal=causal, scale=scale)
    return lax.all_to_all(og, axis_name, split_axis=1, concat_axis=2, tiled=True)


def sequence_parallel_attention(
    q, k, v,
    mesh: Optional[Mesh] = None,
    axis: str = "seq",
    impl: str = "ring",
    causal: bool = False,
    scale=None,
    interpret: Optional[bool] = None,
):
    """Global-view entry point: q/k/v are [B, T, H, D] global arrays; the
    sequence dim is sharded over `axis` of `mesh` and attention runs
    sequence-parallel. Without a mesh (or on a size-1 axis):
    impl="flash" runs the pallas flash kernel on the chip, anything else
    the plain full-matrix attention."""
    if mesh is None:
        from .mesh import get_default_mesh

        mesh = get_default_mesh()
    if impl == "zigzag" and not causal:
        # validate BEFORE the no-mesh fallback so a single-device dev
        # run fails the same way the multi-chip run will
        raise ValueError("impl='zigzag' is causal-only")
    if mesh is None or axis not in mesh.axis_names or mesh.shape[axis] == 1:
        if impl == "flash":
            from .flash_attention import flash_attention, resolve_interpret

            return flash_attention(
                q, k, v, causal=causal, scale=scale,
                interpret=resolve_interpret(interpret),
            )
        return reference_attention(q, k, v, causal=causal, scale=scale)
    if q.shape[1] % mesh.shape[axis] != 0:
        raise ValueError(
            "sequence length %d not divisible by mesh axis %r size %d"
            % (q.shape[1], axis, mesh.shape[axis])
        )
    flash_inner = False
    if impl == "flash":
        # multi-shard flash: ulysses' head<->seq all-to-all puts a full
        # sequence per shard, where the pallas kernel (fwd + backward)
        # applies unchanged; heads not divisible by the axis fall back
        # to ring (jnp online-softmax across ppermute steps)
        flash_inner = q.shape[2] % mesh.shape[axis] == 0
        impl = "ulysses" if flash_inner else "ring"
    if impl == "zigzag":
        return _zigzag_entry(q, k, v, mesh, axis, causal, scale)
    fn = {"ring": ring_attention, "ulysses": ulysses_attention}[impl]
    if impl == "ulysses" and q.shape[2] % mesh.shape[axis] != 0:
        raise ValueError("ulysses needs heads divisible by the seq axis size")
    spec = P(None, axis, None, None)
    body = functools.partial(fn, axis_name=axis, causal=causal,
                             scale=scale)
    if flash_inner:
        body = functools.partial(body, impl="flash", interpret=interpret)
    kwargs = dict(
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    if flash_inner:
        # interpret-mode pallas under the vma type system rejects the
        # kernel's internal dynamic_slice on mixed-vma operands (JAX's
        # own error text recommends check_vma=False as the workaround);
        # only the pallas-bearing path drops the check — ring and
        # ulysses-reference keep the replication typing
        try:
            mapped = shard_map(body, check_vma=False, **kwargs)
        except TypeError:  # older jax: no check_vma kwarg
            mapped = shard_map(body, **kwargs)
    else:
        mapped = shard_map(body, **kwargs)
    return mapped(q, k, v)
