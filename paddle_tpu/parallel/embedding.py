"""Sharded embedding tables: the TPU-native sparse/large-model path.

Replaces the reference's row-sharded sparse parameter-server design
(SURVEY.md §2.2 sparse row: SparseRemoteParameterUpdater, prefetch of
needed rows MultiGradientMachine.h:140-166, fluid SelectedRows +
split/sum ops, design doc large_model_dist_train.md): the table lives
row-sharded across a mesh axis; lookup is a local gather of in-range rows
plus one `psum` over the axis (each id's row lives on exactly one shard),
and the backward pass is the transpose — a local scatter-add of exactly
the rows each shard owns. No parameter server, no prefetch protocol; ICI
does the work.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

__all__ = ["sharded_lookup", "ShardedEmbedding"]


def _lookup_shard(table, ids, axis_name: str):
    """Inside shard_map: table [V/n, D] local shard, ids [N] replicated."""
    me = lax.axis_index(axis_name)
    v_loc = table.shape[0]
    local = ids - me * v_loc
    in_range = jnp.logical_and(local >= 0, local < v_loc)
    safe = jnp.clip(local, 0, v_loc - 1)
    rows = jnp.where(in_range[:, None], table[safe], 0)
    return lax.psum(rows, axis_name)


def sharded_lookup(table, ids, mesh: Optional[Mesh] = None, axis: str = "model"):
    """Global-view lookup: `table` is [V, D] sharded rows-first over
    `axis`; `ids` any int array; returns ids.shape + [D]. Differentiable —
    the vjp scatter-adds each shard's own rows (deterministic, no
    pserver round trip)."""
    if mesh is None:
        from .mesh import get_default_mesh

        mesh = get_default_mesh()
    flat = ids.reshape(-1)
    if mesh is None or axis not in mesh.axis_names or mesh.shape[axis] == 1:
        # out-of-range ids yield zero rows, matching the sharded path
        # (where no shard claims them) instead of jax's gather clamping
        valid = jnp.logical_and(flat >= 0, flat < table.shape[0])
        out = jnp.where(
            valid[:, None], table[jnp.clip(flat, 0, table.shape[0] - 1)], 0
        )
    else:
        if table.shape[0] % mesh.shape[axis] != 0:
            raise ValueError(
                "vocab %d not divisible by mesh axis %r size %d"
                % (table.shape[0], axis, mesh.shape[axis])
            )
        out = shard_map(
            functools.partial(_lookup_shard, axis_name=axis),
            mesh=mesh,
            in_specs=(P(axis, None), P()),
            out_specs=P(),
        )(table, flat)
    return out.reshape(tuple(ids.shape) + (table.shape[1],))


class ShardedEmbedding(object):
    """Convenience owner of a row-sharded table (init + lookup + where to
    place the array)."""

    def __init__(self, vocab: int, dim: int, mesh: Mesh, axis: str = "model",
                 dtype=jnp.float32, scale: float = 0.01, key=None):
        self.mesh = mesh
        self.axis = axis
        key = key if key is not None else jax.random.PRNGKey(0)
        table = scale * jax.random.normal(key, (vocab, dim), dtype)
        self.sharding = NamedSharding(mesh, P(axis, None))
        self.table = jax.device_put(table, self.sharding)

    def __call__(self, ids):
        return sharded_lookup(self.table, ids, self.mesh, self.axis)
