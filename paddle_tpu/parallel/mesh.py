"""Device mesh construction + sharding helpers.

Replaces the reference's device/topology plumbing (platform/device_context,
nccl_gpu_common.h Communicator, trainer_count flag) with jax.sharding.Mesh
over ICI. Axis conventions:

  'data'  — batch sharding (data parallelism; grads psum over this axis)
  'model' — tensor parallelism (weight sharding)
  'seq'   — sequence/context parallelism (ring attention milestone)
  'expert'— expert parallelism (MoE milestone)

Multi-host (DCN) note: jax.devices() already spans hosts under multi-host
runtime; the same mesh code covers pod slices — lay 'data' outermost so
its collectives ride DCN only when crossing slices.
"""

from __future__ import annotations

import zlib
from typing import Dict, Optional, Sequence, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

P = PartitionSpec

_default_mesh: Optional[Mesh] = None


def make_mesh(
    axes: Union[int, Dict[str, int], None] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a Mesh. `axes` may be:
      - None: all local devices on one 'data' axis
      - int N: N devices on the 'data' axis
      - dict {'data': 4, 'model': 2}: multi-axis mesh (row-major)
    """
    devices = list(devices) if devices is not None else jax.devices()
    if axes is None:
        axes = {"data": len(devices)}
    if isinstance(axes, int):
        axes = {"data": axes}
    names = tuple(axes.keys())
    sizes = tuple(int(axes[n]) for n in names)
    n = int(np.prod(sizes))
    if n > len(devices):
        raise ValueError(
            "mesh needs %d devices but only %d available" % (n, len(devices))
        )
    arr = np.asarray(devices[:n]).reshape(sizes)
    return Mesh(arr, names)


def make_hybrid_mesh(
    dcn_axes: Dict[str, int],
    ici_axes: Dict[str, int],
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Multi-slice mesh: `dcn_axes` partition across slices (collectives
    ride the data-center network), `ici_axes` partition within a slice
    (collectives ride the chip interconnect). DCN axes are laid
    outermost so only they cross slice boundaries — the layout the
    scaling playbook prescribes (dp over DCN x tp/sp over ICI), and the
    TPU-native form of the reference's two-tier topology (NCCL ring
    within a node, pserver/gRPC across nodes).

    Batch sharding convention: the executor data-shards over axes
    named 'dcn'/'dcn_*' and 'data' (data_parallel_axes); a DCN axis
    with any other name stays out of the batch partition (e.g. a
    cross-slice pipeline tier).

    Devices are grouped into slices by `slice_index` (TPU multi-slice)
    or `process_index` (multi-host CPU/GPU); a single-group platform —
    e.g. the one-process CPU test fixture — emulates the slice structure
    by splitting the device list into contiguous groups, so the mesh
    layout (and the collectives XLA inserts over it) compiles and
    validates without pod hardware.
    """
    devices = list(devices) if devices is not None else jax.devices()
    dcn_names = tuple(dcn_axes.keys())
    dcn_sizes = tuple(int(dcn_axes[n]) for n in dcn_names)
    ici_names = tuple(ici_axes.keys())
    ici_sizes = tuple(int(ici_axes[n]) for n in ici_names)
    n_slices = int(np.prod(dcn_sizes))
    per_slice = int(np.prod(ici_sizes))

    groups: Dict[int, list] = {}
    for d in devices:
        key = getattr(d, "slice_index", None)
        if key is None:
            key = getattr(d, "process_index", 0)
        groups.setdefault(int(key), []).append(d)
    ordered = [groups[k] for k in sorted(groups)]
    if len(ordered) == 1:
        # single-slice platform: emulate the slice split contiguously
        flat = ordered[0]
        if n_slices * per_slice > len(flat):
            raise ValueError(
                "hybrid mesh needs %d devices but only %d available"
                % (n_slices * per_slice, len(flat))
            )
        ordered = [
            flat[i * per_slice:(i + 1) * per_slice] for i in range(n_slices)
        ]
    if len(ordered) != n_slices:
        raise ValueError(
            "dcn axes %r want %d slices but the platform has %d device "
            "groups" % (dict(dcn_axes), n_slices, len(ordered))
        )
    for g in ordered:
        if len(g) < per_slice:
            raise ValueError(
                "ici axes %r want %d devices per slice, a slice has %d"
                % (dict(ici_axes), per_slice, len(g))
            )
        if len(g) > per_slice and len(groups) > 1:
            # a REAL multi-slice platform with surplus chips per slice:
            # silently dropping them would read as a working mesh while
            # under-utilizing the hardware. (The single-group emulation
            # path above keeps the silent split — its surplus is the
            # virtual-device fixture, not idle chips.)
            import warnings

            warnings.warn(
                "make_hybrid_mesh: slice has %d devices but ici axes %r "
                "use only %d — %d chips per slice will sit idle; size "
                "the ici axes to the slice"
                % (len(g), dict(ici_axes), per_slice, len(g) - per_slice),
                stacklevel=2,
            )
    arr = np.asarray(
        [g[:per_slice] for g in ordered], dtype=object
    ).reshape(dcn_sizes + ici_sizes)
    return Mesh(arr, dcn_names + ici_names)


def data_parallel_axes(mesh: Mesh):
    """(axes, total) of the mesh's data-parallel tiers: every axis named
    'dcn' or 'dcn_*' (slice-crossing, laid outermost by
    make_hybrid_mesh) plus 'data' (within a slice). The executor shards
    batch dims over exactly these axes — the single definition both the
    jit-sharding and multi-process feed paths use."""
    axes = tuple(
        a
        for a in mesh.axis_names
        if a == "data" or a == "dcn" or str(a).startswith("dcn_")
    )
    total = int(np.prod([mesh.shape[a] for a in axes])) if axes else 1
    return axes, total


def set_default_mesh(mesh: Optional[Mesh]):
    global _default_mesh
    _default_mesh = mesh


def get_default_mesh() -> Optional[Mesh]:
    return _default_mesh


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_sharding(mesh: Mesh, ndim: int, axis: str = "data") -> NamedSharding:
    """Shard the leading (batch) dim over the data axis."""
    if axis not in mesh.axis_names:
        return replicated(mesh)
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def shard_parameter(var, spec: PartitionSpec):
    """Annotate a Parameter/Variable with a PartitionSpec (tensor
    parallelism). The executor places the scope array accordingly; XLA
    partitions every op touching it and inserts the collectives.

    Replaces the reference's per-layer `device` placement field
    (ModelConfig.proto:399 / ParallelNeuralNetwork.h) with per-tensor
    sharding — the TPU-idiomatic form of model parallelism.
    """
    program = var.block.program
    program.shardings[var.name] = spec
    return var


def shard_parameters_fsdp(program, mesh: Mesh, axis: str = "data",
                          min_size: int = 1024):
    """ZeRO-3/FSDP-style parameter sharding: every trainable parameter
    (and, through the optimizer-slot inheritance in
    fluid/optimizer.py _add_accumulator, all its optimizer state) is
    sharded over `axis` along its largest divisible dim. XLA SPMD then
    all-gathers weights where the forward needs them and
    reduce-scatters gradients — the memory-per-chip profile of FSDP
    without any new runtime machinery, since the program keeps
    global-batch semantics.

    Parameters smaller than `min_size` elements stay replicated (the
    gather latency would dominate), and parameters that already carry a
    sharding annotation (e.g. tensor-parallel specs) keep it. Call
    BEFORE optimizer.minimize() so the slots inherit the specs.
    Returns the sharded param names.
    """
    n = int(mesh.shape[axis])
    done = []
    for p in program.global_block().all_parameters():
        if not getattr(p, "trainable", True):
            continue
        if p.name in program.shardings:
            continue  # user-placed (TP) specs win
        shape = list(p.shape or [])
        if not shape or int(np.prod(shape)) < min_size:
            continue
        # largest dim divisible by the axis extent
        cand = sorted(
            (d for d in range(len(shape)) if shape[d] % n == 0),
            key=lambda d: -shape[d],
        )
        if not cand:
            continue
        spec = [None] * len(shape)
        spec[cand[0]] = axis
        shard_parameter(p, PartitionSpec(*spec))
        done.append(p.name)
    return done


class DistributedContext(object):
    """Process-level view of the distributed runtime (replaces the
    reference's trainer_id/num_gradient_servers flags, Flags.cpp:60-65,
    and the multi-node bootstrap the reference does via PSERVERS /
    TRAINING_ROLE env + etcd registration, notest_dist_fit_a_line.py:30-45
    and go/pserver/etcd_client.go:70)."""

    _initialized = False

    def __init__(self, mesh: Optional[Mesh] = None):
        self.mesh = mesh or get_default_mesh()

    @classmethod
    def initialize(
        cls,
        coordinator_address: Optional[str] = None,
        num_processes: Optional[int] = None,
        process_id: Optional[int] = None,
        local_device_ids: Optional[Sequence[int]] = None,
    ):
        """Join the multi-controller runtime (DCN): after this,
        jax.devices() spans every process and one global Mesh covers the
        pod — collectives ride ICI within a slice and DCN across.

        Arguments mirror jax.distributed.initialize and fall back to its
        env/cluster autodetection (TPU pods need no arguments at all; the
        CPU test fixture passes explicit localhost coordinates the way the
        reference's tests wired PSERVERS=127.0.0.1 endpoints).
        Idempotent per process.
        """
        if cls._initialized:
            return
        kwargs = {}
        if coordinator_address is not None:
            kwargs["coordinator_address"] = coordinator_address
        if num_processes is not None:
            kwargs["num_processes"] = int(num_processes)
        if process_id is not None:
            kwargs["process_id"] = int(process_id)
        if local_device_ids is not None:
            kwargs["local_device_ids"] = list(local_device_ids)
        jax.distributed.initialize(**kwargs)
        cls._initialized = True

    @classmethod
    def shutdown(cls):
        if cls._initialized:
            jax.distributed.shutdown()
            cls._initialized = False

    @property
    def world_size(self) -> int:
        return jax.device_count()

    @property
    def local_device_count(self) -> int:
        return jax.local_device_count()

    @property
    def process_index(self) -> int:
        return jax.process_index()

    @property
    def process_count(self) -> int:
        return jax.process_count()

    # --- per-process data sharding (replaces per-trainer file lists /
    # master task dispatch for the simple static case) ------------------
    def shard_reader(self, reader, verify_every: Optional[int] = None):
        """Wrap a v2-style reader so each process sees its 1/process_count
        slice of the stream (round-robin by instance). The global batch
        assembled by the executor is identical to single-process order-
        stability aside.

        Round-robin assignment REQUIRES every process to enumerate the
        identical stream (same shuffle seed); silent divergence would feed
        overlapping/duplicated data. `verify_every=K` guards this: after
        every K YIELDED items — the same consumer-visible ordinal on
        every process, so lockstep consumers (the executor's global-batch
        assembly pulls per-process equal counts) hit the collective at
        the same pull — processes all-gather (yield_count, crc-of-
        completed-rounds), and once more at stream end with the full
        (raw_count, crc). Any content or length divergence pairs
        mismatched payloads and raises on every process instead of
        hanging. (A consumer that abandons the generator mid-stream skips
        the end gather — the guard covers stream content/length, not
        consumer aborts.)
        """
        pidx, pcount = self.process_index, self.process_count

        def _check(count, crc):
            from jax.experimental import multihost_utils

            pairs = np.asarray(
                multihost_utils.process_allgather(
                    np.asarray([count, crc], np.uint32)
                )
            ).reshape(-1, 2)
            if len({(int(c), int(f)) for c, f in pairs}) != 1:
                raise RuntimeError(
                    "shard_reader stream divergence: per-process "
                    "(count, fingerprint) pairs %s differ — every "
                    "process must enumerate the identical reader order "
                    "(same shuffle seed, balanced length)" % pairs.tolist()
                )

        def _sharded():
            crc, i, yielded = 0, 0, 0
            # crc over all COMPLETE rounds of pcount raw items: identical
            # on every process at the same yield ordinal, even though
            # their raw positions within the current round differ
            round_crc = 0
            for i, item in enumerate(reader(), start=1):
                if verify_every and pcount > 1:
                    if (i - 1) % pcount == 0:
                        round_crc = crc  # round boundary: all complete
                    crc = _fingerprint(item, crc)
                if (i - 1) % pcount == pidx:
                    yielded += 1
                    yield item
                    if verify_every and pcount > 1 \
                            and yielded % verify_every == 0:
                        _check(yielded, round_crc)
            # end-of-stream gather: full stream totals; a diverging or
            # unbalanced stream pairs this with a peer's interval gather
            # (or an unequal payload) and raises on BOTH sides
            if verify_every and pcount > 1:
                _check(i, crc)

        return _sharded


def _fingerprint(item, crc: int) -> int:
    """Rolling CRC32 of a reader item (arrays / scalars / nested tuples),
    order-sensitive, for shard_reader's divergence guard."""
    if isinstance(item, (tuple, list)):
        for part in item:
            crc = _fingerprint(part, crc)
        return crc
    a = np.asarray(item)
    crc = zlib.crc32(str(a.dtype).encode() + str(a.shape).encode(), crc)
    return zlib.crc32(np.ascontiguousarray(a).tobytes(), crc)


def spans_processes(mesh: Optional[Mesh]) -> bool:
    """True when the mesh includes devices owned by other processes (the
    executor must then assemble global arrays from process-local feeds)."""
    if mesh is None:
        return False
    pidx = jax.process_index()
    return any(d.process_index != pidx for d in mesh.devices.flat)
