"""Pallas TPU flash attention: blockwise online-softmax attention that
never materialises the [T, T] score matrix.

The hot-op kernel story (SURVEY §7.1: "pallas for kernels XLA can't
express"): XLA fuses elementwise chains into matmuls but still allocates
the full attention score matrix; flash attention tiles Q into VMEM-sized
blocks and streams K/V blocks through the MXU with a running
(max, sum, accumulator) — O(T) memory instead of O(T^2), the same
algorithm the ring-attention path uses ACROSS chips
(parallel/attention.py), here applied WITHIN a chip.

Forward is a single `pl.pallas_call` over a (batch*heads, q_blocks,
k_blocks) grid with the k axis innermost (grid-reduction pattern:
initialise at k==0, accumulate, finalise at the last k step). Backward
(jax.custom_vjp) is a blockwise recompute: a lax.scan over q blocks
rebuilds one [block_q, S] score tile per step — the flash-style
"recompute instead of store" trade with transient memory O(block_q*S),
never the full [T, S] residual.

`interpret=True` runs the kernel on CPU for CI (tests/conftest runs on
a CPU mesh); on TPU the same kernel compiles to Mosaic.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention"]

_NEG_INF = -1e30


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
               scale: float, causal: bool, block_q: int, block_k: int):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # causal: a k block strictly above this q block's last row is fully
    # masked — skip its matmuls entirely (half the grid for long T)
    needed = (
        kj * block_k <= qi * block_q + block_q - 1 if causal else True
    )

    @pl.when(needed)
    def _accumulate():
        q = q_ref[0]  # [block_q, D], input dtype (bf16 stays on the MXU
        k = k_ref[0]  # bf16 path; accumulation is f32 via
        v = v_ref[0]  # preferred_element_type)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_k]
        if causal:
            q_idx = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_idx = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_idx >= k_idx, s, _NEG_INF)

        m_prev = m_ref[...]  # [block_q, 1]
        l_prev = l_ref[...]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # guard fully-masked rows (causal upper blocks): exp(-inf - -inf)
        p = jnp.exp(s - m_new)  # [block_q, block_k]
        p = jnp.where(s <= _NEG_INF, 0.0, p)
        alpha = jnp.exp(m_prev - m_new)
        alpha = jnp.where(m_prev <= _NEG_INF, 0.0, alpha)

        l_ref[...] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = m_new

    @pl.when(kj == nk - 1)
    def _finalise():
        denom = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / denom).astype(o_ref.dtype)


def _fa_forward(q, k, v, scale: float, causal: bool, block_q: int,
                block_k: int, interpret: bool):
    BH, T, D = q.shape
    S = k.shape[1]
    nq = pl.cdiv(T, block_q)
    nk = pl.cdiv(S, block_k)
    kernel = functools.partial(
        _fa_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k,
    )
    return pl.pallas_call(
        kernel,
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


def _reference(q, k, v, scale, causal):
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        T, S = s.shape[1], s.shape[2]
        mask = jnp.tril(jnp.ones((T, S), bool), k=S - T)
        s = jnp.where(mask[None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(
        q.dtype
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, block_q, block_k, interpret):
    return _fa_forward(q, k, v, scale, causal, block_q, block_k,
                       interpret)


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    out = _fa_forward(q, k, v, scale, causal, block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_bwd(scale, causal, block_q, block_k, interpret, res, g):
    """Blockwise recompute backward: scan over q blocks, each step
    rebuilding only its [block_q, S] score tile — transient memory
    O(block_q * S), never the full [T, S] matrix (the flash trade)."""
    q, k, v = res
    BH, T, D = q.shape
    nq = T // block_q

    def one_block(carry, i):
        dk_acc, dv_acc = carry
        qb = jax.lax.dynamic_slice_in_dim(q, i * block_q, block_q, axis=1)
        gb = jax.lax.dynamic_slice_in_dim(g, i * block_q, block_q, axis=1)

        def blk(qb, k, v):
            s = jnp.einsum(
                "bqd,bkd->bqk", qb.astype(jnp.float32),
                k.astype(jnp.float32)
            ) * scale
            if causal:
                q_idx = i * block_q + jnp.arange(block_q)
                k_idx = jnp.arange(k.shape[1])
                s = jnp.where(
                    (q_idx[:, None] >= k_idx[None, :])[None], s, _NEG_INF
                )
            p = jax.nn.softmax(s, axis=-1)
            return jnp.einsum("bqk,bkd->bqd", p,
                              v.astype(jnp.float32)).astype(qb.dtype)

        _, vjp = jax.vjp(blk, qb, k, v)
        dqb, dkb, dvb = vjp(gb)
        return (dk_acc + dkb, dv_acc + dvb), dqb

    (dk, dv), dq_blocks = jax.lax.scan(
        one_block,
        (jnp.zeros_like(k), jnp.zeros_like(v)),
        jnp.arange(nq),
    )
    # dq_blocks: [nq, BH, block_q, D] -> [BH, T, D]
    dq = jnp.moveaxis(dq_blocks, 0, 1).reshape(BH, T, D)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None, block_q: int = 1024,
                    block_k: int = 1024, interpret: bool = False):
    """Blockwise attention for [B, T, H, D] tensors (same layout as
    parallel/attention.py). Block sizes clamp to the sequence lengths
    and halve until they divide them. Defaults from the r5 on-chip sweep
    (T=4096 bf16, scan-differenced, compiled Mosaic): 1024x1024 runs
    2.57x FASTER than XLA's full-matrix attention (39.5 TFLOP/s fwd);
    r3's 512x1024 measured 2.24x, 512x512 1.68x, 1024x512 1.60x;
    2048-wide q or k blocks exceed the 16 MB scoped-VMEM budget and
    fail to compile; the old 128x128 was 3x slower (65k-step grid of
    tiny matmuls starves the MXU)."""
    B, T, H, D = q.shape
    S = k.shape[1]
    if scale is None:
        scale = D ** -0.5
    block_q = min(block_q, T)
    block_k = min(block_k, S)
    while block_q > 1 and T % block_q:
        block_q //= 2
    while block_k > 1 and S % block_k:
        block_k //= 2
    if block_q < 8 or block_k < 8:
        # odd lengths would degrade to a per-row grid (T^2 steps of 1-row
        # matmuls) — refuse instead; pad the sequence to a multiple of 8
        raise ValueError(
            "sequence lengths (%d, %d) have no usable block split (need "
            "a multiple of 8); pad the sequence" % (T, S)
        )
    if causal and T != S:
        raise ValueError(
            "causal flash attention requires matching q/k lengths "
            "(got %d vs %d)" % (T, S)
        )

    def bh(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, x.shape[1], D)

    out = _flash(bh(q), bh(k), bh(v), float(scale), bool(causal),
                 int(block_q), int(block_k), bool(interpret))
    return out.reshape(B, H, T, D).transpose(0, 2, 1, 3)
