"""Pallas TPU flash attention: blockwise online-softmax attention that
never materialises the [T, T] score matrix.

The hot-op kernel story (SURVEY §7.1: "pallas for kernels XLA can't
express"): XLA fuses elementwise chains into matmuls but still allocates
the full attention score matrix; flash attention tiles Q into VMEM-sized
blocks and streams K/V blocks through the MXU with a running
(max, sum, accumulator) — O(T) memory instead of O(T^2), the same
algorithm the ring-attention path uses ACROSS chips
(parallel/attention.py), here applied WITHIN a chip.

Forward is a single `pl.pallas_call` over a (batch*heads, q_blocks,
k_blocks) grid with the k axis innermost (grid-reduction pattern:
initialise at k==0, accumulate, finalise at the last k step), emitting
the per-row log-sum-exp as a residual. Backward (jax.custom_vjp) is
two pallas passes that rebuild each probability tile from the lse —
dk/dv over a (bh, k_blocks, q_blocks) grid, dq over the forward's grid
— so every matmul stays a VMEM-tiled MXU op and memory stays O(T)
(r5; the previous XLA blockwise-recompute scan materialised
[block_q, S] f32 score tiles in HBM).

`interpret=True` runs the kernel on CPU for CI (tests/conftest runs on
a CPU mesh); on TPU the same kernel compiles to Mosaic.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .kernel_utils import NEG_INF, causal_fill, resolve_interpret

__all__ = ["flash_attention", "resolve_interpret"]

# back-compat alias: the mask fill + interpret resolution now live in
# kernel_utils.py, shared with the paged-attention kernels (ISSUE 13)
_NEG_INF = NEG_INF

# backward tile cap: the bwd kernels hold ~3 extra [block_q, block_k]
# f32 intermediates vs the forward, so 1024-wide blocks that fit the
# forward would exceed the 16 MB scoped-VMEM budget here
_BWD_BLOCK_CAP = 512


def _block_needed(qi, kj, block_q, block_k, causal):
    """Whole-block causal skip: a k block strictly above this q block's
    last row is fully masked — skip its matmuls entirely."""
    return kj * block_k <= qi * block_q + block_q - 1 if causal else True


# the shared causal tile mask (kernel_utils.causal_fill) under its
# historical module-local name — forward and backward both use it
_causal_fill = causal_fill


def _bwd_block(block, length):
    """Backward tile size: cap at _BWD_BLOCK_CAP, halve until it
    divides — but never below the 8-row minimum the forward refuses;
    awkward lengths (e.g. prime T<=1024 that the forward runs as one
    whole-sequence block) fall back to a whole-length block instead of
    degrading to a per-row grid."""
    b = min(block, _BWD_BLOCK_CAP)
    while b > 1 and length % b:
        b //= 2
    return b if b >= 8 else length


def _out_struct(shape, dtype, like):
    """ShapeDtypeStruct for a pallas output, propagating the input's
    varying-mesh-axes type (vma) so the kernel is callable inside
    shard_map (ulysses runs it per shard) under JAX's check_vma."""
    try:
        vma = jax.typeof(like).vma
    except Exception:
        vma = None
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref,
               l_ref, *, scale: float, causal: bool, block_q: int,
               block_k: int):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(_block_needed(qi, kj, block_q, block_k, causal))
    def _accumulate():
        q = q_ref[0]  # [block_q, D], input dtype (bf16 stays on the MXU
        k = k_ref[0]  # bf16 path; accumulation is f32 via
        v = v_ref[0]  # preferred_element_type)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_k]
        if causal:
            s = _causal_fill(s, qi, kj, block_q, block_k)

        m_prev = m_ref[...]  # [block_q, 1]
        l_prev = l_ref[...]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # guard fully-masked rows (causal upper blocks): exp(-inf - -inf)
        p = jnp.exp(s - m_new)  # [block_q, block_k]
        p = jnp.where(s <= _NEG_INF, 0.0, p)
        alpha = jnp.exp(m_prev - m_new)
        alpha = jnp.where(m_prev <= _NEG_INF, 0.0, alpha)

        l_ref[...] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = m_new

    @pl.when(kj == nk - 1)
    def _finalise():
        denom = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / denom).astype(o_ref.dtype)
        # per-row log-sum-exp residual for the pallas backward:
        # p = exp(s - lse) reconstructs the normalised softmax directly
        lse_ref[0] = m_ref[...] + jnp.log(denom)


def _fa_forward(q, k, v, scale: float, causal: bool, block_q: int,
                block_k: int, interpret: bool):
    BH, T, D = q.shape
    S = k.shape[1]
    nq = pl.cdiv(T, block_q)
    nk = pl.cdiv(S, block_k)
    kernel = functools.partial(
        _fa_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k,
    )
    return pl.pallas_call(
        kernel,
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            _out_struct((BH, T, D), q.dtype, q),
            _out_struct((BH, T, 1), jnp.float32, q),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


def _reference(q, k, v, scale, causal):
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        T, S = s.shape[1], s.shape[2]
        mask = jnp.tril(jnp.ones((T, S), bool), k=S - T)
        s = jnp.where(mask[None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(
        q.dtype
    )


def _bwd_scores(q, k, lse, qi, kj, *, scale, causal, block_q, block_k):
    """Rebuild one normalised probability tile p = exp(s*scale - lse)
    inside a backward kernel. Masked taps reconstruct to exact 0 via
    exp(-inf); no separate mask needed beyond the causal score fill."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    if causal:
        s = _causal_fill(s, qi, kj, block_q, block_k)
    return jnp.exp(s - lse)


def _fa_bwd_kv_kernel(q_ref, g_ref, k_ref, v_ref, lse_ref, delta_ref,
                      dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                      block_q, block_k):
    """dk/dv pass: grid (BH, k_blocks, q_blocks), q innermost — each k
    block accumulates over the q blocks that attend to it."""
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    @pl.when(_block_needed(qi, kj, block_q, block_k, causal))
    def _accumulate():
        q = q_ref[0]
        g = g_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        p = _bwd_scores(q, k, lse_ref[0], qi, kj, scale=scale,
                        causal=causal, block_q=block_q, block_k=block_k)
        # dv += p^T g   (contract the q rows)
        dv_acc[...] += jax.lax.dot_general(
            p.astype(g.dtype), g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # ds = p * (g v^T - delta) * scale
        dp = jax.lax.dot_general(
            g, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0]) * scale
        # dk += ds^T q
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == nq - 1)
    def _finalise():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _fa_bwd_q_kernel(q_ref, g_ref, k_ref, v_ref, lse_ref, delta_ref,
                     dq_ref, dq_acc, *, scale, causal, block_q,
                     block_k):
    """dq pass: grid (BH, q_blocks, k_blocks), k innermost — mirrors the
    forward's grid-reduction shape."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    @pl.when(_block_needed(qi, kj, block_q, block_k, causal))
    def _accumulate():
        q = q_ref[0]
        g = g_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        p = _bwd_scores(q, k, lse_ref[0], qi, kj, scale=scale,
                        causal=causal, block_q=block_q, block_k=block_k)
        dp = jax.lax.dot_general(
            g, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0]) * scale
        # dq += ds k
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(kj == nk - 1)
    def _finalise():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, block_q, block_k, interpret):
    out, _ = _fa_forward(q, k, v, scale, causal, block_q, block_k,
                         interpret)
    return out


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    out, lse = _fa_forward(q, k, v, scale, causal, block_q, block_k,
                           interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, causal, block_q, block_k, interpret, res, g):
    """Pallas flash backward (r5; previously an XLA blockwise-recompute
    scan that materialised [block_q, S] f32 score tiles in HBM): two
    tiled passes that rebuild each probability block from the saved
    log-sum-exp — dk/dv with q innermost, dq with k innermost. Memory
    stays O(T), all matmuls hit the MXU with f32 accumulation."""
    q, k, v, out, lse = res
    BH, T, D = q.shape
    S = k.shape[1]
    bq = _bwd_block(block_q, T)
    bk = _bwd_block(block_k, S)
    nq = T // bq
    nk = S // bk
    # delta_i = rowsum(g * o): the p·dp row-dot every ds tile needs
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1,
        keepdims=True,
    )
    lse = lse.reshape(BH, T, 1)

    q_spec = pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0))
    kv_spec = pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0))
    row_spec = pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_fa_bwd_kv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk),
        grid=(BH, nk, nq),
        in_specs=[q_spec, q_spec, kv_spec, kv_spec, row_spec, row_spec],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            _out_struct((BH, S, D), k.dtype, k),
            _out_struct((BH, S, D), v.dtype, k),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, g, k, v, lse, delta)

    q_spec2 = pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0))
    kv_spec2 = pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0))
    row_spec2 = pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0))
    dq = pl.pallas_call(
        functools.partial(_fa_bwd_q_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk),
        grid=(BH, nq, nk),
        in_specs=[q_spec2, q_spec2, kv_spec2, kv_spec2, row_spec2,
                  row_spec2],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=_out_struct((BH, T, D), q.dtype, q),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(q, g, k, v, lse, delta)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None, block_q: int = 1024,
                    block_k: int = 1024, interpret: bool = False):
    """Blockwise attention for [B, T, H, D] tensors (same layout as
    parallel/attention.py). Block sizes clamp to the sequence lengths
    and halve until they divide them. Defaults from the r5 on-chip sweep
    (T=4096 bf16, scan-differenced, compiled Mosaic): 1024x1024 runs
    2.57x FASTER than XLA's full-matrix attention (39.5 TFLOP/s fwd);
    r3's 512x1024 measured 2.24x, 512x512 1.68x, 1024x512 1.60x;
    2048-wide q or k blocks exceed the 16 MB scoped-VMEM budget and
    fail to compile; the old 128x128 was 3x slower (65k-step grid of
    tiny matmuls starves the MXU)."""
    B, T, H, D = q.shape
    S = k.shape[1]
    if scale is None:
        scale = D ** -0.5
    block_q = min(block_q, T)
    block_k = min(block_k, S)
    while block_q > 1 and T % block_q:
        block_q //= 2
    while block_k > 1 and S % block_k:
        block_k //= 2
    if block_q < 8 or block_k < 8:
        # odd lengths would degrade to a per-row grid (T^2 steps of 1-row
        # matmuls) — refuse instead; pad the sequence to a multiple of 8
        raise ValueError(
            "sequence lengths (%d, %d) have no usable block split (need "
            "a multiple of 8); pad the sequence" % (T, S)
        )
    if causal and T != S:
        raise ValueError(
            "causal flash attention requires matching q/k lengths "
            "(got %d vs %d)" % (T, S)
        )

    def bh(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, x.shape[1], D)

    out = _flash(bh(q), bh(k), bh(v), float(scale), bool(causal),
                 int(block_q), int(block_k), bool(interpret))
    return out.reshape(B, H, T, D).transpose(0, 2, 1, 3)
