"""Pallas TPU paged attention: attend THROUGH the block table, with the
gather happening inside the kernel (ISSUE 13).

The paged serving primitives (models/transformer.py: paged_decode_step,
paged_verify_step, paged_prefill_chunk) attend over a block-pool KV
cache [NB, Bt, H, Dh] indirected by per-slot block tables [S, MAXB]
(PagedAttention, Kwon et al., SOSP '23). Their XLA form materialises a
transient contiguous per-slot view [S, MAXB*Bt, H, Dh] PER LAYER
(`_paged_view` — PERF.md's "known trade until a fused paged kernel
lands"): HBM write + read of the whole gathered context every step,
which is exactly the traffic a decode step is bounded by. These kernels
delete that view: a (slots, table-groups) grid walks each slot's block
table with the table and positions as SCALAR-PREFETCH operands
(PrefetchScalarGridSpec), so the pipeline DMAs each group's G K/V
blocks [Bt, H, Dh] straight from the pool buffer into VMEM (G blocks
per step so the per-head score tile spans G*Bt >= 128 lanes — the
reference pages_per_compute_block idea) — the "gather" is the index
map, and no HBM-resident contiguous view ever exists. Blockwise
online softmax (running (max, sum, acc), the flash_attention.py
discipline) keeps VMEM at one group of blocks plus per-head [R, Dh]
accumulators, regardless of context length.

Masking mirrors the gather primitives exactly: row r of a window based
at `base` attends positions <= base + r, so unwritten depths — and the
garbage rows a `-1` (unallocated) table entry surfaces after its clamp
to block 0 — are excluded by position and contribute EXACTLY 0. A
fully-masked block is an exact no-op on the (m, l, acc) state (the
NEG_INF guards, kernel_utils.py), so a slot whose table tail is -1
produces bit-identical output to the same slot over a fully-allocated
table (the tier-1 garbage-row invariant, tests/test_paged_kernel.py).

Two numerics families, matching the callers they replace (the same
low-bit split models/transformer.py documents):

  * decode  — `_cached_attention`'s divide-after-matmul scaling
    (scores / sqrt(Dh)); softmax accumulation in f32.
  * chunk   — `reference_attention`'s scale-into-q (q * scale BEFORE
    the matmul), the verify/prefill family.

Online softmax reorders the reduction vs the one-shot softmax the XLA
path runs, so fused-vs-gather logits agree to float tolerance, not bit
— the tested bar (atol-pinned logits + greedy token identity through
the engine), the same class as the padded-prefill drift documented
since PR 2.

Quantized pools (ISSUE 14): with `k_scale`/`v_scale` [NB, H] the
pools hold int8/fp8 codes and the kernels dequantize IN VMEM — the
scales ride as scalar-prefetch operands (SMEM, like the tables), the
DMA stays in the storage dtype, and each per-head f32 slice
multiplies by its block's scalar scale before the matmuls. The same
no-HBM-view discipline, applied to the dequantized values: they
never exist outside VMEM.

`interpret=None` resolves via kernel_utils.resolve_interpret: CPU CI
runs the identical kernel interpreted; on TPU it compiles to Mosaic.

Alignment: the pool's block rows are the sublane dim — keep
`kv_block_tokens` a multiple of 8 (f32; 16 for bf16; 32 for int8/fp8
storage) — and Dh is the lane dim (128-aligned Dh runs the MXU
full-width; smaller Dh works, padded).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .kernel_utils import NEG_INF, resolve_interpret

__all__ = ["paged_decode_attention", "paged_verify_attention",
           "paged_prefill_attention"]


def _pa_kernel(*args, Bt: int, R: int, G: int, scale: float,
               scale_in_q: bool, quant: bool):
    """One (slot, table-GROUP) grid step: stream the G consecutive
    blocks the slot's table names at this depth range, fold them into
    the running online-softmax state for all R window rows of every
    head.

    Grid (S, ceil(MAXB/G)), groups innermost — the flash
    grid-reduction pattern: init at b == 0, accumulate per group,
    finalise at the last group. `tbl_ref` [S, MAXB] / `base_ref` [S]
    are scalar-prefetch refs; the g-th K/V BlockSpec index map already
    used tbl_ref to pick physical block tbl[s, b*G + g] (clamped to 0
    when unallocated — masked below, exact no-op), so the per-head
    score tile is [R, G*Bt].

    Mosaic constraints shape the body, each probed by AOT-compiling
    for a virtual v5e (the bench_offline pattern): its dot takes 2D
    operands only (no batch dims), so heads run as a static in-kernel
    loop; 16-bit mid-dim VMEM extracts don't lower, so blocks upcast
    to f32 once and every head slices f32 (the f32 MXU path halves
    peak matmul rate vs bf16, which these HBM-bandwidth-bound steps
    never see — the DMA stays in the pool dtype); per-head softmax
    state must be WHOLE refs, never slices of a shared scratch (see
    the comment below); and G groups blocks until G*Bt >= 128 so the
    score tile spans full 128-lane tiles (the reference
    pages_per_compute_block idea, jax paged_attention_kernel — also
    fewer, larger grid steps for the DMA pipeline to overlap).

    With `quant` (ISSUE 14) the pools hold int8/fp8 codes and two more
    SCALAR-PREFETCH operands carry the per-(physical block, head)
    absmax scales [NB, H] f32: after each group's blocks upcast to f32
    in VMEM (the same one-upcast-then-slice-f32 discipline the 16-bit
    path needs anyway), every per-head 2D slice multiplies by its
    block's scalar scale read from SMEM — dequantization happens
    entirely in VMEM/SMEM, the DMA stays in the storage dtype, and no
    HBM-materialised dequantized view ever exists (the discipline that
    killed the gather tax, applied to the quant read path)."""
    if quant:
        tbl_ref, base_ref, ksc_ref, vsc_ref, q_ref = args[:5]
        refs = args[5:]
    else:
        tbl_ref, base_ref, q_ref = args[:3]
        refs = args[3:]
    k_refs = refs[:G]
    v_refs = refs[G:2 * G]
    o_ref = refs[2 * G]
    H = o_ref.shape[1]  # the output block is HEAD-major (1, H, R, Dh)
    # per-head state lives in H SEPARATE whole refs, accessed full-ref
    # only: mid-dim slice reads/writes of a shared scratch poison
    # Mosaic's layout inference (the lane-1 m/l slices gave the score
    # tile a lane-replicated layout whose reduction does not lower,
    # and the sliced acc store needs the same unimplemented relayout);
    # whole-ref per-head state is the shipped paged_attention_kernel's
    # own shape discipline
    acc_refs = refs[2 * G + 1:2 * G + 1 + H]
    m_refs = refs[2 * G + 1 + H:2 * G + 1 + 2 * H]
    l_refs = refs[2 * G + 1 + 2 * H:]
    si = pl.program_id(0)
    b = pl.program_id(1)
    nb = pl.num_programs(1)
    W = G * Bt  # tokens per grid step

    @pl.when(b == 0)
    def _init():
        for ar, mr, lr in zip(acc_refs, m_refs, l_refs):
            ar[...] = jnp.zeros_like(ar)
            mr[...] = jnp.full_like(mr, NEG_INF)
            lr[...] = jnp.zeros_like(lr)

    base = base_ref[si]
    # whole-group skip: every row of this window sits at or below
    # base + R - 1, so a group starting past that depth is fully
    # masked — skip its matmuls entirely (masked groups are exact
    # no-ops on the state either way; this is pure speed)
    @pl.when(b * W <= base + R - 1)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32)  # [R, H, Dh]
        ks = [r[0].astype(jnp.float32) for r in k_refs]  # G x [Bt, H, Dh]
        vs = [r[0].astype(jnp.float32) for r in v_refs]
        if quant:
            # the physical block each group entry streamed (the same
            # expression its index map used; -1 clamps to 0 — its
            # scale is garbage-but-finite, position-masked below)
            pbs = [jnp.maximum(tbl_ref[si, b * G + g], 0)
                   for g in range(G)]
        if scale_in_q:  # chunk family: scale folded into q pre-matmul
            q = q * scale
        # position mask: row r (global position base + r) attends
        # depths <= base + r; everything deeper — including the
        # garbage a clamped -1 (or tail-padded) entry streams —
        # contributes exactly 0
        depth = b * W + jax.lax.broadcasted_iota(jnp.int32, (R, W), 1)
        rowpos = base + jax.lax.broadcasted_iota(jnp.int32, (R, W), 0)
        masked = depth > rowpos  # [R, W]
        for hh in range(H):
            if quant:
                # dequant per (group entry, head): 2D f32 slice times
                # one scalar SMEM scale — layout-safe (no mid-dim
                # vector ops on the quantized block)
                k = jnp.concatenate(
                    [ks[g][:, hh, :] * ksc_ref[pbs[g], hh]
                     for g in range(G)], axis=0)
                v = jnp.concatenate(
                    [vs[g][:, hh, :] * vsc_ref[pbs[g], hh]
                     for g in range(G)], axis=0)
            else:
                k = jnp.concatenate([kk[:, hh, :] for kk in ks], axis=0)
                v = jnp.concatenate([vv[:, hh, :] for vv in vs], axis=0)
            s = jax.lax.dot_general(
                q[:, hh, :], k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [R, W]
            if not scale_in_q:  # decode family: scale after the matmul
                s = s * scale
            s = jnp.where(masked, NEG_INF, s)

            m_prev = m_refs[hh][...]  # [R, 1]
            l_prev = l_refs[hh][...]
            m_cur = jax.lax.broadcast_in_dim(
                jnp.max(s, axis=1), (R, 1), (0,))
            m_new = jnp.maximum(m_prev, m_cur)
            # fully-masked guards (kernel_utils.NEG_INF contract): a
            # group with no attended depth leaves (m, l, acc) exactly
            # unchanged
            p = jnp.exp(s - m_new)
            p = jnp.where(s <= NEG_INF, 0.0, p)
            alpha = jnp.exp(m_prev - m_new)
            alpha = jnp.where(m_prev <= NEG_INF, 0.0, alpha)

            l_refs[hh][...] = l_prev * alpha + jax.lax.broadcast_in_dim(
                jnp.sum(p, axis=1), (R, 1), (0,))
            pv = jax.lax.dot_general(
                p, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [R, Dh]
            acc_refs[hh][...] = acc_refs[hh][...] * alpha + pv
            m_refs[hh][...] = m_new

    @pl.when(b == nb - 1)
    def _finalise():
        # the output block is head-major so each head's write indexes
        # LEADING dims only (a mid-dim 16-bit store would not lower);
        # the builder transposes back outside the kernel
        for hh in range(H):
            denom = jnp.maximum(l_refs[hh][...], 1e-30)  # [R, 1]
            o_ref[0, hh] = (acc_refs[hh][...] / denom).astype(
                o_ref.dtype)


def _paged_attention(q, k_pool, v_pool, tables, base, *, scale,
                     scale_in_q, interpret, k_scale=None, v_scale=None):
    """Shared pallas_call builder: q [S, R, H, Dh] windows based at
    `base` [S] over per-slot tables [S, MAXB] into the pools
    [NB, Bt, H, Dh] -> out [S, R, H, Dh].

    `k_scale`/`v_scale` [NB, H] f32 (both or neither) mark a quantized
    pool (ISSUE 14): they ride as two more scalar-prefetch operands —
    SMEM-resident like the tables, read per (block, head) scalar in
    the kernel body — and the blocks dequantize in VMEM after the DMA.
    SMEM cost is 2 x NB x H x 4 bytes; at pool sizes where that
    presses the scalar-memory budget, shrink NB (more, smaller
    engines) before reaching for a VMEM-block scale plumbing.

    The window-row dim R is the kernel's sublane dim: Mosaic wants it
    in whole 8-row tiles (the flash kernel refuses blocks under 8 for
    the same reason), so 1 < R < multiple-of-8 windows pad with zero
    rows up to the tile and slice the result. Pad rows compute masked
    garbage nothing reads; every real row's online-softmax state is
    row-independent, so real rows are BIT-identical to the unpadded
    math. R == 1 (the decode shape) lowers fine as-is and stays
    unpadded."""
    S, R, H, dh = q.shape
    NB, Bt = k_pool.shape[0], k_pool.shape[1]
    maxb = tables.shape[1]
    tables = jnp.asarray(tables, jnp.int32)
    base = jnp.asarray(base, jnp.int32)
    quant = k_scale is not None
    if quant != (v_scale is not None):
        raise ValueError("pass both k_scale and v_scale, or neither")
    Rp = R if R == 1 else -(-R // 8) * 8
    if Rp != R:
        q = jnp.concatenate(
            [q, jnp.zeros((S, Rp - R, H, dh), q.dtype)], axis=1)
    # group size: enough table entries per grid step for the per-head
    # score tile [Rp, G*Bt] to fill the 128-lane dim (capped at the
    # whole table for tiny configs — the score tile then equals the
    # array dim, which Mosaic also accepts); the table pads to a whole
    # number of groups with -1 (unallocated) entries — clamped and
    # position-masked like any other -1, i.e. exact no-ops
    G = max(1, min(-(-128 // Bt), maxb))
    pad = -maxb % G
    if pad:
        tables = jnp.concatenate(
            [tables, jnp.full((S, pad), -1, jnp.int32)], axis=1)

    # index maps take the scalar-prefetch refs after the grid indices:
    # (tbl, pos) unquantized, (tbl, pos, ksc, vsc) quantized — only
    # tbl is consulted, so the maps accept either arity
    def _q_map(si, b, tbl, *pref):
        return (si, 0, 0, 0)

    def _kv_map(g):
        def _map(si, b, tbl, *pref):
            # THE gather: the pipeline DMAs pool block tbl[s, b*G+g]
            # for this grid step. -1 (unallocated or group padding)
            # clamps to block 0 — its rows are excluded by the
            # position mask, so they contribute exactly 0
            return (jnp.maximum(tbl[si, b * G + g], 0), 0, 0, 0)
        return _map

    kernel = functools.partial(
        _pa_kernel, Bt=Bt, R=Rp, G=G, scale=scale,
        scale_in_q=scale_in_q, quant=quant,
    )
    prefetch = (tables, base)
    if quant:
        prefetch = prefetch + (jnp.asarray(k_scale, jnp.float32),
                               jnp.asarray(v_scale, jnp.float32))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(prefetch),
        grid=(S, (maxb + pad) // G),
        in_specs=[pl.BlockSpec((1, Rp, H, dh), _q_map)]
        + [pl.BlockSpec((1, Bt, H, dh), _kv_map(g)) for g in range(G)]
        + [pl.BlockSpec((1, Bt, H, dh), _kv_map(g)) for g in range(G)],
        out_specs=pl.BlockSpec((1, H, Rp, dh), _q_map),
        scratch_shapes=[pltpu.VMEM((Rp, dh), jnp.float32)
                        for _ in range(H)]
        + [pltpu.VMEM((Rp, 1), jnp.float32) for _ in range(2 * H)],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, H, Rp, dh), q.dtype),
        interpret=resolve_interpret(interpret),
    )(*prefetch, q, *([k_pool] * G), *([v_pool] * G))
    # the kernel emits head-major [S, H, Rp, Dh] (leading-dim writes
    # only); this transpose is ordinary XLA on the activation-sized
    # output, not a pool-sized materialisation
    out = out.transpose(0, 2, 1, 3)
    return out[:, :R] if Rp != R else out


def paged_decode_attention(q, k_pool, v_pool, tables, pos,
                           interpret=None, k_scale=None, v_scale=None):
    """Batched single-token paged decode attention: one query per slot.

    q [S, H, Dh] at per-slot positions `pos` [S] over block tables
    [S, MAXB] into pools [NB, Bt, H, Dh] -> out [S, H, Dh]. Mirrors
    `_cached_attention` over `_paged_view` (divide-after-matmul
    scaling, depths > pos excluded) without ever materialising the
    view. A parked row (pos >= MAXB*Bt) attends everything its table
    clamps to — garbage out, exactly like the gather path, and nothing
    reads it. `k_scale`/`v_scale` [NB, H] dequantize an int8/fp8 pool
    inside the kernel (ISSUE 14)."""
    S, H, dh = q.shape
    out = _paged_attention(
        q[:, None], k_pool, v_pool, tables, pos,
        scale=1.0 / math.sqrt(dh), scale_in_q=False,
        interpret=interpret, k_scale=k_scale, v_scale=v_scale,
    )
    return out[:, 0]


def paged_verify_attention(q, k_pool, v_pool, tables, pos,
                           interpret=None, k_scale=None, v_scale=None):
    """K-row paged verify windows (the spec-decode path): q [S, K, H,
    Dh], row (s, i) at global position pos[s] + i, attending the slot's
    cache up to and including itself — the intra-window causal prefix
    falls out of the position mask, exactly like `paged_verify_step`'s
    gather form. Chunk-family numerics (scale-into-q); scales
    dequantize a quantized pool in-kernel (ISSUE 14)."""
    dh = q.shape[-1]
    return _paged_attention(
        q, k_pool, v_pool, tables, pos,
        scale=1.0 / math.sqrt(dh), scale_in_q=True,
        interpret=interpret, k_scale=k_scale, v_scale=v_scale,
    )


def paged_prefill_attention(q, k_pool, v_pool, table_row, start,
                            interpret=None, k_scale=None, v_scale=None):
    """Chunked paged prefill attention for ONE slot: a [C]-token chunk
    q [C, H, Dh] whose first row sits at global position `start`,
    attending cache[0:start] plus the intra-chunk causal prefix through
    `table_row` [MAXB]. Chunk-family numerics (scale-into-q), padded
    rows past true_len compute garbage nothing reads — identical
    semantics to `paged_prefill_chunk`'s gather form. The whole chunk
    stays resident in VMEM (C <= max_len; at serving shapes a chunk is
    `prefill_chunk_tokens`, well under the VMEM budget). Scales
    dequantize a quantized pool in-kernel (ISSUE 14)."""
    C, H, dh = q.shape
    out = _paged_attention(
        q[None], k_pool, v_pool, jnp.asarray(table_row)[None],
        jnp.asarray(start, jnp.int32).reshape(1),
        scale=1.0 / math.sqrt(dh), scale_in_q=True,
        interpret=interpret, k_scale=k_scale, v_scale=v_scale,
    )
    return out[0]
