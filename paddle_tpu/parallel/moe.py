"""Expert parallelism: Switch-style mixture-of-experts FFN with
all-to-all token dispatch over an 'expert' mesh axis.

A NEW capability beyond the 2018 reference (SURVEY.md §2.2 lists EP as
absent), first-class here because expert sharding shapes the collective
layout the same way data/tensor/sequence sharding do: experts live one
(or more) per device on the 'expert' axis, tokens are sharded over the
same axis, and two `lax.all_to_all` hops (dispatch + return) ride ICI.

Design (Switch Transformer routing, top-1):
  * gate: logits = x @ gate_w, expert = argmax, prob = softmax max —
    the token's output is scaled by its gate probability so the router
    receives gradient.
  * dispatch: each shard builds an [E, C, D] buffer (C = per-shard
    per-expert capacity); position-in-expert beyond C drops the token
    (standard capacity truncation — dropped tokens pass through with
    zero expert output).
  * all_to_all swaps the E axis for the shard axis: each device then
    holds every shard's buffer for ITS expert(s), runs the expert FFN
    on one dense [n*C, D] block (MXU-friendly), and the reverse
    all_to_all returns results to the token owners.

Everything runs inside `shard_map`; the routing one-hots are plain
matmuls/segment ops so the whole layer is differentiable (routing
indices are argmax — non-differentiable by construction, as in the
reference Switch formulation; the gate gets gradient through the
probability scaling).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

__all__ = ["expert_parallel_moe", "reference_moe", "moe_capacity"]


def moe_capacity(n_tokens_per_shard: int, n_experts: int,
                 capacity_factor: float = 1.25) -> int:
    """Per-shard per-expert slot count (Switch capacity rule)."""
    return max(1, int(math.ceil(
        n_tokens_per_shard / n_experts * capacity_factor)))


def _expert_ffn(x, w1, b1, w2, b2):
    return jnp.maximum(x @ w1 + b1, 0.0) @ w2 + b2


def reference_moe(x, gate_w, w1, b1, w2, b2):
    """Single-device oracle: every token goes to its argmax expert (no
    all-to-all, no capacity truncation), output scaled by the gate
    probability. With ample capacity the sharded path reproduces this
    exactly; under truncation only the sharded path drops tokens.

    x: [N, D]; gate_w: [D, E]; w1: [E, D, H]; b1: [E, H];
    w2: [E, H, D]; b2: [E, D].
    """
    logits = x @ gate_w  # [N, E]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(logits, axis=-1)  # [N]
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]
    outs = jax.vmap(_expert_ffn, in_axes=(None, 0, 0, 0, 0))(
        x, w1, b1, w2, b2
    )  # [E, N, D]
    picked = jnp.take_along_axis(
        outs, expert[None, :, None], axis=0
    )[0]  # [N, D]
    return picked * gate[:, None]


def _moe_shard(x, gate_w, w1, b1, w2, b2, axis_name: str, capacity: int):
    """Per-shard body under shard_map: x [n_local, D]; this device owns
    experts [e0, e0+e_local) where e_local = E // n_shards."""
    n_shards = lax.psum(1, axis_name)
    E = gate_w.shape[1]
    e_local = E // n_shards
    n_local, D = x.shape

    logits = x @ gate_w
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(logits, axis=-1)  # [n_local]
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]

    # position of each token within its expert's local queue
    onehot = jax.nn.one_hot(expert, E, dtype=x.dtype)  # [n_local, E]
    pos = (jnp.cumsum(onehot, axis=0) - 1.0)  # [n_local, E]
    pos_in_e = jnp.take_along_axis(pos, expert[:, None], axis=1)[:, 0]
    keep = pos_in_e < capacity
    slot = jnp.clip(pos_in_e.astype(jnp.int32), 0, capacity - 1)

    # dispatch buffer [E, C, D]: scatter kept tokens into their slot
    dispatch = jnp.zeros((E, capacity, D), x.dtype)
    dispatch = dispatch.at[expert, slot].add(
        jnp.where(keep[:, None], x, 0.0)
    )
    # group E as [n_shards, e_local, C, D] and swap shard <-> expert-group
    dispatch = dispatch.reshape(n_shards, e_local, capacity, D)
    recv = lax.all_to_all(
        dispatch, axis_name, split_axis=0, concat_axis=0, tiled=False
    )  # [n_shards, e_local, C, D]: peer s's tokens for my experts

    # expert params arrive SHARDED over the axis: [e_local, ...] locally
    my_w1, my_b1, my_w2, my_b2 = w1, b1, w2, b2

    def one_expert(tokens, w1e, b1e, w2e, b2e):
        # tokens [n_shards, C, D] -> one dense FFN block
        flat = tokens.reshape(-1, D)
        return _expert_ffn(flat, w1e, b1e, w2e, b2e).reshape(tokens.shape)

    recv_e = jnp.swapaxes(recv, 0, 1)  # [e_local, n_shards, C, D]
    out_e = jax.vmap(one_expert)(recv_e, my_w1, my_b1, my_w2, my_b2)
    out = jnp.swapaxes(out_e, 0, 1)  # [n_shards, e_local, C, D]

    back = lax.all_to_all(
        out, axis_name, split_axis=0, concat_axis=0, tiled=False
    ).reshape(E, capacity, D)
    # gather each token's result from its (expert, slot) cell
    y = back[expert, slot]  # [n_local, D]
    y = jnp.where(keep[:, None], y, 0.0)
    return y * gate[:, None]


def expert_parallel_moe(x, gate_w, w1, b1, w2, b2, mesh: Mesh,
                        axis: str = "expert",
                        capacity_factor: float = 1.25,
                        capacity: Optional[int] = None):
    """Top-1 MoE FFN with experts sharded over `axis`.

    x: [N, D] tokens, sharded over `axis` on dim 0 (N divisible by the
    axis size). Expert params are sharded over their leading E dim.
    Returns [N, D] with the same sharding as x.
    """
    n_shards = mesh.shape[axis]
    E = gate_w.shape[1]
    if E % n_shards:
        raise ValueError("n_experts %d must divide over %d shards"
                         % (E, n_shards))
    if x.shape[0] % n_shards:
        raise ValueError("token count %d must divide over %d shards"
                         % (x.shape[0], n_shards))
    if capacity is None:
        capacity = moe_capacity(x.shape[0] // n_shards, E, capacity_factor)

    fn = shard_map(
        lambda *a: _moe_shard(*a, axis_name=axis, capacity=capacity),
        mesh=mesh,
        in_specs=(P(axis, None), P(), P(axis, None, None), P(axis, None),
                  P(axis, None, None), P(axis, None)),
        out_specs=P(axis, None),
    )
    return fn(x, gate_w, w1, b1, w2, b2)
