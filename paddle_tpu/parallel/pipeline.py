"""Pipeline parallelism: GPipe-style microbatch pipeline over a 'pipe'
mesh axis.

A NEW capability beyond the 2018 reference (SURVEY.md §2.2 lists PP as
absent; the nearest reference machinery is ParallelNeuralNetwork's
per-layer device threads, ParallelNeuralNetwork.h:34). TPU-first
re-design: every device holds ONE pipeline stage's parameters (stage
dim sharded over the axis), and activations flow stage-to-stage with a
single `lax.ppermute` hop per tick inside a `lax.scan` — the classic
shard_map pipeline. With M microbatches and S stages the schedule runs
M + S - 1 ticks; per-device memory is one microbatch, and the bubble
fraction is the usual (S-1)/(M+S-1).

The stage body must be shape-preserving ([mb, D] -> [mb, D]) so one
rotating buffer serves every stage. Differentiable end-to-end (ppermute
and scan both have transpose rules), so the same schedule backpropagates
as the reverse pipeline.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

__all__ = ["gpipe_pipeline", "reference_pipeline"]


def reference_pipeline(stage_fn: Callable, stage_params, x):
    """Sequential oracle: fold x through every stage on one device.
    stage_params: pytree whose leaves have a leading stage dim [S, ...]."""
    S = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
    out = x
    for s in range(S):
        p_s = jax.tree_util.tree_map(lambda a: a[s], stage_params)
        out = stage_fn(p_s, out)
    return out


def _pipe_shard(stage_fn, params, x, axis_name: str, n_micro: int):
    """Per-device body: params = THIS device's stage params (leading
    stage dim already sharded away to size 1); x = full input, used only
    by stage 0."""
    S = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    params = jax.tree_util.tree_map(lambda a: a[0], params)
    B, D = x.shape
    mb = B // n_micro
    micro = x.reshape(n_micro, mb, D)

    n_ticks = n_micro + S - 1
    state = jnp.zeros((mb, D), x.dtype)
    outs = jnp.zeros((n_micro, mb, D), x.dtype)
    # the carry becomes device-varying after one tick; mark the zero
    # initials as varying so scan's carry types line up
    if hasattr(lax, "pcast"):
        state = lax.pcast(state, (axis_name,), to="varying")
        outs = lax.pcast(outs, (axis_name,), to="varying")

    def tick(carry, t):
        state, outs = carry
        # stage 0 injects microbatch t (older ticks already flowed on)
        inject = micro[jnp.clip(t, 0, n_micro - 1)]
        state = jnp.where((stage == 0) & (t < n_micro), inject, state)
        state = stage_fn(params, state)
        # last stage banks microbatch t-(S-1) as it completes
        done_idx = t - (S - 1)
        outs = jnp.where(
            (stage == S - 1) & (done_idx >= 0),
            outs.at[jnp.clip(done_idx, 0, n_micro - 1)].set(state),
            outs,
        )
        # rotate: stage s -> s+1 (last stage's send is ignored by 0)
        state = lax.ppermute(
            state, axis_name,
            [(i, (i + 1) % S) for i in range(S)],
        )
        return (state, outs), None

    (_, outs), _ = lax.scan(tick, (state, outs), jnp.arange(n_ticks))
    # only the last stage holds real outputs; replicate via psum
    outs = jnp.where(stage == S - 1, outs, 0.0)
    outs = lax.psum(outs, axis_name)
    return outs.reshape(B, D)


def gpipe_pipeline(stage_fn: Callable, stage_params, x, mesh: Mesh,
                   axis: str = "pipe", n_microbatches: int = 4):
    """Run x through S pipeline stages sharded over `axis`.

    stage_fn(params_one_stage, x_mb) -> y_mb, shape-preserving.
    stage_params: pytree with leading stage dim S == mesh.shape[axis].
    x: [B, D] with B divisible by n_microbatches. Returns [B, D],
    replicated over the axis.
    """
    S = mesh.shape[axis]
    leaves = jax.tree_util.tree_leaves(stage_params)
    if not leaves or leaves[0].shape[0] != S:
        raise ValueError(
            "stage_params leading dim must equal the '%s' axis size %d"
            % (axis, S)
        )
    if x.shape[0] % n_microbatches:
        raise ValueError("batch %d must divide into %d microbatches"
                         % (x.shape[0], n_microbatches))

    param_specs = jax.tree_util.tree_map(
        lambda a: P(axis, *([None] * (a.ndim - 1))), stage_params
    )
    fn = shard_map(
        lambda p, xx: _pipe_shard(stage_fn, p, xx, axis_name=axis,
                                  n_micro=n_microbatches),
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
    )
    return fn(stage_params, x)
