"""AsyncSGD -> local-SGD: the TPU-native redesign of asynchronous DP.

The reference's asynchronous data parallelism applies each trainer's
gradient to the shared parameters without waiting for the others (C++
pserver per-block async updates, ParameterServer2.h:127 + the AsyncSGD
algorithm setting in TrainerConfig.proto OptimizationConfig; the Go
pserver is async per gradient, go/pserver/service.go:285 SendGrad). The
statistical trade is staleness for communication: each replica trains on
parameters that miss the other replicas' in-flight updates.

A TPU SPMD step is globally synchronous by construction, so the redesign
expresses the same trade natively as **local SGD** (periodic model
averaging): every 'data'-axis replica keeps its OWN parameter + optimizer
state copy and runs `sync_every` optimizer steps purely locally — zero
inter-chip traffic — then the replicas average their models (one pmean
over ICI per round). `sync_every=1` with a gradient-linear update rule
(SGD, momentum) is *mathematically identical* to the synchronous
allreduce step, which is this module's exactness oracle
(tests/test_async_local.py); larger `sync_every` is the async regime:
between syncs each replica's updates are invisible to the others —
bounded staleness in place of the pserver's unbounded race.

Entry point: `Executor.run_async_local(...)` (fluid/executor.py), reached
from the user surface via `DistributeTranspiler.transpile(sync_mode=
False)` — see fluid/distribute_transpiler.py.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

if hasattr(lax, "pcast"):
    def _revary(v, axis):
        return lax.pcast(v, axis, to="varying")
else:  # pragma: no cover - older jax
    def _revary(v, axis):
        return lax.pvary(v, (axis,))


def build_local_sgd_fn(
    step,
    mesh: Mesh,
    feed_names: Sequence[str],
    steps: int,
    sync_every: int,
    axis: str = "data",
):
    """Wrap a single-step program fn into a jittable local-SGD runner.

    `step`: (persist: dict, feeds: dict, key) -> (fetches, new_persist)
    as built by core.lowering.build_step_fn, with persist_out ==
    persist_in. Feeds must each carry a leading [steps] dim, then the
    global batch dim (sharded over `axis`). Returns
      fn(persist, feeds, key) -> (fetches stacked [steps, ...] and
      replica-averaged, consensus new_persist)
    Parameters enter and leave UNstacked (ordinary replicated arrays):
    the per-replica copies exist only inside the computation, and every
    round ends on a pmean, so the result is the consensus model.
    """
    if steps % sync_every:
        raise ValueError(
            "steps (%d) must be a multiple of sync_every (%d)"
            % (steps, sync_every)
        )
    rounds = steps // sync_every
    nrep = mesh.shape[axis]
    feed_specs = {n: P(None, axis) for n in feed_names}

    def body(persist, feeds, key):
        # inside shard_map: persist values arrive stacked [1, ...] (this
        # replica's copy), feeds [steps, B/nrep, ...]
        persist = {n: v[0] for n, v in persist.items()}
        key = jax.random.fold_in(key, lax.axis_index(axis))
        # [steps, ...] -> [rounds, sync_every, ...]
        feeds = {
            n: v.reshape((rounds, sync_every) + v.shape[1:])
            for n, v in feeds.items()
        }

        def round_body(carry, xs):
            i, per_round = xs

            def local_body(c, xs_local):
                j, f = xs_local
                fetches, newp = step(
                    c, f, jax.random.fold_in(key, i * sync_every + j)
                )
                return newp, fetches

            newp, fetch_stack = lax.scan(
                local_body, carry,
                (jnp.arange(sync_every), per_round),
            )
            # sync point: replicas average their models (the only
            # collective; everything above ran replica-local). pvary
            # re-tags the now-identical copies as axis-varying so the
            # scan carry type stays stable (shard_map VMA tracking)
            newp = {
                n: _revary(lax.pmean(v, axis), axis)
                for n, v in newp.items()
            }
            return newp, fetch_stack

        new_persist, fetches = lax.scan(
            round_body, persist, (jnp.arange(rounds), feeds)
        )
        # report the replica-mean of each per-step fetch (pre-sync local
        # losses differ across replicas)
        fetches = jax.tree_util.tree_map(
            lambda a: lax.pmean(
                a.reshape((steps,) + a.shape[2:]), axis
            ),
            fetches,
        )
        return fetches, {n: v[None] for n, v in new_persist.items()}

    def fn(persist: Dict[str, Any], feeds: Dict[str, Any], key):
        stacked = {
            n: jnp.broadcast_to(v, (nrep,) + jnp.shape(v))
            for n, v in persist.items()
        }
        in_specs = ({n: P(axis) for n in stacked}, feed_specs, P())
        out_specs = (P(), {n: P(axis) for n in stacked})
        fetches, newp = shard_map(
            body, mesh=mesh, in_specs=in_specs, out_specs=out_specs
        )(stacked, feeds, key)
        # every round ends on a pmean, so the replica copies are equal:
        # keep replica 0 as the consensus model
        return fetches, {n: v[0] for n, v in newp.items()}

    return fn
