"""Auto-generated thin layer wrappers for element-wise / activation ops
(reference layers/ops.py + layer_function_generator.py)."""

from __future__ import annotations

from ..layer_helper import LayerHelper

__activations__ = [
    "sigmoid",
    "logsigmoid",
    "exp",
    "relu",
    "tanh",
    "tanh_shrink",
    "softshrink",
    "sqrt",
    "abs",
    "ceil",
    "floor",
    "round",
    "reciprocal",
    "log",
    "square",
    "softplus",
    "softsign",
    "brelu",
    "leaky_relu",
    "soft_relu",
    "elu",
    "relu6",
    "pow",
    "stanh",
    "hard_shrink",
    "thresholded_relu",
    "hard_sigmoid",
    "swish",
    "softmax",
]

__all__ = __activations__ + [
    "mean",
    "mul",
    "scale",
    "clip",
    "clip_by_norm",
    "elementwise_add",
    "elementwise_sub",
    "elementwise_mul",
    "elementwise_div",
    "elementwise_max",
    "elementwise_min",
    "elementwise_pow",
    "sequence_softmax",
]


def _single_in(op_type, out_dtype=None):
    def layer(x=None, **kwargs):
        if x is None:
            x = kwargs.pop("x", None) or kwargs.pop("input")
        attrs = {
            k: v
            for k, v in kwargs.items()
            if k not in ("name", "main_program", "startup_program") and v is not None
        }
        helper = LayerHelper(op_type, name=kwargs.get("name"))
        out = helper.create_tmp_variable(dtype=out_dtype or x.dtype)
        helper.append_op(
            type=op_type, inputs={"X": [x]}, outputs={"Out": [out]}, attrs=attrs
        )
        return out

    layer.__name__ = op_type
    layer.__doc__ = "TPU-lowered %s op (see core/kernels)." % op_type
    return layer


for _op in __activations__ + ["clip", "clip_by_norm", "sequence_softmax"]:
    globals()[_op] = _single_in(_op)


def mean(x=None, **kwargs):
    if x is None:
        x = kwargs.pop("x")
    helper = LayerHelper("mean", name=kwargs.get("name"))
    out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(type="mean", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def scale(x=None, scale=1.0, bias=0.0, **kwargs):
    if x is None:
        x = kwargs.pop("x", None) or kwargs.pop("input")
    helper = LayerHelper("scale", name=kwargs.get("name"))
    out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(
        type="scale",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"scale": float(scale), "bias": float(bias)},
    )
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, **kwargs):
    helper = LayerHelper("mul", name=kwargs.get("name"))
    out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(
        type="mul",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"x_num_col_dims": x_num_col_dims, "y_num_col_dims": y_num_col_dims},
    )
    return out


def _elementwise(op_type):
    def layer(x, y, axis=-1, act=None, name=None):
        helper = LayerHelper(op_type, name=name, act=act)
        out = helper.create_tmp_variable(dtype=x.dtype)
        helper.append_op(
            type=op_type,
            inputs={"X": [x], "Y": [y]},
            outputs={"Out": [out]},
            attrs={"axis": axis},
        )
        return helper.append_activation(out)

    layer.__name__ = op_type
    return layer


elementwise_add = _elementwise("elementwise_add")
elementwise_sub = _elementwise("elementwise_sub")
elementwise_mul = _elementwise("elementwise_mul")
elementwise_div = _elementwise("elementwise_div")
elementwise_max = _elementwise("elementwise_max")
elementwise_min = _elementwise("elementwise_min")
elementwise_pow = _elementwise("elementwise_pow")
