"""Control-flow layers: While, DynamicRNN, tensor arrays, beam search.

API parity with reference python/paddle/v2/fluid/layers/control_flow.py
(While, DynamicRNN, array_read/array_write/array_length, create_array,
increment, less_than) and layers/nn.py beam_search / beam_search_decode.
Execution model differs by design — see core/kernels_control.py.
"""

from __future__ import annotations

import contextlib

from ..core.program import unique_name
from ..layer_helper import LayerHelper

__all__ = [
    "While",
    "DynamicRNN",
    "ParallelDo",
    "get_places",
    "create_array",
    "array_read",
    "array_write",
    "array_length",
    "increment",
    "less_than",
    "beam_search",
    "beam_search_decode",
    "Print",
]


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """Debug print of a tensor's runtime value (reference
    layers/control_flow.py:149). Returns a pass-through of `input`; the
    message fires whenever the compiled step computes the value —
    including the gradient when print_phase is 'backward'/'both'."""
    if print_phase.upper() not in ("FORWARD", "BACKWARD", "BOTH"):
        raise ValueError(
            "print_phase must be 'forward', 'backward' or 'both', got %r"
            % (print_phase,)
        )
    helper = LayerHelper("print", **locals())
    out = helper.create_tmp_variable(
        dtype=input.dtype, shape=tuple(input.shape)
    )
    helper.append_op(
        type="print",
        inputs={"In": [input]},
        outputs={"Out": [out]},
        attrs={
            "first_n": int(first_n),
            "summarize": int(summarize),
            "message": message or "",
            "print_tensor_name": print_tensor_name,
            "print_tensor_type": print_tensor_type,
            "print_tensor_shape": print_tensor_shape,
            "print_tensor_lod": print_tensor_lod,
            "print_phase": print_phase.upper(),
        },
    )
    return out


def get_places(device_count=None, device_type=None):
    """Reference layers/device.py get_places: the list of devices a
    ParallelDo would split over. Here: the chips of the default mesh (or
    all local devices) — informational, since SPMD does the splitting."""
    import jax

    from ..core import TPUPlace

    n = device_count
    if not n:
        from ...parallel.mesh import get_default_mesh

        mesh = get_default_mesh()
        n = mesh.devices.size if mesh is not None else jax.local_device_count()
    return [TPUPlace(i) for i in range(int(n))]


class ParallelDo(object):
    """Data-parallel execution of a sub-region (reference
    layers/control_flow.py:233 ParallelDo -> operators/parallel_do_op.cc:27,
    which splits the batch across per-place scopes, runs the sub-block on
    each device and averages gradients).

    TPU-first redesign: under a `jax.sharding.Mesh` the Executor already
    shards every feed's batch dim over the 'data' axis and XLA SPMD
    inserts the gradient allreduce — the scope-per-place machinery is the
    mesh itself. The ops written inside `do()` therefore inline straight
    into the parent program (no sub-block), and the per-place
    output-gather is the identity: a per-example output already spans the
    global batch, and reducing a per-place mean over equal splits equals
    the global mean. The reference API (read_input / write_output /
    `pd()`) is preserved so scripts like benchmark/cluster/vgg16/
    vgg16_fluid.py run unchanged."""

    _BEFORE, _IN, _AFTER = 0, 1, 2

    def __init__(self, places, name=None):
        self.places = places
        self.inputs = []
        self.outputs = []
        self._status = self._BEFORE

    @contextlib.contextmanager
    def do(self):
        if self._status != self._BEFORE:
            raise RuntimeError("ParallelDo.do() may only be entered once")
        self._status = self._IN
        try:
            yield
        finally:
            self._status = self._AFTER

    def read_input(self, var):
        if self._status != self._IN:
            raise RuntimeError("read_input must be called inside do()")
        self.inputs.append(var)
        return var

    def write_output(self, var):
        if self._status != self._IN:
            raise RuntimeError("write_output must be called inside do()")
        self.outputs.append(var)

    def __call__(self, *args, **kwargs):
        if self._status != self._AFTER:
            raise ValueError(
                "ParallelDo output can only be retrieved after the do() block"
            )
        if not self.outputs:
            raise ValueError("ParallelDo has no output")
        return self.outputs[0] if len(self.outputs) == 1 else self.outputs


def increment(x, value=1.0, in_place=True):
    """x += value (reference control_flow.py increment)."""
    helper = LayerHelper("increment", **locals())
    if in_place:
        out = x
    else:
        out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(
        type="increment",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"step": float(value)},
    )
    return out


def less_than(x, y, cond=None, **ignored):
    helper = LayerHelper("less_than", **locals())
    if cond is None:
        cond = helper.create_tmp_variable(dtype="bool")
        cond.stop_gradient = True
    helper.append_op(
        type="less_than", inputs={"X": [x], "Y": [y]}, outputs={"Out": [cond]}
    )
    return cond


def create_array(dtype):
    """A LoDTensorArray variable (reference: LOD_TENSOR_ARRAY var type)."""
    helper = LayerHelper("array", **locals())
    arr = helper.main_program.current_block().create_var(
        name=unique_name("array"), dtype=dtype
    )
    arr.is_tensor_array = True
    arr.stop_gradient = True
    return arr


def array_write(x, i, array=None):
    helper = LayerHelper("array_write", **locals())
    if array is None:
        array = create_array(x.dtype)
    helper.append_op(
        type="array_write",
        inputs={"X": [x], "I": [i], "Array": [array]},
        outputs={"Out": [array]},
    )
    return array


def array_read(array, i):
    helper = LayerHelper("array_read", **locals())
    out = helper.create_tmp_variable(dtype=array.dtype)
    helper.append_op(
        type="array_read", inputs={"X": [array], "I": [i]}, outputs={"Out": [out]}
    )
    return out


def array_length(array):
    helper = LayerHelper("array_length", **locals())
    out = helper.create_tmp_variable(dtype="int64")
    out.stop_gradient = True
    helper.append_op(
        type="array_length", inputs={"X": [array]}, outputs={"Out": [out]}
    )
    return out


class While(object):
    """Counter-bounded loop; unrolls at trace time (kernels_control.py).

    Usage (reference control_flow.py While):
        cond = less_than(counter, limit)
        w = While(cond)
        with w.block():
            ... body ops; must update `cond` via less_than(..., cond=cond)
    """

    def __init__(self, cond, name=None):
        self.helper = LayerHelper("while", name=name)
        if cond.dtype != "bool":
            raise TypeError("While condition must be a bool variable")
        self.cond_var = cond

    @contextlib.contextmanager
    def block(self):
        main = self.helper.main_program
        parent = main.current_block()
        sub = main.create_block()
        try:
            yield
        finally:
            main.rollback()
        # compute the op's outer reads/writes for pruning: names the sub-block
        # reads but does not produce, and names it writes that exist outside
        produced = set()
        reads, writes = [], []
        for op in sub.ops:
            for n in op.input_arg_names:
                if n not in produced and n not in reads:
                    reads.append(n)
            for n in op.output_arg_names:
                produced.add(n)
                outer = parent._find_var_recursive(n)
                if outer is not None and n not in writes:
                    writes.append(n)
        parent.append_op(
            type="while",
            inputs={"Condition": [self.cond_var], "X": reads},
            outputs={"Out": writes},
            attrs={"sub_block": sub.idx},
        )


class DynamicRNN(object):
    """Per-timestep sub-network over a ragged batch (reference
    control_flow.py DynamicRNN, RecurrentGradientMachine in the legacy
    stack). Lowers to ONE lax.scan over bucketed padded time — no host
    loop, dense MXU steps (core/kernels_control.py dynamic_rnn)."""

    def __init__(self, name=None):
        self.helper = LayerHelper("dynamic_rnn", name=name)
        self._step_in = []  # (outer_name, inner_name)
        self._static_in = []
        self._mems = []  # dict(init, pre, update, shape, value, dtype)
        self._outputs = []  # (inner_name, outer_var)
        self._sub_idx = None
        self._in_block = False
        self._closed = False

    @contextlib.contextmanager
    def block(self):
        main = self.helper.main_program
        parent = main.current_block()
        sub = main.create_block()
        self._sub_idx = sub.idx
        self._in_block = True
        try:
            yield
        finally:
            self._in_block = False
            main.rollback()
        for m in self._mems:
            if m["update"] is None:
                raise ValueError(
                    "DynamicRNN memory %r was never update_memory()'d" % m["pre"]
                )
        if not self._outputs:
            raise ValueError("DynamicRNN needs at least one output()")
        parent.append_op(
            type="dynamic_rnn",
            inputs={
                "StepIn": [n for n, _ in self._step_in],
                "Static": [n for n, _ in self._static_in],
                "MemInit": [m["init"] for m in self._mems if m["init"]],
            },
            outputs={"Out": [v.name for _, v in self._outputs]},
            attrs={
                "sub_block": sub.idx,
                "step_inner": [i for _, i in self._step_in],
                "static_inner": [i for _, i in self._static_in],
                "mem_pre": [m["pre"] for m in self._mems],
                "mem_update": [m["update"] for m in self._mems],
                "mem_init_names": [m["init"] or "" for m in self._mems],
                "mem_shapes": [m["shape"] or [] for m in self._mems],
                "mem_values": [m["value"] for m in self._mems],
                "mem_dtypes": [m["dtype"] for m in self._mems],
                "out_inner": [i for i, _ in self._outputs],
            },
        )
        self._closed = True

    def _require_in_block(self, what):
        if not self._in_block:
            raise RuntimeError("%s must be called inside rnn.block()" % what)

    def step_input(self, x):
        self._require_in_block("step_input")
        blk = self.helper.main_program.current_block()
        # per-step value is [n_seqs, ...feature dims]: same rank as the
        # packed outer var, the ragged axis becomes the (dynamic) batch
        inner = blk.create_var(
            name=unique_name(x.name + "@step"),
            shape=((-1,) + tuple(x.shape[1:])) if x.shape else None,
            dtype=x.dtype,
        )
        self._step_in.append((x.name, inner.name))
        return inner

    def static_input(self, x):
        self._require_in_block("static_input")
        blk = self.helper.main_program.current_block()
        inner = blk.create_var(
            name=unique_name(x.name + "@static"), shape=x.shape, dtype=x.dtype
        )
        self._static_in.append((x.name, inner.name))
        return inner

    def memory(self, init=None, shape=None, value=0.0, dtype="float32"):
        self._require_in_block("memory")
        blk = self.helper.main_program.current_block()
        if init is not None:
            pre = blk.create_var(
                name=unique_name("mem@pre"), shape=init.shape, dtype=init.dtype
            )
            self._mems.append(
                dict(init=init.name, pre=pre.name, update=None, shape=None,
                     value=0.0, dtype=str(init.dtype))
            )
        else:
            if shape is None:
                raise ValueError("memory() needs init= or shape=")
            # shape is the per-sequence feature shape; the leading dim is
            # the (dynamic) live-sequence batch
            feat = [int(s) for s in shape if int(s) > 0]
            pre = blk.create_var(
                name=unique_name("mem@pre"), shape=(-1,) + tuple(feat), dtype=dtype
            )
            self._mems.append(
                dict(init=None, pre=pre.name, update=None,
                     shape=[int(s) for s in shape], value=float(value),
                     dtype=dtype)
            )
        return pre

    def update_memory(self, ex_mem, new_mem):
        self._require_in_block("update_memory")
        for m in self._mems:
            if m["pre"] == ex_mem.name:
                m["update"] = new_mem.name
                return
        raise ValueError("%r is not a DynamicRNN memory" % ex_mem.name)

    def output(self, *outputs):
        self._require_in_block("output")
        parent = self.helper.main_program.block(
            self.helper.main_program.current_block().parent_idx
        )
        for o in outputs:
            outer = parent.create_var(
                name=unique_name("dynamic_rnn_out"),
                shape=o.shape,
                dtype=o.dtype,
                lod_level=1,
            )
            self._outputs.append((o.name, outer))

    def __call__(self, *args, **kwargs):
        if not self._closed:
            raise RuntimeError("call rnn() after the rnn.block() context ends")
        outs = [v for _, v in self._outputs]
        return outs[0] if len(outs) == 1 else outs


def beam_search(pre_ids, ids, scores, beam_size, end_id, level=0):
    """One beam-search step (reference layers beam_search -> operators/
    beam_search_op.cc; TPU-native full-width redesign in kernels_control)."""
    helper = LayerHelper("beam_search", **locals())
    selected_ids = helper.create_tmp_variable(dtype=ids.dtype)
    selected_scores = helper.create_tmp_variable(dtype=scores.dtype)
    helper.append_op(
        type="beam_search",
        inputs={"pre_ids": [pre_ids], "ids": [ids], "scores": [scores]},
        outputs={
            "selected_ids": [selected_ids],
            "selected_scores": [selected_scores],
        },
        attrs={"beam_size": int(beam_size), "end_id": int(end_id), "level": level},
    )
    return selected_ids, selected_scores


def beam_search_decode(ids, scores, beam_width=0,
                       num_results_per_sample=0):
    """Backtrack completed beams into sentences. Returns (sentence_ids,
    sentence_scores) as padded [n_source*beam, T] arrays; per-row true
    lengths are fetchable via `sentence_ids.lens_name`. When
    0 < num_results_per_sample < beam_width, only each source's top-n
    rows (by cumulative score) are kept."""
    helper = LayerHelper("beam_search_decode", **locals())
    sentence_ids = helper.create_tmp_variable(dtype=ids.dtype)
    sentence_scores = helper.create_tmp_variable(dtype=scores.dtype)
    lens = helper.create_tmp_variable(dtype="int32")
    helper.append_op(
        type="beam_search_decode",
        inputs={"Ids": [ids], "Scores": [scores]},
        outputs={
            "SentenceIds": [sentence_ids],
            "SentenceScores": [sentence_scores],
            "SentenceLens": [lens],
        },
        attrs={
            "beam_width": int(beam_width),
            "num_results_per_sample": int(num_results_per_sample),
        },
    )
    sentence_ids.lens_name = lens.name
    sentence_scores.lens_name = lens.name
    return sentence_ids, sentence_scores


class IfElse(object):
    """Row-wise two-branch conditional (reference control_flow.py IfElse
    + split_lod_tensor/merge_lod_tensor ops). `cond` is an [N, 1] bool;
    inputs are routed into the active branch's rows, both branch bodies
    append row-parallel ops, and outputs merge back into original row
    order.

    TPU-first: both branches always execute on their routed (compacted,
    zero-padded) buffers inside the one fused XLA program — there is no
    host-side branching; `merge_lod_tensor` reassembles by the mask's
    rank, so branch ops must be row-wise (the same contract the
    reference's scope-per-branch execution imposes).

    Usage:
        ie = layers.IfElse(cond)
        with ie.true_block():
            d = ie.input(x)
            ie.output(some_rowwise_fn(d))
        with ie.false_block():
            d = ie.input(x)
            ie.output(other_fn(d))
        (out,) = ie()
    """

    OUT_IF_ELSE_BLOCKS = 2

    def __init__(self, cond, name=None):
        self.helper = LayerHelper("ifelse", name=name)
        self.cond = cond
        self._branch = None  # True / False while inside a block
        self._outs = {True: [], False: []}
        self._splits = {}  # input var name -> (true_rows, false_rows)

    @contextlib.contextmanager
    def _block(self, is_true):
        if self._branch is not None:
            raise RuntimeError("IfElse blocks cannot nest")
        self._branch = is_true
        try:
            yield
        finally:
            self._branch = None

    def true_block(self):
        return self._block(True)

    def false_block(self):
        return self._block(False)

    def input(self, x):
        if self._branch is None:
            raise RuntimeError("IfElse.input() must be called in a block")
        if x.name not in self._splits:
            from . import nn as _nn

            self._splits[x.name] = _nn.split_lod_tensor(x, self.cond)
        t, f = self._splits[x.name]
        return t if self._branch else f

    def output(self, *outs):
        if self._branch is None:
            raise RuntimeError("IfElse.output() must be called in a block")
        self._outs[self._branch].extend(outs)

    def __call__(self):
        if len(self._outs[True]) != len(self._outs[False]):
            raise ValueError(
                "IfElse branches produced %d vs %d outputs"
                % (len(self._outs[True]), len(self._outs[False]))
            )
        from . import nn as _nn

        return [
            _nn.merge_lod_tensor(t, f, t, self.cond)
            for t, f in zip(self._outs[True], self._outs[False])
        ]


class Switch(object):
    """First-true-case-wins conditional assignment (reference
    control_flow.py Switch + conditional_block_op; the learning-rate
    warmup pattern).

    Case bodies may contain any ops; every variable they WRITE that is
    visible outside the Switch becomes a select chain: the value from the
    first case whose scalar condition holds, else the value from
    `default()`, else the variable's prior value. Lowered to `select`
    ops — all branches compute inside the fused program, selection is a
    jnp.where (the TPU-idiomatic form of the reference's scope-guarded
    conditional block execution).
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self._cases = []  # (cond_var or None for default, writes dict)
        self._inside = False
        # select chains are built only for variables that existed BEFORE
        # the switch: those are the assignment targets; vars created
        # inside a case body are case-local temps
        self._preexisting = set(
            self.helper.main_program.current_block().vars
        )

    @contextlib.contextmanager
    def case(self, condition):
        yield from self._capture(condition)

    @contextlib.contextmanager
    def default(self):
        yield from self._capture(None)

    def _capture(self, condition):
        if self._inside:
            raise RuntimeError("Switch cases cannot nest")
        if any(c is None for c, _ in self._cases):
            # reference control_flow.py Switch raises the same way
            raise ValueError("there should be no case after default")
        block = self.helper.main_program.current_block()
        start = len(block.ops)
        self._inside = True
        try:
            yield
        finally:
            self._inside = False
        # redirect every visible write of the case body into a case-local
        # temp; record target -> temp for the select chain. Only reads
        # AFTER the write see the temp — reads before it (e.g.
        # `scale(lr, 0.5)` feeding the `assign` back into lr) must keep
        # reading the prior value.
        writes = {}
        case_ops = block.ops[start:]
        for i, op in enumerate(case_ops):
            for slot, names in op.outputs.items():
                for k, n in enumerate(names):
                    if n not in self._preexisting and not (
                        n not in block.vars
                        and block._find_var_recursive(n) is not None
                    ):
                        # created inside the switch: case-local temp.
                        # Parent-block vars (Switch inside a While body)
                        # are targets even though the current block's own
                        # vars dict never held them.
                        continue
                    tmp = "%s@case%d" % (n, len(self._cases))
                    src = block.var(n)
                    block.create_var(name=tmp, dtype=src.dtype,
                                     shape=src.shape)
                    op.outputs[slot][k] = tmp
                    for later in case_ops[i + 1:]:
                        for islot, inames in later.inputs.items():
                            for j, inn in enumerate(inames):
                                if inn == n:
                                    later.inputs[islot][j] = tmp
                    writes[n] = tmp
        self._cases.append((condition, writes))

    def __exit__(self, *exc):
        if exc and exc[0] is not None:
            return False
        block = self.helper.main_program.current_block()
        targets = []
        for _, writes in self._cases:
            for t in writes:
                if t not in targets:
                    targets.append(t)
        for t in targets:
            # first-true-wins: fold cases right-to-left. EVERY case
            # participates in every target's chain — a case that matched
            # but did not write t pins t to its PRIOR value (the
            # reference executes exactly one conditional block, so later
            # cases must not leak through a matching earlier one).
            current = t  # no case matches -> prior value
            for cond, writes in reversed(self._cases):
                val = writes.get(t, t)
                if cond is None:
                    current = val  # default runs when nothing matched
                    continue
                sel = "%s@sel%d" % (t, len(block.ops))
                block.create_var(name=sel, dtype=block.var(t).dtype,
                                 shape=block.var(t).shape)
                block.append_op(
                    type="select",
                    inputs={"Cond": [cond.name], "X": [val],
                            "Y": [current]},
                    outputs={"Out": [sel]},
                )
                current = sel
            if current != t:
                block.append_op(
                    type="assign", inputs={"X": [current]},
                    outputs={"Out": [t]},
                )
        return False

    def __enter__(self):
        return self


class StaticRNN(object):
    """Fixed-length unrolled RNN builder (reference control_flow.py
    StaticRNN): inputs are [T, ...] time-major dense tensors, the step
    body is captured once and REPLAYED T times at graph-build time with
    step-suffixed variable names (graph-level unroll — XLA then sees T
    identical fused steps; for ragged batches use DynamicRNN, which
    lowers to one lax.scan instead).
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self._mems = []      # dict(init_name, pre_name, update_name)
        self._step_in = []   # (outer_name, inner_name)
        self._outs = []      # (inner_name, outer_var)
        self._T = None
        self._ops = None

    @contextlib.contextmanager
    def step(self):
        block = self.helper.main_program.current_block()
        start = len(block.ops)
        yield
        self._ops = block.ops[start:]
        del block.ops[start:]
        self._unroll(block)

    def step_input(self, x):
        if not x.shape or int(x.shape[0]) <= 0:
            raise ValueError(
                "StaticRNN.step_input needs a STATIC time-major leading "
                "dim (got shape %r); declare the data layer with "
                "append_batch_size=False and an explicit T" % (x.shape,)
            )
        T = int(x.shape[0])
        if self._T is None:
            self._T = T
        elif self._T != T:
            raise ValueError("step_input lengths disagree: %d vs %d"
                             % (self._T, T))
        block = self.helper.main_program.current_block()
        inner = self.helper.create_tmp_variable(x.dtype)
        inner.shape = tuple(x.shape[1:])
        self._step_in.append((x.name, inner.name))
        return inner

    def memory(self, init):
        block = self.helper.main_program.current_block()
        pre = self.helper.create_tmp_variable(init.dtype)
        pre.shape = init.shape
        self._mems.append({"init": init.name, "pre": pre.name,
                           "update": None})
        return pre

    def update_memory(self, mem, new):
        for m in self._mems:
            if m["pre"] == mem.name:
                m["update"] = new.name
                return
        raise ValueError("update_memory: unknown memory %r" % mem.name)

    def step_output(self, o):
        outer = self.helper.create_tmp_variable(o.dtype)
        outer.lod_level = 0
        self._outs.append((o.name, outer))

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def __call__(self):
        outs = [v for _, v in self._outs]
        return outs[0] if len(outs) == 1 else outs

    # ------------------------------------------------------------------
    def _unroll(self, block):
        for m in self._mems:
            if m["update"] is None:
                raise ValueError("StaticRNN memory never update_memory()'d")
        if self._T is None:
            raise ValueError("StaticRNN needs at least one step_input")
        T = self._T

        inner_names = {n for _, n in self._step_in}
        inner_names |= {m["pre"] for m in self._mems}
        for op in self._ops:
            inner_names.update(op.output_arg_names)

        def t_name(n, t):
            return "%s@t%d" % (n, t) if n in inner_names else n

        step_results = {i: [] for i, _ in self._outs}
        for t in range(T):
            # bind step inputs: x[t] (slice keeps a leading 1, squeeze it)
            for outer, inner in self._step_in:
                src = block.var(outer)
                sl = t_name(inner, t) + "@sl"
                block.create_var(name=sl, dtype=src.dtype)
                block.create_var(name=t_name(inner, t), dtype=src.dtype)
                block.append_op(
                    type="slice",
                    inputs={"Input": [outer]},
                    outputs={"Out": [sl]},
                    attrs={"axes": [0], "starts": [t], "ends": [t + 1]},
                )
                block.append_op(
                    type="squeeze",
                    inputs={"X": [sl]},
                    outputs={"Out": [t_name(inner, t)]},
                    attrs={"axes": [0]},
                )
            # bind memories: init at t=0, else previous step's update
            for m in self._mems:
                src = m["init"] if t == 0 else t_name(m["update"], t - 1)
                block.create_var(name=t_name(m["pre"], t), dtype="float32")
                block.append_op(
                    type="assign", inputs={"X": [src]},
                    outputs={"Out": [t_name(m["pre"], t)]},
                )
            # replay body with step-suffixed names
            for op in self._ops:
                inputs = {s: [t_name(n, t) for n in ns]
                          for s, ns in op.inputs.items()}
                outputs = {}
                for s, ns in op.outputs.items():
                    outs = []
                    for n in ns:
                        nn = t_name(n, t)
                        if block._find_var_recursive(nn) is None:
                            v = block.var(n)
                            block.create_var(name=nn, dtype=v.dtype)
                        outs.append(nn)
                    outputs[s] = outs
                block.append_op(type=op.type, inputs=inputs,
                                outputs=outputs, attrs=dict(op.attrs))
            for inner, _ in self._outs:
                step_results[inner].append(t_name(inner, t))

        # stack step outputs to [T, ...]
        for inner, outer in self._outs:
            parts = []
            for t, n in enumerate(step_results[inner]):
                un = n + "@u"
                block.create_var(name=un, dtype="float32")
                block.append_op(
                    type="unsqueeze", inputs={"X": [n]},
                    outputs={"Out": [un]}, attrs={"axes": [0]},
                )
                parts.append(un)
            block.append_op(
                type="concat", inputs={"X": parts},
                outputs={"Out": [outer.name]}, attrs={"axis": 0},
            )


__all__ += ["IfElse", "Switch", "StaticRNN"]
