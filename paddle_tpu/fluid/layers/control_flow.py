"""Control-flow layers: While, DynamicRNN, tensor arrays, beam search.

API parity with reference python/paddle/v2/fluid/layers/control_flow.py
(While, DynamicRNN, array_read/array_write/array_length, create_array,
increment, less_than) and layers/nn.py beam_search / beam_search_decode.
Execution model differs by design — see core/kernels_control.py.
"""

from __future__ import annotations

import contextlib

from ..core.program import unique_name
from ..layer_helper import LayerHelper

__all__ = [
    "While",
    "DynamicRNN",
    "ParallelDo",
    "get_places",
    "create_array",
    "array_read",
    "array_write",
    "array_length",
    "increment",
    "less_than",
    "beam_search",
    "beam_search_decode",
]


def get_places(device_count=None, device_type=None):
    """Reference layers/device.py get_places: the list of devices a
    ParallelDo would split over. Here: the chips of the default mesh (or
    all local devices) — informational, since SPMD does the splitting."""
    import jax

    from ..core import TPUPlace

    n = device_count
    if not n:
        from ...parallel.mesh import get_default_mesh

        mesh = get_default_mesh()
        n = mesh.devices.size if mesh is not None else jax.local_device_count()
    return [TPUPlace(i) for i in range(int(n))]


class ParallelDo(object):
    """Data-parallel execution of a sub-region (reference
    layers/control_flow.py:233 ParallelDo -> operators/parallel_do_op.cc:27,
    which splits the batch across per-place scopes, runs the sub-block on
    each device and averages gradients).

    TPU-first redesign: under a `jax.sharding.Mesh` the Executor already
    shards every feed's batch dim over the 'data' axis and XLA SPMD
    inserts the gradient allreduce — the scope-per-place machinery is the
    mesh itself. The ops written inside `do()` therefore inline straight
    into the parent program (no sub-block), and the per-place
    output-gather is the identity: a per-example output already spans the
    global batch, and reducing a per-place mean over equal splits equals
    the global mean. The reference API (read_input / write_output /
    `pd()`) is preserved so scripts like benchmark/cluster/vgg16/
    vgg16_fluid.py run unchanged."""

    _BEFORE, _IN, _AFTER = 0, 1, 2

    def __init__(self, places, name=None):
        self.places = places
        self.inputs = []
        self.outputs = []
        self._status = self._BEFORE

    @contextlib.contextmanager
    def do(self):
        if self._status != self._BEFORE:
            raise RuntimeError("ParallelDo.do() may only be entered once")
        self._status = self._IN
        try:
            yield
        finally:
            self._status = self._AFTER

    def read_input(self, var):
        if self._status != self._IN:
            raise RuntimeError("read_input must be called inside do()")
        self.inputs.append(var)
        return var

    def write_output(self, var):
        if self._status != self._IN:
            raise RuntimeError("write_output must be called inside do()")
        self.outputs.append(var)

    def __call__(self, *args, **kwargs):
        if self._status != self._AFTER:
            raise ValueError(
                "ParallelDo output can only be retrieved after the do() block"
            )
        if not self.outputs:
            raise ValueError("ParallelDo has no output")
        return self.outputs[0] if len(self.outputs) == 1 else self.outputs


def increment(x, value=1.0, in_place=True):
    """x += value (reference control_flow.py increment)."""
    helper = LayerHelper("increment", **locals())
    if in_place:
        out = x
    else:
        out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(
        type="increment",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"step": float(value)},
    )
    return out


def less_than(x, y, cond=None, **ignored):
    helper = LayerHelper("less_than", **locals())
    if cond is None:
        cond = helper.create_tmp_variable(dtype="bool")
        cond.stop_gradient = True
    helper.append_op(
        type="less_than", inputs={"X": [x], "Y": [y]}, outputs={"Out": [cond]}
    )
    return cond


def create_array(dtype):
    """A LoDTensorArray variable (reference: LOD_TENSOR_ARRAY var type)."""
    helper = LayerHelper("array", **locals())
    arr = helper.main_program.current_block().create_var(
        name=unique_name("array"), dtype=dtype
    )
    arr.is_tensor_array = True
    arr.stop_gradient = True
    return arr


def array_write(x, i, array=None):
    helper = LayerHelper("array_write", **locals())
    if array is None:
        array = create_array(x.dtype)
    helper.append_op(
        type="array_write",
        inputs={"X": [x], "I": [i], "Array": [array]},
        outputs={"Out": [array]},
    )
    return array


def array_read(array, i):
    helper = LayerHelper("array_read", **locals())
    out = helper.create_tmp_variable(dtype=array.dtype)
    helper.append_op(
        type="array_read", inputs={"X": [array], "I": [i]}, outputs={"Out": [out]}
    )
    return out


def array_length(array):
    helper = LayerHelper("array_length", **locals())
    out = helper.create_tmp_variable(dtype="int64")
    out.stop_gradient = True
    helper.append_op(
        type="array_length", inputs={"X": [array]}, outputs={"Out": [out]}
    )
    return out


class While(object):
    """Counter-bounded loop; unrolls at trace time (kernels_control.py).

    Usage (reference control_flow.py While):
        cond = less_than(counter, limit)
        w = While(cond)
        with w.block():
            ... body ops; must update `cond` via less_than(..., cond=cond)
    """

    def __init__(self, cond, name=None):
        self.helper = LayerHelper("while", name=name)
        if cond.dtype != "bool":
            raise TypeError("While condition must be a bool variable")
        self.cond_var = cond

    @contextlib.contextmanager
    def block(self):
        main = self.helper.main_program
        parent = main.current_block()
        sub = main.create_block()
        try:
            yield
        finally:
            main.rollback()
        # compute the op's outer reads/writes for pruning: names the sub-block
        # reads but does not produce, and names it writes that exist outside
        produced = set()
        reads, writes = [], []
        for op in sub.ops:
            for n in op.input_arg_names:
                if n not in produced and n not in reads:
                    reads.append(n)
            for n in op.output_arg_names:
                produced.add(n)
                outer = parent._find_var_recursive(n)
                if outer is not None and n not in writes:
                    writes.append(n)
        parent.append_op(
            type="while",
            inputs={"Condition": [self.cond_var], "X": reads},
            outputs={"Out": writes},
            attrs={"sub_block": sub.idx},
        )


class DynamicRNN(object):
    """Per-timestep sub-network over a ragged batch (reference
    control_flow.py DynamicRNN, RecurrentGradientMachine in the legacy
    stack). Lowers to ONE lax.scan over bucketed padded time — no host
    loop, dense MXU steps (core/kernels_control.py dynamic_rnn)."""

    def __init__(self, name=None):
        self.helper = LayerHelper("dynamic_rnn", name=name)
        self._step_in = []  # (outer_name, inner_name)
        self._static_in = []
        self._mems = []  # dict(init, pre, update, shape, value, dtype)
        self._outputs = []  # (inner_name, outer_var)
        self._sub_idx = None
        self._in_block = False
        self._closed = False

    @contextlib.contextmanager
    def block(self):
        main = self.helper.main_program
        parent = main.current_block()
        sub = main.create_block()
        self._sub_idx = sub.idx
        self._in_block = True
        try:
            yield
        finally:
            self._in_block = False
            main.rollback()
        for m in self._mems:
            if m["update"] is None:
                raise ValueError(
                    "DynamicRNN memory %r was never update_memory()'d" % m["pre"]
                )
        if not self._outputs:
            raise ValueError("DynamicRNN needs at least one output()")
        parent.append_op(
            type="dynamic_rnn",
            inputs={
                "StepIn": [n for n, _ in self._step_in],
                "Static": [n for n, _ in self._static_in],
                "MemInit": [m["init"] for m in self._mems if m["init"]],
            },
            outputs={"Out": [v.name for _, v in self._outputs]},
            attrs={
                "sub_block": sub.idx,
                "step_inner": [i for _, i in self._step_in],
                "static_inner": [i for _, i in self._static_in],
                "mem_pre": [m["pre"] for m in self._mems],
                "mem_update": [m["update"] for m in self._mems],
                "mem_init_names": [m["init"] or "" for m in self._mems],
                "mem_shapes": [m["shape"] or [] for m in self._mems],
                "mem_values": [m["value"] for m in self._mems],
                "mem_dtypes": [m["dtype"] for m in self._mems],
                "out_inner": [i for i, _ in self._outputs],
            },
        )
        self._closed = True

    def _require_in_block(self, what):
        if not self._in_block:
            raise RuntimeError("%s must be called inside rnn.block()" % what)

    def step_input(self, x):
        self._require_in_block("step_input")
        blk = self.helper.main_program.current_block()
        # per-step value is [n_seqs, ...feature dims]: same rank as the
        # packed outer var, the ragged axis becomes the (dynamic) batch
        inner = blk.create_var(
            name=unique_name(x.name + "@step"),
            shape=((-1,) + tuple(x.shape[1:])) if x.shape else None,
            dtype=x.dtype,
        )
        self._step_in.append((x.name, inner.name))
        return inner

    def static_input(self, x):
        self._require_in_block("static_input")
        blk = self.helper.main_program.current_block()
        inner = blk.create_var(
            name=unique_name(x.name + "@static"), shape=x.shape, dtype=x.dtype
        )
        self._static_in.append((x.name, inner.name))
        return inner

    def memory(self, init=None, shape=None, value=0.0, dtype="float32"):
        self._require_in_block("memory")
        blk = self.helper.main_program.current_block()
        if init is not None:
            pre = blk.create_var(
                name=unique_name("mem@pre"), shape=init.shape, dtype=init.dtype
            )
            self._mems.append(
                dict(init=init.name, pre=pre.name, update=None, shape=None,
                     value=0.0, dtype=str(init.dtype))
            )
        else:
            if shape is None:
                raise ValueError("memory() needs init= or shape=")
            # shape is the per-sequence feature shape; the leading dim is
            # the (dynamic) live-sequence batch
            feat = [int(s) for s in shape if int(s) > 0]
            pre = blk.create_var(
                name=unique_name("mem@pre"), shape=(-1,) + tuple(feat), dtype=dtype
            )
            self._mems.append(
                dict(init=None, pre=pre.name, update=None,
                     shape=[int(s) for s in shape], value=float(value),
                     dtype=dtype)
            )
        return pre

    def update_memory(self, ex_mem, new_mem):
        self._require_in_block("update_memory")
        for m in self._mems:
            if m["pre"] == ex_mem.name:
                m["update"] = new_mem.name
                return
        raise ValueError("%r is not a DynamicRNN memory" % ex_mem.name)

    def output(self, *outputs):
        self._require_in_block("output")
        parent = self.helper.main_program.block(
            self.helper.main_program.current_block().parent_idx
        )
        for o in outputs:
            outer = parent.create_var(
                name=unique_name("dynamic_rnn_out"),
                shape=o.shape,
                dtype=o.dtype,
                lod_level=1,
            )
            self._outputs.append((o.name, outer))

    def __call__(self, *args, **kwargs):
        if not self._closed:
            raise RuntimeError("call rnn() after the rnn.block() context ends")
        outs = [v for _, v in self._outputs]
        return outs[0] if len(outs) == 1 else outs


def beam_search(pre_ids, ids, scores, beam_size, end_id, level=0):
    """One beam-search step (reference layers beam_search -> operators/
    beam_search_op.cc; TPU-native full-width redesign in kernels_control)."""
    helper = LayerHelper("beam_search", **locals())
    selected_ids = helper.create_tmp_variable(dtype=ids.dtype)
    selected_scores = helper.create_tmp_variable(dtype=scores.dtype)
    helper.append_op(
        type="beam_search",
        inputs={"pre_ids": [pre_ids], "ids": [ids], "scores": [scores]},
        outputs={
            "selected_ids": [selected_ids],
            "selected_scores": [selected_scores],
        },
        attrs={"beam_size": int(beam_size), "end_id": int(end_id), "level": level},
    )
    return selected_ids, selected_scores


def beam_search_decode(ids, scores):
    """Backtrack completed beams into sentences. Returns (sentence_ids,
    sentence_scores) as padded [n_source*beam, T] arrays; per-row true
    lengths are fetchable via `sentence_ids.lens_name`."""
    helper = LayerHelper("beam_search_decode", **locals())
    sentence_ids = helper.create_tmp_variable(dtype=ids.dtype)
    sentence_scores = helper.create_tmp_variable(dtype=scores.dtype)
    lens = helper.create_tmp_variable(dtype="int32")
    helper.append_op(
        type="beam_search_decode",
        inputs={"Ids": [ids], "Scores": [scores]},
        outputs={
            "SentenceIds": [sentence_ids],
            "SentenceScores": [sentence_scores],
            "SentenceLens": [lens],
        },
    )
    sentence_ids.lens_name = lens.name
    sentence_scores.lens_name = lens.name
    return sentence_ids, sentence_scores
