"""Tensor creation/manipulation layers (reference layers/tensor.py)."""

from __future__ import annotations

from ..core.program import Variable
from ..initializer import Constant
from ..layer_helper import LayerHelper

__all__ = [
    "create_tensor",
    "create_parameter",
    "create_global_var",
    "cast",
    "concat",
    "sums",
    "assign",
    "fill_constant",
    "fill_constant_batch_size_like",
    "ones",
    "zeros",
]


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_variable(
        name=helper.name, dtype=dtype, persistable=persistable
    )


def create_parameter(
    shape, dtype, name=None, attr=None, is_bias=False, default_initializer=None
):
    from ..param_attr import ParamAttr

    helper = LayerHelper("create_parameter", name=name)
    attr = ParamAttr.to_attr(attr)
    if attr.name is None and name is not None:
        attr.name = name
    return helper.create_parameter(attr, shape, dtype, is_bias, default_initializer)


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False, name=None):
    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(
        dtype=dtype, shape=shape, persistable=persistable, name=name
    )
    helper.set_variable_initializer(var, initializer=Constant(value=float(value)))
    return var


def cast(x, dtype):
    helper = LayerHelper("cast", **locals())
    out = helper.create_tmp_variable(dtype=dtype)
    helper.append_op(
        type="cast",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"in_dtype": x.dtype, "out_dtype": dtype},
    )
    return out


def concat(input, axis=0):
    helper = LayerHelper("concat", **locals())
    out = helper.create_tmp_variable(dtype=helper.input_dtype())
    helper.append_op(
        type="concat",
        inputs={"X": input},
        outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def sums(input, out=None):
    helper = LayerHelper("sum", **locals())
    if out is None:
        out = helper.create_tmp_variable(dtype=helper.input_dtype())
    helper.append_op(type="sum", inputs={"X": input}, outputs={"Out": [out]})
    return out


def assign(input, output):
    helper = LayerHelper("assign", **locals())
    if isinstance(input, Variable):
        helper.append_op(
            type="assign", inputs={"X": [input]}, outputs={"Out": [output]}
        )
    else:
        import numpy as np

        arr = np.asarray(input)
        helper.append_op(
            type="assign_value",
            outputs={"Out": [output]},
            attrs={
                "shape": list(arr.shape),
                "dtype": str(arr.dtype),
                "values": arr.reshape(-1).tolist(),
            },
        )
    return output


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant", **locals())
    if out is None:
        out = helper.create_tmp_variable(dtype=dtype)
    helper.append_op(
        type="fill_constant",
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": dtype, "value": float(value)},
    )
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(
    input, shape, dtype, value, input_dim_idx=0, output_dim_idx=0
):
    helper = LayerHelper("fill_constant_batch_size_like", **locals())
    out = helper.create_tmp_variable(dtype=dtype)
    helper.append_op(
        type="fill_constant_batch_size_like",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={
            "shape": list(shape),
            "dtype": dtype,
            "value": float(value),
            "input_dim_idx": input_dim_idx,
            "output_dim_idx": output_dim_idx,
        },
    )
    out.stop_gradient = True
    return out


def ones(shape, dtype, force_cpu=False):
    return fill_constant(value=1.0, shape=shape, dtype=dtype)


def zeros(shape, dtype, force_cpu=False):
    return fill_constant(value=0.0, shape=shape, dtype=dtype)
