"""Data-entry layers (reference layers/io.py: data:24)."""

from __future__ import annotations

from ..core.program import default_main_program, default_startup_program

__all__ = ["data"]


def data(
    name,
    shape,
    append_batch_size=True,
    dtype="float32",
    lod_level=0,
    type=None,
    stop_gradient=True,
    **kwargs,
):
    """Declare a feed slot. With append_batch_size, -1 is prepended as the
    batch dim (reference layers/io.py data)."""
    helper_shape = list(shape)
    if append_batch_size:
        helper_shape = [-1] + helper_shape
    main = kwargs.get("main_program") or default_main_program()
    var = main.global_block().create_var(
        name=name,
        shape=helper_shape,
        dtype=dtype,
        lod_level=lod_level,
        stop_gradient=stop_gradient,
        is_data=True,
    )
    return var


# ---------------------------------------------------------------------
# pserver-surface shims (reference layers/io.py:102 ListenAndServ, :173
# Send). In the reference these wrap the gRPC listen_and_serv / send ops
# (operators/listen_and_serv_op.cc:56, send_op.cc); in this framework
# dense distributed training is XLA-SPMD over the mesh (the
# DistributeTranspiler maps the whole pserver topology onto it), so
# these classes keep reference programs IMPORTING and BUILDING: the
# optimize block recorded under `do()` runs inline in this process —
# the same single-process layout the reference's own
# send_recv_op_test.cc exercises — and `Send` resolves against the
# in-process endpoint registry.
# ---------------------------------------------------------------------

_SERV_REGISTRY = {}  # endpoint -> ListenAndServ


class BlockGuardServ(object):
    """`with serv.do():` — ops appended inside the guard become the
    server's optimize block (reference layers/io.py:30 BlockGuardServ)."""

    def __init__(self, server):
        self.server = server

    def __enter__(self):
        prog = default_main_program()
        self.block = prog.create_block()
        self.server._block = self.block
        return self.block

    def __exit__(self, exc_type, exc_val, exc_tb):
        prog = default_main_program()
        prog.rollback()
        if exc_type is None:
            self.server.complete_op()
        return False


class ListenAndServ(object):
    """Reference layers/io.py:102. Records an optimize block and an
    endpoint; a later in-process `Send` to that endpoint executes the
    block's semantics (which, under the fused executor, happens by the
    ops being traced into the same step — fan-in barriers are XLA-SPMD's
    job here, not a gRPC loop's)."""

    def __init__(self, endpoint, inputs=None, fan_in=1, optimizer_mode=True):
        self.endpoint = endpoint
        self.inputs = list(inputs or [])
        self.fan_in = fan_in
        self.optimizer_mode = optimizer_mode
        self._block = None
        self._params_grads = None  # captured by complete_op

    def do(self):
        return BlockGuardServ(self)

    def get_params_and_grads(self):
        if self._params_grads is not None:
            return self._params_grads
        params, grads = [], []
        if self._block is None:
            return params, grads
        for op in self._block.ops:
            if self.optimizer_mode:
                if "Param" in op.inputs and "Grad" in op.inputs:
                    params.append(op.inputs["Param"][0])
                    grads.append(op.inputs["Grad"][0])
            else:
                # reference layers/io.py:135-139 simple recv mode: every
                # input var lands in BOTH lists (faithfully mirrored)
                for names in op.inputs.values():
                    params.extend(names)
                    grads.extend(names)
        return params, grads

    def complete_op(self):
        # single-process semantics: splice the optimize block's ops into
        # the parent block in place (they run where the reference's
        # pserver would run them after fan-in; with SPMD data-parallel
        # the gradient arriving here is already the global sum)
        self._params_grads = self.get_params_and_grads()
        prog = default_main_program()
        parent = prog.global_block()
        for op in self._block.ops:
            parent.ops.append(op)
        for name, var in self._block.vars.items():
            parent.vars.setdefault(name, var)
        self._block.ops = []
        _SERV_REGISTRY[self.endpoint] = self


def Send(endpoints, send_vars, get_vars):
    """Reference layers/io.py:173. In-process: validates the endpoints
    against registered ListenAndServ instances; the data movement the
    reference does over gRPC is the executor's job here (variables
    already live in the scope the spliced optimize block reads)."""
    assert isinstance(send_vars, list)
    assert isinstance(get_vars, list)
    epmap = endpoints.split(",")
    unknown = [e for e in set(epmap) if e not in _SERV_REGISTRY]
    if unknown and _SERV_REGISTRY:
        raise ValueError(
            "Send to unregistered endpoint(s) %r; in this framework "
            "cross-process parameter service is the SPMD mesh + "
            "coordinator (distributed/coordinator.py), and ListenAndServ/"
            "Send shims only pair up in-process" % unknown
        )
    return get_vars


__all__ += ["BlockGuardServ", "ListenAndServ", "Send"]
