"""Data-entry layers (reference layers/io.py: data:24)."""

from __future__ import annotations

from ..core.program import default_main_program, default_startup_program

__all__ = ["data"]


def data(
    name,
    shape,
    append_batch_size=True,
    dtype="float32",
    lod_level=0,
    type=None,
    stop_gradient=True,
    **kwargs,
):
    """Declare a feed slot. With append_batch_size, -1 is prepended as the
    batch dim (reference layers/io.py data)."""
    helper_shape = list(shape)
    if append_batch_size:
        helper_shape = [-1] + helper_shape
    main = kwargs.get("main_program") or default_main_program()
    var = main.global_block().create_var(
        name=name,
        shape=helper_shape,
        dtype=dtype,
        lod_level=lod_level,
        stop_gradient=stop_gradient,
        is_data=True,
    )
    return var
