"""Detection layers (reference python/paddle/v2/fluid/layers/detection.py
detection_output:23, plus thin wrappers over the detection op kernels)."""

from __future__ import annotations

from ..layer_helper import LayerHelper

__all__ = [
    "detection_output",
    "prior_box",
    "box_coder",
    "bipartite_match",
    "multiclass_nms",
]


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", name=None):
    helper = LayerHelper("box_coder", **locals())
    output_box = helper.create_tmp_variable(dtype=target_box.dtype)
    helper.append_op(
        type="box_coder",
        inputs={
            "PriorBox": [prior_box],
            "PriorBoxVar": [prior_box_var],
            "TargetBox": [target_box],
        },
        outputs={"OutputBox": [output_box]},
        attrs={"code_type": code_type},
    )
    return output_box


def multiclass_nms(scores, bboxes, background_label=0, score_threshold=0.01,
                   nms_top_k=400, nms_threshold=0.3, keep_top_k=200,
                   nms_eta=1.0, name=None):
    helper = LayerHelper("multiclass_nms", **locals())
    out = helper.create_tmp_variable(dtype=bboxes.dtype)
    out.lod_level = 1
    helper.append_op(
        type="multiclass_nms",
        inputs={"Scores": [scores], "BBoxes": [bboxes]},
        outputs={"Out": [out]},
        attrs={
            "background_label": background_label,
            "score_threshold": score_threshold,
            "nms_top_k": nms_top_k,
            "nms_threshold": nms_threshold,
            "keep_top_k": keep_top_k,
            "nms_eta": nms_eta,
        },
    )
    return out


def detection_output(scores, loc, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0):
    """Decode predicted offsets against priors, then multiclass NMS
    (reference detection.py:23). Output rows are
    [label, confidence, xmin, ymin, xmax, ymax], padded per image with -1
    rows to keep_top_k; per-image valid counts ride the LoD side-band."""
    decoded = box_coder(
        prior_box=prior_box,
        prior_box_var=prior_box_var,
        target_box=loc,
        code_type="decode_center_size",
    )
    return multiclass_nms(
        scores=scores,
        bboxes=decoded,
        background_label=background_label,
        score_threshold=score_threshold,
        nms_top_k=nms_top_k,
        nms_threshold=nms_threshold,
        keep_top_k=keep_top_k,
        nms_eta=nms_eta,
    )


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=None,
              variance=None, flip=False, clip=False, step_w=0.0, step_h=0.0,
              offset=0.5, name=None):
    helper = LayerHelper("prior_box", **locals())
    # static output shape [H, W, P, 4]: P follows the kernel's anchor
    # count — per min_size: {1} u aspects(x2 if flip), plus ONE
    # sqrt(min*max) box when max_sizes are given (kernel pairs them
    # per min_size)
    n_ar = 1 + len(aspect_ratios or []) * (2 if flip else 1)
    n_priors = len(min_sizes) * (n_ar + (1 if max_sizes else 0))
    h = input.shape[2] if input.shape and len(input.shape) == 4 else None
    w = input.shape[3] if input.shape and len(input.shape) == 4 else None
    out_shape = (
        (int(h), int(w), n_priors, 4) if h and w else None
    )
    boxes = helper.create_tmp_variable(dtype=input.dtype, shape=out_shape)
    variances = helper.create_tmp_variable(dtype=input.dtype, shape=out_shape)
    helper.append_op(
        type="prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [variances]},
        attrs={
            "min_sizes": list(min_sizes),
            "max_sizes": list(max_sizes or []),
            "aspect_ratios": list(aspect_ratios or []),
            "variances": list(variance or [0.1, 0.1, 0.2, 0.2]),
            "flip": flip,
            "clip": clip,
            "step_w": step_w,
            "step_h": step_h,
            "offset": offset,
        },
    )
    return boxes, variances


def bipartite_match(dist_matrix, name=None):
    helper = LayerHelper("bipartite_match", **locals())
    match_indices = helper.create_tmp_variable(dtype="int32")
    match_dist = helper.create_tmp_variable(dtype=dist_matrix.dtype)
    helper.append_op(
        type="bipartite_match",
        inputs={"DistMat": [dist_matrix]},
        outputs={
            "ColToRowMatchIndices": [match_indices],
            "ColToRowMatchDist": [match_dist],
        },
    )
    return match_indices, match_dist


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var, overlap_threshold=0.5, neg_pos_ratio=3.0,
             neg_overlap=0.5, background_label=0, name=None, **kwargs):
    """SSD MultiBox training loss (legacy gserver MultiBoxLossLayer.cpp).
    location [N,P,4], confidence [N,P,C], gt_box packed [G,4] with a LoD
    mapping boxes to images, gt_label packed [G,1]. Returns a per-image
    cost [N, 1]."""
    helper = LayerHelper("ssd_multibox_loss", **locals())
    out = helper.create_tmp_variable(dtype=location.dtype)
    helper.append_op(
        type="ssd_multibox_loss",
        inputs={
            "Loc": [location], "Conf": [confidence],
            "GTBox": [gt_box], "GTLabel": [gt_label],
            "PriorBox": [prior_box], "PriorVar": [prior_box_var],
        },
        outputs={"Out": [out]},
        attrs={
            "overlap_threshold": overlap_threshold,
            "neg_pos_ratio": neg_pos_ratio,
            "neg_overlap": neg_overlap,
            "background_id": background_label,
        },
    )
    return out


__all__.append("ssd_loss")
