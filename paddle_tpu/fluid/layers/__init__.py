from . import control_flow, detection, io, nn, ops, tensor
from .control_flow import *  # noqa: F401,F403
from .detection import *  # noqa: F401,F403
from .io import *  # noqa: F401,F403
from .nn import *  # noqa: F401,F403
from .ops import *  # noqa: F401,F403
from .tensor import *  # noqa: F401,F403

__all__ = []
__all__ += control_flow.__all__
__all__ += detection.__all__
__all__ += io.__all__
__all__ += nn.__all__
__all__ += ops.__all__
__all__ += tensor.__all__
