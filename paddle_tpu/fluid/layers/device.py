"""Device-placement layers (reference python/paddle/v2/fluid/layers/
device.py). `get_places` itself lives with the ParallelDo machinery in
control_flow.py; this module keeps the reference's module path importable.
"""

from .control_flow import get_places

__all__ = ["get_places"]
