"""Operator sugar on Variable (reference layers/math_op_patch.py)."""

from __future__ import annotations

import numpy as np

from ..core.program import Variable, unique_name


def _scalar_to_var(block, value, dtype):
    var = block.create_var(
        name=unique_name("_scalar_const"), shape=(1,), dtype=dtype
    )
    block.append_op(
        type="fill_constant",
        outputs={"Out": [var]},
        attrs={"shape": (1,), "dtype": dtype, "value": float(value)},
    )
    return var


def binary(x: Variable, other, op_type: str, reverse: bool = False) -> Variable:
    block = x.block
    if isinstance(other, Variable):
        y = other
    elif np.isscalar(other):
        y = _scalar_to_var(block, other, x.dtype)
    else:
        raise TypeError("cannot combine Variable with %r" % (other,))
    lhs, rhs = (y, x) if reverse else (x, y)
    out_dtype = "bool" if op_type in (
        "less_than", "less_equal", "greater_than", "greater_equal", "equal", "not_equal"
    ) else x.dtype
    out = block.create_var(name=unique_name("_binary_out"), dtype=out_dtype)
    block.append_op(
        type=op_type,
        inputs={"X": [lhs], "Y": [rhs]},
        outputs={"Out": [out]},
        attrs={"axis": -1},
    )
    return out
