"""Neural-network layer functions.

API parity with reference python/paddle/v2/fluid/layers/nn.py (fc:72,
embedding:193, conv2d:1136, pool2d:1432, batch_norm:1481, dropout:851,
cross_entropy:897, accuracy:1020, sequence_* family, matmul:2386, ...).
Each function appends ops to the default main program; the executor lowers
the whole block to one XLA computation, so layer granularity has no runtime
cost on TPU.
"""

from __future__ import annotations

import numpy as np

from ..core.program import Variable
from ..initializer import Constant, Normal, Xavier
from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr
from . import tensor as tensor_layers

__all__ = [
    "fc",
    "embedding",
    "sampling_id",
    "bilinear_interp",
    "conv_shift",
    "sequence_context",
    "slice",
    "equal",
    "conv2d",
    "conv2d_transpose",
    "pool2d",
    "batch_norm",
    "layer_norm",
    "dropout",
    "cross_entropy",
    "square_error_cost",
    "accuracy",
    "softmax_with_cross_entropy",
    "sigmoid_cross_entropy_with_logits",
    "smooth_l1",
    "chunk_eval",
    "linear_chain_crf",
    "crf_decoding",
    "warpctc",
    "edit_distance",
    "nce",
    "hsigmoid",
    "sequence_erase",
    "precision_recall",
    "auc",
    "topk",
    "matmul",
    "reduce_sum",
    "reduce_mean",
    "reduce_max",
    "reduce_min",
    "reduce_prod",
    "split",
    "l2_normalize",
    "transpose",
    "reshape",
    "lrn",
    "cos_sim",
    "dropout",
    "one_hot",
    "dynamic_lstm",
    "dynamic_gru",
    "lstm_unit",
    "gru_unit",
    "sequence_conv",
    "sequence_pool",
    "sequence_first_step",
    "sequence_last_step",
    "sequence_softmax",
    "sequence_expand",
    "sequence_reshape",
    "sequence_slice",
    "reverse",
    "im2sequence",
    "flash_attention",
    "row_conv",
    "multiplex",
    "maxout",
    "expand",
    "pad",
    "gather",
]


def fc(
    input,
    size,
    num_flatten_dims=1,
    param_attr=None,
    bias_attr=None,
    use_mkldnn=False,
    act=None,
    is_test=False,
    name=None,
    **kwargs,
):
    """Fully connected (reference layers/nn.py:72): per-input weight mul,
    summed, plus bias, then activation."""
    helper = LayerHelper("fc", **locals())
    dtype = helper.input_dtype()

    mul_results = []
    for input_var, param_attr in helper.iter_inputs_and_params():
        input_shape = input_var.shape
        param_shape = [
            int(np.prod(input_shape[num_flatten_dims:])),
            size,
        ]
        w = helper.create_parameter(
            attr=param_attr, shape=param_shape, dtype=dtype, is_bias=False
        )
        tmp = helper.create_tmp_variable(dtype)
        helper.append_op(
            type="mul",
            inputs={"X": [input_var], "Y": [w]},
            outputs={"Out": [tmp]},
            attrs={"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1},
        )
        mul_results.append(tmp)

    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_tmp_variable(dtype)
        helper.append_op(
            type="sum", inputs={"X": mul_results}, outputs={"Out": [pre_bias]}
        )
    pre_activation = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims)
    return helper.append_activation(pre_activation)


def embedding(
    input,
    size,
    is_sparse=False,
    padding_idx=None,
    param_attr=None,
    dtype="float32",
    **kwargs,
):
    """Lookup-table layer (reference nn.py:193).

    `is_sparse=True` selects the SelectedRows sparse-gradient path
    (reference framework/selected_rows.h + the SelectedRows branches of
    sgd/adagrad/adam ops): the backward produces a (rows, values) pair
    sized by the batch's lookup count, and the optimizer applies a
    row-scatter update — work proportional to touched rows, not vocab
    size. The sparse path engages when the table is read only by sparse
    lookups over fed ids and its gradient feeds a sparse-capable
    optimizer (sgd/momentum/adagrad/adam, no regularizer/clip on this
    param); otherwise lowering falls back to the exact dense gradient
    (core/lowering.py:_find_sparse_sites). Moment-tracking optimizers
    update touched rows lazily, matching the reference's sparse
    branches."""
    helper = LayerHelper("embedding", **locals())
    w = helper.create_parameter(
        attr=helper.param_attr, shape=size, dtype=dtype, is_bias=False
    )
    tmp = helper.create_tmp_variable(dtype)
    padding_idx = -1 if padding_idx is None else (
        padding_idx if padding_idx >= 0 else size[0] + padding_idx
    )
    helper.append_op(
        type="lookup_table",
        inputs={"Ids": [input], "W": [w]},
        outputs={"Out": [tmp]},
        attrs={"is_sparse": is_sparse, "padding_idx": padding_idx},
    )
    return tmp


def conv2d(
    input,
    num_filters,
    filter_size,
    stride=1,
    padding=0,
    dilation=1,
    groups=None,
    param_attr=None,
    bias_attr=None,
    use_cudnn=True,
    use_mkldnn=False,
    act=None,
    name=None,
    **kwargs,
):
    """2-D convolution, NCHW (reference nn.py:1136). use_cudnn/use_mkldnn
    are accepted and ignored: on TPU the MXU path is always taken."""
    helper = LayerHelper("conv2d", **locals())
    dtype = helper.input_dtype()
    num_channels = input.shape[1]
    groups = groups or 1
    if num_channels % groups != 0:
        raise ValueError("num_channels must be divisible by groups")

    def _pair(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v]

    filter_size = _pair(filter_size)
    stride = _pair(stride)
    padding = _pair(padding)
    dilation = _pair(dilation)

    filter_shape = [num_filters, num_channels // groups] + filter_size
    fan_in = (num_channels // groups) * filter_size[0] * filter_size[1]
    std = (2.0 / fan_in) ** 0.5
    w = helper.create_parameter(
        attr=helper.param_attr,
        shape=filter_shape,
        dtype=dtype,
        default_initializer=Normal(0.0, std, 0),
    )
    pre_bias = helper.create_tmp_variable(dtype)
    helper.append_op(
        type="conv2d",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={
            "strides": stride,
            "paddings": padding,
            "dilations": dilation,
            "groups": groups,
        },
    )
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv2d_transpose(
    input,
    num_filters,
    output_size=None,
    filter_size=None,
    padding=0,
    stride=1,
    dilation=1,
    param_attr=None,
    bias_attr=None,
    use_cudnn=True,
    act=None,
    name=None,
    **kwargs,
):
    helper = LayerHelper("conv2d_transpose", **locals())
    dtype = helper.input_dtype()
    num_channels = input.shape[1]

    def _pair(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v]

    stride = _pair(stride)
    padding = _pair(padding)
    dilation = _pair(dilation)
    if filter_size is None:
        if output_size is None:
            raise ValueError("filter_size or output_size must be given")
        output_size = _pair(output_size)
        h_in, w_in = input.shape[2], input.shape[3]
        filter_size = [
            (output_size[0] - (h_in - 1) * stride[0] + 2 * padding[0] - 1) // dilation[0] + 1,
            (output_size[1] - (w_in - 1) * stride[1] + 2 * padding[1] - 1) // dilation[1] + 1,
        ]
    else:
        filter_size = _pair(filter_size)

    filter_shape = [num_channels, num_filters] + filter_size  # IOHW
    w = helper.create_parameter(
        attr=helper.param_attr, shape=filter_shape, dtype=dtype
    )
    pre_bias = helper.create_tmp_variable(dtype)
    helper.append_op(
        type="conv2d_transpose",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={"strides": stride, "paddings": padding, "dilations": dilation},
    )
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def pool2d(
    input,
    pool_size=-1,
    pool_type="max",
    pool_stride=1,
    pool_padding=0,
    global_pooling=False,
    use_cudnn=True,
    ceil_mode=False,
    use_mkldnn=False,
    name=None,
    **kwargs,
):
    if pool_type not in ["max", "avg"]:
        raise ValueError("pool_type must be 'max' or 'avg', got %r" % pool_type)
    if not global_pooling:
        sizes = pool_size if isinstance(pool_size, (list, tuple)) else [pool_size]
        if any(s <= 0 for s in sizes):
            raise ValueError(
                "pool_size must be positive unless global_pooling=True, got %r"
                % (pool_size,)
            )
    helper = LayerHelper("pool2d", **locals())
    dtype = helper.input_dtype()
    out = helper.create_tmp_variable(dtype)

    def _pair(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v]

    helper.append_op(
        type="pool2d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "pooling_type": pool_type,
            "ksize": _pair(pool_size),
            "global_pooling": global_pooling,
            "strides": _pair(pool_stride),
            "paddings": _pair(pool_padding),
            "ceil_mode": ceil_mode,
        },
    )
    return out


def batch_norm(
    input,
    act=None,
    is_test=False,
    momentum=0.9,
    epsilon=1e-05,
    param_attr=None,
    bias_attr=None,
    data_layout="NCHW",
    in_place=False,
    use_mkldnn=False,
    name=None,
    moving_mean_name=None,
    moving_variance_name=None,
    **kwargs,
):
    """Batch normalization (reference nn.py:1481). Running mean/variance are
    persistable non-trainable vars updated inside the same fused step."""
    helper = LayerHelper("batch_norm", **locals())
    dtype = helper.input_dtype()
    input_shape = input.shape
    if data_layout == "NCHW":
        channel_num = input_shape[1]
    else:
        channel_num = input_shape[-1]
    param_shape = [channel_num]

    scale = helper.create_parameter(
        attr=helper.param_attr,
        shape=param_shape,
        dtype=dtype,
        default_initializer=Constant(1.0),
    )
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=param_shape, dtype=dtype, is_bias=True
    )

    mean = helper.create_global_variable(
        name=moving_mean_name,
        dtype=dtype,
        shape=param_shape,
        persistable=True,
    )
    helper.set_variable_initializer(mean, Constant(0.0))
    variance = helper.create_global_variable(
        name=moving_variance_name,
        dtype=dtype,
        shape=param_shape,
        persistable=True,
    )
    helper.set_variable_initializer(variance, Constant(1.0))

    saved_mean = helper.create_tmp_variable(dtype, stop_gradient=True)
    saved_variance = helper.create_tmp_variable(dtype, stop_gradient=True)
    out = helper.create_tmp_variable(dtype)

    helper.append_op(
        type="batch_norm",
        inputs={
            "X": [input],
            "Scale": [scale],
            "Bias": [bias],
            "Mean": [mean],
            "Variance": [variance],
        },
        outputs={
            "Y": [out],
            "MeanOut": [mean],
            "VarianceOut": [variance],
            "SavedMean": [saved_mean],
            "SavedVariance": [saved_variance],
        },
        attrs={
            "momentum": momentum,
            "epsilon": epsilon,
            "is_test": is_test,
            "data_layout": data_layout,
        },
    )
    return helper.append_activation(out)


def layer_norm(
    input,
    scale=True,
    shift=True,
    begin_norm_axis=1,
    epsilon=1e-05,
    param_attr=None,
    bias_attr=None,
    act=None,
    name=None,
    **kwargs,
):
    helper = LayerHelper("layer_norm", **locals())
    dtype = helper.input_dtype()
    input_shape = input.shape
    param_shape = [int(np.prod(input_shape[begin_norm_axis:]))]
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(
            attr=helper.param_attr,
            shape=param_shape,
            dtype=dtype,
            default_initializer=Constant(1.0),
        )
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(
            attr=helper.bias_attr, shape=param_shape, dtype=dtype, is_bias=True
        )
        inputs["Bias"] = [b]
    mean_out = helper.create_tmp_variable(dtype, stop_gradient=True)
    variance_out = helper.create_tmp_variable(dtype, stop_gradient=True)
    out = helper.create_tmp_variable(dtype)
    helper.append_op(
        type="layer_norm",
        inputs=inputs,
        outputs={"Y": [out], "Mean": [mean_out], "Variance": [variance_out]},
        attrs={"epsilon": epsilon, "begin_norm_axis": begin_norm_axis},
    )
    return helper.append_activation(out)


def dropout(x, dropout_prob, is_test=False, seed=None, **kwargs):
    helper = LayerHelper("dropout", **locals())
    out = helper.create_tmp_variable(dtype=x.dtype)
    mask = helper.create_tmp_variable(dtype=x.dtype, stop_gradient=True)
    helper.append_op(
        type="dropout",
        inputs={"X": [x]},
        outputs={"Out": [out], "Mask": [mask]},
        attrs={"dropout_prob": dropout_prob, "is_test": is_test, "seed": seed or 0},
    )
    return out


def cross_entropy(input, label, **kwargs):
    helper = LayerHelper("cross_entropy", **kwargs)
    out = helper.create_tmp_variable(dtype=input.dtype)
    helper.append_op(
        type="cross_entropy",
        inputs={"X": [input], "Label": [label]},
        outputs={"Y": [out]},
        attrs={"soft_label": kwargs.get("soft_label", False)},
    )
    return out


def square_error_cost(input, label, **kwargs):
    helper = LayerHelper("square_error_cost", **kwargs)
    minus_out = helper.create_tmp_variable(dtype=input.dtype)
    helper.append_op(
        type="elementwise_sub",
        inputs={"X": [input], "Y": [label]},
        outputs={"Out": [minus_out]},
    )
    square_out = helper.create_tmp_variable(dtype=input.dtype)
    helper.append_op(
        type="square", inputs={"X": [minus_out]}, outputs={"Out": [square_out]}
    )
    return square_out


def softmax_with_cross_entropy(logits, label, soft_label=False, **kwargs):
    helper = LayerHelper("softmax_with_cross_entropy", **kwargs)
    softmax = helper.create_tmp_variable(dtype=logits.dtype)
    loss = helper.create_tmp_variable(dtype=logits.dtype)
    helper.append_op(
        type="softmax_with_cross_entropy",
        inputs={"Logits": [logits], "Label": [label]},
        outputs={"Softmax": [softmax], "Loss": [loss]},
        attrs={"soft_label": soft_label},
    )
    return loss


def sigmoid_cross_entropy_with_logits(x, label, **kwargs):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", **kwargs)
    out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(
        type="sigmoid_cross_entropy_with_logits",
        inputs={"X": [x], "Label": [label]},
        outputs={"Out": [out]},
    )
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None, **kwargs):
    helper = LayerHelper("smooth_l1", **kwargs)
    diff = helper.create_tmp_variable(dtype=x.dtype)
    loss = helper.create_tmp_variable(dtype=x.dtype)
    inputs = {"X": [x], "Y": [y]}
    if inside_weight is not None:
        inputs["InsideWeight"] = [inside_weight]
    if outside_weight is not None:
        inputs["OutsideWeight"] = [outside_weight]
    helper.append_op(
        type="smooth_l1_loss",
        inputs=inputs,
        outputs={"Diff": [diff], "Out": [loss]},
        attrs={"sigma": sigma or 1.0},
    )
    return loss


def accuracy(input, label, k=1, correct=None, total=None, **kwargs):
    """Top-k accuracy (reference nn.py:1020): top_k + accuracy ops."""
    helper = LayerHelper("accuracy", **kwargs)
    topk_out = helper.create_tmp_variable(dtype=input.dtype)
    topk_indices = helper.create_tmp_variable(dtype="int64")
    helper.append_op(
        type="top_k",
        inputs={"X": [input]},
        outputs={"Out": [topk_out], "Indices": [topk_indices]},
        attrs={"k": k},
    )
    acc_out = helper.create_tmp_variable(dtype="float32")
    if correct is None:
        correct = helper.create_tmp_variable(dtype="int64")
    if total is None:
        total = helper.create_tmp_variable(dtype="int64")
    helper.append_op(
        type="accuracy",
        inputs={"Out": [topk_out], "Indices": [topk_indices], "Label": [label]},
        outputs={"Accuracy": [acc_out], "Correct": [correct], "Total": [total]},
    )
    return acc_out


def auc(input, label, curve="ROC", num_thresholds=200, **kwargs):
    helper = LayerHelper("auc", **kwargs)
    auc_out = helper.create_tmp_variable(dtype="float32")
    helper.append_op(
        type="auc",
        inputs={"Out": [input], "Label": [label]},
        outputs={"AUC": [auc_out]},
        attrs={"curve": curve, "num_thresholds": num_thresholds},
    )
    return auc_out


def chunk_eval(input, label, chunk_scheme, num_chunk_types, excluded_chunk_types=None, **kwargs):
    """Chunk-level precision/recall/F1 (reference layers/nn.py chunk_eval ->
    operators/chunk_eval_op; vectorised kernel in core/kernels_crf.py)."""
    helper = LayerHelper("chunk_eval", **kwargs)
    precision = helper.create_tmp_variable(dtype="float32")
    recall = helper.create_tmp_variable(dtype="float32")
    f1_score = helper.create_tmp_variable(dtype="float32")
    num_infer = helper.create_tmp_variable(dtype="int64")
    num_label = helper.create_tmp_variable(dtype="int64")
    num_correct = helper.create_tmp_variable(dtype="int64")
    helper.append_op(
        type="chunk_eval",
        inputs={"Inference": [input], "Label": [label]},
        outputs={
            "Precision": [precision],
            "Recall": [recall],
            "F1-Score": [f1_score],
            "NumInferChunks": [num_infer],
            "NumLabelChunks": [num_label],
            "NumCorrectChunks": [num_correct],
        },
        attrs={
            "chunk_scheme": chunk_scheme,
            "num_chunk_types": num_chunk_types,
            "excluded_chunk_types": excluded_chunk_types or [],
        },
    )
    return precision, recall, f1_score, num_infer, num_label, num_correct


def linear_chain_crf(input, label, param_attr=None):
    """CRF negative log-likelihood over a ragged batch (reference
    layers/nn.py linear_chain_crf -> operators/linear_chain_crf_op).
    Transition parameter is [size+2, size]: start row, end row, then the
    [size, size] transition matrix."""
    helper = LayerHelper("linear_chain_crf", **locals())
    size = input.shape[1]
    transition = helper.create_parameter(
        attr=helper.param_attr, shape=[size + 2, size], dtype=helper.input_dtype()
    )
    alpha = helper.create_tmp_variable(dtype=helper.input_dtype())
    emission_exps = helper.create_tmp_variable(dtype=helper.input_dtype())
    transition_exps = helper.create_tmp_variable(dtype=helper.input_dtype())
    log_likelihood = helper.create_tmp_variable(dtype=helper.input_dtype())
    helper.append_op(
        type="linear_chain_crf",
        inputs={"Emission": [input], "Transition": [transition], "Label": [label]},
        outputs={
            "Alpha": [alpha],
            "EmissionExps": [emission_exps],
            "TransitionExps": [transition_exps],
            "LogLikelihood": [log_likelihood],
        },
    )
    return log_likelihood


def crf_decoding(input, param_attr, label=None):
    """Viterbi decode with the trained CRF transitions (reference
    layers/nn.py crf_decoding -> operators/crf_decoding_op). With `label`,
    returns per-token correctness instead of the path."""
    helper = LayerHelper("crf_decoding", **locals())
    transition = helper.get_parameter(param_attr.name)
    viterbi_path = helper.create_tmp_variable(dtype="int64")
    inputs = {"Emission": [input], "Transition": [transition]}
    if label is not None:
        inputs["Label"] = [label]
    helper.append_op(
        type="crf_decoding",
        inputs=inputs,
        outputs={"ViterbiPath": [viterbi_path]},
    )
    return viterbi_path


def topk(input, k, **kwargs):
    helper = LayerHelper("top_k", **kwargs)
    values = helper.create_tmp_variable(dtype=input.dtype)
    indices = helper.create_tmp_variable(dtype="int64")
    helper.append_op(
        type="top_k",
        inputs={"X": [input]},
        outputs={"Out": [values], "Indices": [indices]},
        attrs={"k": k},
    )
    return values, indices


def matmul(x, y, transpose_x=False, transpose_y=False, name=None, **kwargs):
    helper = LayerHelper("matmul", **locals())
    out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(
        type="matmul",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"transpose_X": transpose_x, "transpose_Y": transpose_y},
    )
    return out


def _reduce(op_type, input, dim=None, keep_dim=False, name=None):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_tmp_variable(dtype=input.dtype)
    attrs = {"keep_dim": keep_dim}
    if dim is None:
        attrs["reduce_all"] = True
        attrs["dim"] = 0
    else:
        attrs["reduce_all"] = False
        attrs["dim"] = dim
    helper.append_op(
        type=op_type, inputs={"X": [input]}, outputs={"Out": [out]}, attrs=attrs
    )
    return out


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_sum", input, dim, keep_dim, name)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_mean", input, dim, keep_dim, name)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_max", input, dim, keep_dim, name)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_min", input, dim, keep_dim, name)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_prod", input, dim, keep_dim, name)


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", **locals())
    if isinstance(num_or_sections, int):
        num = num_or_sections
        attrs = {"num": num, "sections": [], "axis": dim}
    else:
        num = len(num_or_sections)
        attrs = {"num": 0, "sections": list(num_or_sections), "axis": dim}
    outs = [helper.create_tmp_variable(dtype=input.dtype) for _ in range(num)]
    helper.append_op(
        type="split", inputs={"X": [input]}, outputs={"Out": outs}, attrs=attrs
    )
    return outs


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", **locals())
    out = helper.create_tmp_variable(dtype=x.dtype)
    norm = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(
        type="l2_normalize",
        inputs={"X": [x]},
        outputs={"Out": [out], "Norm": [norm]},
        attrs={"axis": axis, "epsilon": epsilon},
    )
    return out


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose", **locals())
    out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(
        type="transpose",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"axis": list(perm)},
    )
    return out


def reshape(x, shape, actual_shape=None, act=None, inplace=True, name=None):
    helper = LayerHelper("reshape", **locals())
    out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(
        type="reshape",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"shape": list(shape)},
    )
    return helper.append_activation(out)


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", **locals())
    out = helper.create_tmp_variable(dtype=input.dtype)
    mid = helper.create_tmp_variable(dtype=input.dtype, stop_gradient=True)
    helper.append_op(
        type="lrn",
        inputs={"X": [input]},
        outputs={"Out": [out], "MidOut": [mid]},
        attrs={"n": n, "k": k, "alpha": alpha, "beta": beta},
    )
    return out


def cos_sim(X, Y, **kwargs):
    helper = LayerHelper("cos_sim", **kwargs)
    out = helper.create_tmp_variable(dtype=X.dtype)
    xnorm = helper.create_tmp_variable(dtype=X.dtype)
    ynorm = helper.create_tmp_variable(dtype=X.dtype)
    helper.append_op(
        type="cos_sim",
        inputs={"X": [X], "Y": [Y]},
        outputs={"Out": [out], "XNorm": [xnorm], "YNorm": [ynorm]},
    )
    return out


def one_hot(input, depth, **kwargs):
    helper = LayerHelper("one_hot", **kwargs)
    out = helper.create_tmp_variable(dtype="float32")
    helper.append_op(
        type="one_hot",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"depth": depth},
    )
    return out


# --- sequence layers ----------------------------------------------------

def dynamic_lstm(
    input,
    size,
    param_attr=None,
    bias_attr=None,
    use_peepholes=True,
    is_reverse=False,
    gate_activation="sigmoid",
    cell_activation="tanh",
    candidate_activation="tanh",
    dtype="float32",
    name=None,
    **kwargs,
):
    """LSTM over a ragged batch (reference nn.py:252 dynamic_lstm,
    operators/lstm_op). `size` is 4*hidden (paddle convention); `input`
    must already be the 4H-wide projection (an fc ahead of this layer).
    Returns (hidden, cell), both LoD-shaped like the input."""
    helper = LayerHelper("dynamic_lstm", name=name, **kwargs)
    hidden_size = size // 4
    weight = helper.create_parameter(
        attr=ParamAttr.to_attr(param_attr),
        shape=[hidden_size, 4 * hidden_size],
        dtype=dtype,
    )
    bias_size = [1, 7 * hidden_size] if use_peepholes else [1, 4 * hidden_size]
    bias = helper.create_parameter(
        attr=ParamAttr.to_attr(bias_attr),
        shape=bias_size,
        dtype=dtype,
        is_bias=True,
    )
    hidden = helper.create_tmp_variable(dtype, shape=(-1, hidden_size), lod_level=1)
    cell = helper.create_tmp_variable(dtype, shape=(-1, hidden_size), lod_level=1)
    helper.append_op(
        type="lstm",
        inputs={"Input": [input], "Weight": [weight], "Bias": [bias]},
        outputs={"Hidden": [hidden], "Cell": [cell]},
        attrs={
            "use_peepholes": use_peepholes,
            "is_reverse": is_reverse,
            "gate_activation": gate_activation,
            "cell_activation": cell_activation,
            "candidate_activation": candidate_activation,
        },
    )
    return hidden, cell


def dynamic_gru(
    input,
    size,
    param_attr=None,
    bias_attr=None,
    is_reverse=False,
    gate_activation="sigmoid",
    candidate_activation="tanh",
    h_0=None,
    dtype="float32",
    **kwargs,
):
    """GRU over a ragged batch (reference nn.py dynamic_gru, operators/
    gru_op). `size` is the hidden width; `input` must be the 3H-wide
    projection. Returns the LoD-shaped hidden sequence."""
    helper = LayerHelper("dynamic_gru", **kwargs)
    weight = helper.create_parameter(
        attr=ParamAttr.to_attr(param_attr), shape=[size, 3 * size], dtype=dtype
    )
    bias = helper.create_parameter(
        attr=ParamAttr.to_attr(bias_attr), shape=[1, 3 * size], dtype=dtype,
        is_bias=True,
    )
    hidden = helper.create_tmp_variable(dtype, shape=(-1, size), lod_level=1)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    helper.append_op(
        type="gru",
        inputs=inputs,
        outputs={"Hidden": [hidden]},
        attrs={
            "is_reverse": is_reverse,
            "gate_activation": gate_activation,
            "activation": candidate_activation,
        },
    )
    return hidden


def lstm_unit(
    x_t,
    hidden_t_prev,
    cell_t_prev,
    forget_bias=0.0,
    param_attr=None,
    bias_attr=None,
    **kwargs,
):
    """One dense LSTM step (reference nn.py lstm_unit:2194): fc over
    [x_t, h_prev] to 4H gates, then the cell update. Returns (h, c)."""
    helper = LayerHelper("lstm_unit", **kwargs)
    size = cell_t_prev.shape[-1]
    concat_out = tensor_layers.concat(input=[x_t, hidden_t_prev], axis=1)
    fc_out = fc(
        input=concat_out, size=4 * size, param_attr=param_attr,
        bias_attr=bias_attr,
    )
    dtype = x_t.dtype
    c = helper.create_tmp_variable(dtype, shape=(-1, size))
    h = helper.create_tmp_variable(dtype, shape=(-1, size))
    helper.append_op(
        type="lstm_unit",
        inputs={"X": [fc_out], "C_prev": [cell_t_prev]},
        outputs={"C": [c], "H": [h]},
        attrs={"forget_bias": forget_bias},
    )
    return h, c


def gru_unit(
    input,
    hidden,
    size,
    param_attr=None,
    bias_attr=None,
    activation="tanh",
    gate_activation="sigmoid",
    **kwargs,
):
    """One dense GRU step (reference nn.py gru_unit). `size` is 3*hidden
    (paddle convention). Returns (hidden, reset_hidden_prev, gate)."""
    helper = LayerHelper("gru_unit", **kwargs)
    dtype = input.dtype
    H = size // 3
    weight = helper.create_parameter(
        attr=ParamAttr.to_attr(param_attr), shape=[H, 3 * H], dtype=dtype
    )
    bias = helper.create_parameter(
        attr=ParamAttr.to_attr(bias_attr), shape=[1, 3 * H], dtype=dtype,
        is_bias=True,
    )
    gate = helper.create_tmp_variable(dtype, shape=(-1, 3 * H))
    reset_hidden_prev = helper.create_tmp_variable(dtype, shape=(-1, H))
    updated_hidden = helper.create_tmp_variable(dtype, shape=(-1, H))
    helper.append_op(
        type="gru_unit",
        inputs={"Input": [input], "HiddenPrev": [hidden], "Weight": [weight],
                "Bias": [bias]},
        outputs={"Gate": [gate], "ResetHiddenPrev": [reset_hidden_prev],
                 "Hidden": [updated_hidden]},
        attrs={"activation": activation, "gate_activation": gate_activation},
    )
    return updated_hidden, reset_hidden_prev, gate


def sequence_conv(
    input, num_filters, filter_size=3, filter_stride=1, padding=None,
    bias_attr=None, param_attr=None, act=None, **kwargs
):
    """Context-window conv over a packed ragged batch (reference nn.py:1095,
    operators/sequence_conv_op): each token's window of `filter_size`
    neighbours is gathered (zero beyond sequence bounds) and hit with one
    GEMM."""
    helper = LayerHelper("sequence_conv", **kwargs)
    dtype = input.dtype
    filter_shape = [filter_size * input.shape[-1], num_filters]
    filter_param = helper.create_parameter(
        attr=ParamAttr.to_attr(param_attr), shape=filter_shape, dtype=dtype
    )
    pre_bias = helper.create_tmp_variable(dtype, shape=(-1, num_filters), lod_level=1)
    helper.append_op(
        type="sequence_conv",
        inputs={"X": [input], "Filter": [filter_param]},
        outputs={"Out": [pre_bias]},
        attrs={
            "contextStride": filter_stride,
            "contextStart": -int(filter_size // 2),
            "contextLength": filter_size,
        },
    )
    helper.kwargs["bias_attr"] = bias_attr
    helper.kwargs["act"] = act
    pre_act = helper.append_bias_op(pre_bias)
    return helper.append_activation(pre_act)


def sequence_pool(input, pool_type, **kwargs):
    helper = LayerHelper("sequence_pool", input=input, **kwargs)
    dtype = helper.input_dtype()
    pool_out = helper.create_tmp_variable(dtype)
    max_index = helper.create_tmp_variable(dtype="int32", stop_gradient=True)
    helper.append_op(
        type="sequence_pool",
        inputs={"X": [input]},
        outputs={"Out": [pool_out], "MaxIndex": [max_index]},
        attrs={"pooltype": pool_type.upper()},
    )
    return pool_out


def sequence_first_step(input, **kwargs):
    return sequence_pool(input, "first")


def sequence_last_step(input, **kwargs):
    return sequence_pool(input, "last")


def sequence_softmax(x, **kwargs):
    helper = LayerHelper("sequence_softmax", **kwargs)
    out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(
        type="sequence_softmax", inputs={"X": [x]}, outputs={"Out": [out]}
    )
    return out


def sequence_reverse(x, name=None):
    """Reverse each sequence of a LoD tensor in time (kept LoD). Lowers
    reverse recurrent groups: reverse -> forward scan -> reverse."""
    helper = LayerHelper("sequence_reverse", **locals())
    out = helper.create_tmp_variable(dtype=x.dtype, lod_level=1)
    helper.append_op(
        type="sequence_reverse", inputs={"X": [x]}, outputs={"Out": [out]}
    )
    return out


def sequence_expand(x, y, name=None):
    helper = LayerHelper("sequence_expand", **locals())
    out = helper.create_tmp_variable(dtype=x.dtype, lod_level=y.lod_level)
    helper.append_op(
        type="sequence_expand", inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]}
    )
    return out


def sequence_reshape(input, new_dim):
    helper = LayerHelper("sequence_reshape", **locals())
    out = helper.create_tmp_variable(dtype=input.dtype, lod_level=1)
    helper.append_op(
        type="sequence_reshape",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"new_dim": new_dim},
    )
    return out


def flash_attention(q, k, v, causal=False, scale=None, name=None):
    """Fused blockwise attention over [B, T, H, D] inputs (the pallas
    flash kernel; beyond-reference perf surface). Gradients flow through
    the kernel's custom vjp; on CPU it runs in interpret mode so the
    graph is platform-portable."""
    helper = LayerHelper("flash_attention", **locals())
    out = helper.create_tmp_variable(dtype=q.dtype, shape=tuple(q.shape))
    helper.append_op(
        type="flash_attention",
        inputs={"Q": [q], "K": [k], "V": [v]},
        outputs={"Out": [out]},
        attrs={"causal": bool(causal), "scale": scale},
    )
    return out


def im2sequence(input, filter_size=1, stride=1, padding=0, name=None):
    helper = LayerHelper("im2sequence", **locals())

    def _pair(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v]

    padding = padding if isinstance(padding, (list, tuple)) and len(padding) == 4 else _pair(padding) * 2
    out = helper.create_tmp_variable(dtype=input.dtype, lod_level=1)
    helper.append_op(
        type="im2sequence",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "kernels": _pair(filter_size),
            "strides": _pair(stride),
            "paddings": list(padding),
        },
    )
    return out


def row_conv(input, future_context_size, param_attr=None, act=None):
    helper = LayerHelper("row_conv", **locals())
    dtype = helper.input_dtype()
    filter_shape = [future_context_size + 1, input.shape[-1]]
    filter_param = helper.create_parameter(
        attr=helper.param_attr, shape=filter_shape, dtype=dtype
    )
    out = helper.create_tmp_variable(dtype)
    helper.append_op(
        type="row_conv",
        inputs={"X": [input], "Filter": [filter_param]},
        outputs={"Out": [out]},
    )
    return helper.append_activation(out)


def multiplex(inputs, index):
    helper = LayerHelper("multiplex", **locals())
    out = helper.create_tmp_variable(dtype=inputs[0].dtype)
    helper.append_op(
        type="multiplex",
        inputs={"X": inputs, "Ids": [index]},
        outputs={"Out": [out]},
    )
    return out


def maxout(x, groups, name=None):
    helper = LayerHelper("maxout", **locals())
    out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(
        type="maxout",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"groups": groups},
    )
    return out


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", **locals())
    out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(
        type="expand",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"expand_times": list(expand_times)},
    )
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", **locals())
    out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(
        type="pad",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"paddings": list(paddings), "pad_value": float(pad_value)},
    )
    return out


def gather(input, index):
    helper = LayerHelper("gather", **locals())
    out = helper.create_tmp_variable(dtype=input.dtype)
    helper.append_op(
        type="gather",
        inputs={"X": [input], "Index": [index]},
        outputs={"Out": [out]},
    )
    return out

def warpctc(input, label, blank=0, norm_by_times=False, **kwargs):
    """CTC loss over ragged logits/labels (reference layers/nn.py:2657 ->
    operators/warpctc_op; TPU-native log-space recursion in
    core/kernels_ctc.py instead of the dynloaded libwarpctc)."""
    helper = LayerHelper("warpctc", **kwargs)
    loss_out = helper.create_tmp_variable(dtype=input.dtype)
    grad_out = helper.create_tmp_variable(dtype=input.dtype)
    helper.append_op(
        type="warpctc",
        inputs={"Logits": [input], "Label": [label]},
        outputs={"WarpCTCGrad": [grad_out], "Loss": [loss_out]},
        attrs={"blank": blank, "norm_by_times": norm_by_times},
    )
    return loss_out


def sequence_erase(input, tokens):
    """Remove the given token values from each sequence (reference
    operators/sequence_erase_op)."""
    helper = LayerHelper("sequence_erase", **locals())
    out = helper.create_tmp_variable(dtype=input.dtype)
    helper.append_op(
        type="sequence_erase",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"tokens": list(tokens)},
    )
    return out


def edit_distance(input, label, normalized=False, ignored_tokens=None,
                  name=None):
    """Levenshtein distance between hypothesis and reference id sequences
    (reference layers/nn.py:2492 -> operators/edit_distance_op)."""
    helper = LayerHelper("edit_distance", **locals())
    if ignored_tokens is not None and len(ignored_tokens) > 0:
        input = sequence_erase(input, ignored_tokens)
        label = sequence_erase(label, ignored_tokens)
    out = helper.create_tmp_variable(dtype="float32")
    seq_num = helper.create_tmp_variable(dtype="int64")
    helper.append_op(
        type="edit_distance",
        inputs={"Hyps": [input], "Refs": [label]},
        outputs={"Out": [out], "SequenceNum": [seq_num]},
        attrs={"normalized": normalized},
    )
    return out, seq_num


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None, neg_distribution=None):
    """Noise-contrastive estimation loss (reference layers/nn.py:2767 ->
    operators/nce_op)."""
    helper = LayerHelper("nce", **locals())
    dim = input.shape[1]
    num_true_class = label.shape[1] if label.shape and len(label.shape) > 1 else 1
    w = helper.create_parameter(
        attr=helper.param_attr, shape=[num_total_classes, dim],
        is_bias=False, dtype=input.dtype,
    )
    b = helper.create_parameter(
        attr=helper.bias_attr, shape=[num_total_classes, 1],
        is_bias=True, dtype=input.dtype,
    )
    cost = helper.create_tmp_variable(dtype=input.dtype)
    sample_logits = helper.create_tmp_variable(dtype=input.dtype)
    sample_labels = helper.create_tmp_variable(dtype=label.dtype)
    num_neg_samples = 10 if num_neg_samples is None else int(num_neg_samples)
    inputs = {"Input": [input], "Label": [label], "Weight": [w], "Bias": [b]}
    if sample_weight is not None:
        inputs["SampleWeight"] = [sample_weight]
    helper.append_op(
        type="nce",
        inputs=inputs,
        outputs={
            "Cost": [cost],
            "SampleLogits": [sample_logits],
            "SampleLabels": [sample_labels],
        },
        attrs={
            "num_total_classes": int(num_total_classes),
            "num_neg_samples": num_neg_samples,
            "neg_distribution": (
                list(neg_distribution) if neg_distribution else None
            ),
        },
    )
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None):
    """Hierarchical sigmoid loss over the complete binary class tree
    (reference operators/hierarchical_sigmoid_op, gserver
    HierarchicalSigmoidLayer)."""
    helper = LayerHelper("hsigmoid", **locals())
    dim = input.shape[1]
    w = helper.create_parameter(
        attr=helper.param_attr, shape=[num_classes - 1, dim],
        is_bias=False, dtype=input.dtype,
    )
    b = helper.create_parameter(
        attr=helper.bias_attr, shape=[num_classes - 1, 1],
        is_bias=True, dtype=input.dtype,
    )
    out = helper.create_tmp_variable(dtype=input.dtype)
    pre_out = helper.create_tmp_variable(dtype=input.dtype)
    helper.append_op(
        type="hierarchical_sigmoid",
        inputs={"X": [input], "W": [w], "Label": [label], "Bias": [b]},
        outputs={"Out": [out], "PreOut": [pre_out]},
        attrs={"num_classes": int(num_classes)},
    )
    return out


def precision_recall(input, label, class_number, max_probs=None,
                     weights=None, states=None, **kwargs):
    """Multi-class precision/recall metrics (reference
    operators/precision_recall_op). `input` is the predicted class-index
    tensor (e.g. topk indices); returns (batch_metrics, accum_metrics,
    accum_states) where metrics = [macro-P, macro-R, macro-F1, micro-P,
    micro-R, micro-F1]."""
    helper = LayerHelper("precision_recall", **kwargs)
    batch_metrics = helper.create_tmp_variable(dtype="float32")
    accum_metrics = helper.create_tmp_variable(dtype="float32")
    accum_states = helper.create_tmp_variable(dtype="float32")
    inputs = {"Indices": [input], "Labels": [label]}
    if max_probs is not None:
        inputs["MaxProbs"] = [max_probs]
    if weights is not None:
        inputs["Weights"] = [weights]
    if states is not None:
        inputs["StatesInfo"] = [states]
    helper.append_op(
        type="precision_recall",
        inputs=inputs,
        outputs={
            "BatchMetrics": [batch_metrics],
            "AccumMetrics": [accum_metrics],
            "AccumStatesInfo": [accum_states],
        },
        attrs={"class_number": int(class_number)},
    )
    return batch_metrics, accum_metrics, accum_states


def sequence_context(input, context_length, context_start=None, name=None,
                     **kwargs):
    """Context-window concatenation without weights (reference
    ContextProjection; the gather half of sequence_conv). Output width is
    context_length * input_width."""
    helper = LayerHelper("sequence_context", name=name, **kwargs)
    width = None
    if input.shape and int(input.shape[-1]) > 0:
        width = int(input.shape[-1]) * int(context_length)
    out = helper.create_tmp_variable(
        dtype=input.dtype, shape=(-1, width) if width else None, lod_level=1
    )
    helper.append_op(
        type="sequence_context",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "context_length": int(context_length),
            "context_start": (
                -(int(context_length) // 2)
                if context_start is None else int(context_start)
            ),
        },
    )
    return out


def slice(input, axes, starts, ends, name=None):
    """Static slice (reference slice_op)."""
    helper = LayerHelper("slice", name=name)
    out = helper.create_tmp_variable(dtype=input.dtype)
    helper.append_op(
        type="slice",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={"axes": list(axes), "starts": list(starts),
               "ends": list(ends)},
    )
    return out


def equal(x, y, name=None, **kwargs):
    """Elementwise x == y -> bool (reference equal op)."""
    helper = LayerHelper("equal", name=name)
    out = helper.create_tmp_variable(dtype="bool")
    out.stop_gradient = True
    helper.append_op(
        type="equal", inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]}
    )
    return out


def sampling_id(x, name=None, **kwargs):
    """Sample a class id per row of a probability matrix (reference
    sampling_id_op)."""
    helper = LayerHelper("sampling_id", name=name)
    out = helper.create_tmp_variable(dtype="int32")
    out.stop_gradient = True
    helper.append_op(
        type="sampling_id", inputs={"X": [x]}, outputs={"Out": [out]}
    )
    return out


def bilinear_interp(input, out_h, out_w, name=None, **kwargs):
    """Bilinear resize on NCHW (reference bilinear_interp_op)."""
    helper = LayerHelper("bilinear_interp", name=name)
    out = helper.create_tmp_variable(dtype=input.dtype)
    helper.append_op(
        type="bilinear_interp",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"out_h": int(out_h), "out_w": int(out_w)},
    )
    return out


def conv_shift(x, y, name=None, **kwargs):
    """Circular convolution of each row of x by the (odd-width) kernel
    row of y (reference conv_shift_op)."""
    helper = LayerHelper("conv_shift", name=name)
    out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(
        type="conv_shift", inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
    )
    return out


def sequence_slice(input, offset, length, name=None, **kwargs):
    """Per-sequence subranges (reference sequence_slice_op): row ranges
    [offset_i, offset_i+length_i) of each sequence, compacted."""
    helper = LayerHelper("sequence_slice", name=name)
    out = helper.create_tmp_variable(dtype=input.dtype, lod_level=1)
    helper.append_op(
        type="sequence_slice",
        inputs={"X": [input], "Offset": [offset], "Length": [length]},
        outputs={"Out": [out]},
    )
    return out


def reverse(x, axis, name=None, **kwargs):
    """Flip along axes (reference reverse_op)."""
    helper = LayerHelper("reverse", name=name)
    out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(
        type="reverse", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"axis": list(axis) if isinstance(axis, (list, tuple))
               else [axis]},
    )
    return out


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, act=None, name=None,
           **kwargs):
    """3-D convolution, NCDHW (reference conv3d kernels under
    operators/conv_op.cc; legacy gserver Conv3DLayer.cpp)."""
    helper = LayerHelper("conv3d", **locals())
    dtype = helper.input_dtype()
    num_channels = input.shape[1]
    groups = groups or 1

    def _triple(v):
        return list(v) if isinstance(v, (list, tuple)) else [v] * 3

    filter_size = _triple(filter_size)
    stride = _triple(stride)
    padding = _triple(padding)
    dilation = _triple(dilation)
    filter_shape = [num_filters, num_channels // groups] + filter_size
    fan_in = (num_channels // groups) * int(np.prod(filter_size))
    w = helper.create_parameter(
        attr=helper.param_attr, shape=filter_shape, dtype=dtype,
        default_initializer=Normal(0.0, (2.0 / fan_in) ** 0.5, 0),
    )
    pre_bias = helper.create_tmp_variable(dtype)
    helper.append_op(
        type="conv3d",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={"strides": stride, "paddings": padding,
               "dilations": dilation, "groups": groups},
    )
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def pool3d(input, pool_size, pool_type="max", pool_stride=1, pool_padding=0,
           global_pooling=False, ceil_mode=False, name=None, **kwargs):
    """3-D pooling, NCDHW (reference operators/pool_op.cc pool3d;
    legacy gserver Pool3DLayer.cpp)."""
    def _triple(v):
        return list(v) if isinstance(v, (list, tuple)) else [v] * 3

    helper = LayerHelper("pool3d", **locals())
    out = helper.create_tmp_variable(helper.input_dtype())
    helper.append_op(
        type="pool3d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "pooling_type": pool_type,
            "ksize": _triple(pool_size),
            "strides": _triple(pool_stride),
            "paddings": _triple(pool_padding),
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
        },
    )
    return out


def prelu(x, mode="all", param_attr=None, name=None, **kwargs):
    """Parametric ReLU (reference prelu_op.cc; legacy PReluLayer).
    mode: 'all' one alpha, 'channel' per channel, 'element' per element."""
    helper = LayerHelper("prelu", **locals())
    if mode == "all":
        alpha_shape = [1]
    elif mode == "channel":
        alpha_shape = [int(x.shape[1])]
    else:
        alpha_shape = [int(s) for s in x.shape[1:]]
    alpha = helper.create_parameter(
        attr=helper.param_attr, shape=alpha_shape, dtype=x.dtype,
        default_initializer=Constant(0.25),
    )
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(
        type="prelu", inputs={"X": [x], "Alpha": [alpha]},
        outputs={"Out": [out]}, attrs={"mode": mode},
    )
    return out


def crop(x, shape=None, offsets=None, name=None, **kwargs):
    """Crop a static window out of x (reference crop_op.cc; legacy
    CropLayer). shape/offsets are python lists over ALL axes."""
    helper = LayerHelper("crop", **locals())
    out = helper.create_tmp_variable(x.dtype)
    if offsets is None:
        offsets = [0] * len(shape)
    helper.append_op(
        type="crop", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"offsets": list(offsets), "shape": list(shape)},
    )
    return out


def roi_pool(input, rois, pooled_height, pooled_width, spatial_scale=1.0,
             name=None, **kwargs):
    """ROI max pooling (legacy gserver ROIPoolLayer.cpp). `rois` is an
    [R, 4] (x1,y1,x2,y2) tensor whose LoD maps ROIs to batch images."""
    helper = LayerHelper("roi_pool", **locals())
    out = helper.create_tmp_variable(input.dtype, lod_level=rois.lod_level)
    helper.append_op(
        type="roi_pool",
        inputs={"X": [input], "ROIs": [rois]},
        outputs={"Out": [out]},
        attrs={
            "pooled_height": pooled_height,
            "pooled_width": pooled_width,
            "spatial_scale": spatial_scale,
        },
    )
    return out


def scale_sub_region(x, indices, value, name=None, **kwargs):
    """Scale a per-sample (channel, height, width) box by `value`
    (legacy gserver ScaleSubRegionLayer.cpp; indices rows are 1-based
    inclusive [c0, c1, h0, h1, w0, w1])."""
    helper = LayerHelper("scale_sub_region", **locals())
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(
        type="scale_sub_region",
        inputs={"X": [x], "Indices": [indices]},
        outputs={"Out": [out]}, attrs={"value": value},
    )
    return out


def kmax_sequence_score(input, beam_size=1, name=None, **kwargs):
    """Within-sequence indices of each sequence's top-`beam_size` scores,
    -1 padded (legacy gserver KmaxSeqScoreLayer.cpp)."""
    helper = LayerHelper("kmax_seq_score", **locals())
    out = helper.create_tmp_variable("int32")
    helper.append_op(
        type="kmax_seq_score", inputs={"X": [input]},
        outputs={"Out": [out]}, attrs={"beam_size": beam_size},
    )
    return out


def sub_nested_seq(input, selected_indices, name=None, **kwargs):
    """Select sub-sequences of a nested (2-level LoD) sequence by index
    (legacy gserver SubNestedSequenceLayer.cpp). Output slot (i, j) is
    sub-sequence selected_indices[i, j] of sequence i (empty for -1)."""
    helper = LayerHelper("sub_nested_seq", **locals())
    out = helper.create_tmp_variable(input.dtype, lod_level=1)
    helper.append_op(
        type="sub_nested_seq",
        inputs={"X": [input], "S": [selected_indices]},
        outputs={"Out": [out]},
    )
    return out


def lambda_rank_cost(score, label, ndcg_num=5, name=None, **kwargs):
    """LambdaRank cost over score sequences (legacy gserver
    CostLayer.cpp LambdaCost): forward is per-sequence NDCG@ndcg_num
    broadcast over rows; backward is the lambda pairwise gradient."""
    helper = LayerHelper("lambda_rank", **locals())
    out = helper.create_tmp_variable(score.dtype, lod_level=1)
    helper.append_op(
        type="lambda_rank",
        inputs={"X": [score], "Score": [label]},
        outputs={"Out": [out]}, attrs={"NDCG_num": ndcg_num},
    )
    return out


__all__ += [
    "conv3d", "pool3d", "prelu", "crop", "roi_pool", "scale_sub_region",
    "kmax_sequence_score", "sub_nested_seq", "lambda_rank_cost",
    "sequence_reverse",
]


def lod_reset(x, y=None, target_lod=None, name=None, **kwargs):
    """Re-attach/replace a LoD on x (reference lod_reset_op.cc): from
    variable `y`'s LoD when given, else from the static `target_lod`."""
    helper = LayerHelper("lod_reset", **locals())
    out = helper.create_tmp_variable(x.dtype, lod_level=1)
    inputs = {"X": [x]}
    if y is not None:
        inputs["Y"] = [y]
    helper.append_op(
        type="lod_reset", inputs=inputs, outputs={"Out": [out]},
        attrs={} if target_lod is None else {"target_lod": list(target_lod)},
    )
    return out


__all__.append("lod_reset")


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=True, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  dtype="float32", name=None, **kwargs):
    """LSTM with recurrent projection over a ragged batch (reference
    nn.py:339 dynamic_lstmp, operators/lstmp_op). `size` is 4*hidden;
    `proj_size` is the projection width the recurrence runs on.
    Returns (projection, cell), LoD-shaped like the input."""
    helper = LayerHelper("dynamic_lstmp", name=name, **kwargs)
    hidden_size = size // 4
    attr = ParamAttr.to_attr(param_attr)
    weight = helper.create_parameter(
        attr=attr,
        shape=[proj_size, 4 * hidden_size], dtype=dtype,
    )
    # the projection weight needs its OWN attr object: create_parameter
    # fills in attr.name, so reusing the caller's would collide both
    # params on one (overwritten) variable. copy keeps every field
    # (regularizer, gradient_clip, ...) intact.
    import copy as _copy

    proj_attr = _copy.copy(attr)
    proj_attr.name = (attr.name + "_proj") if getattr(attr, "name", None) \
        else None
    proj_weight = helper.create_parameter(
        attr=proj_attr,
        shape=[hidden_size, proj_size], dtype=dtype,
    )
    bias_size = [1, 7 * hidden_size] if use_peepholes else [1, 4 * hidden_size]
    bias = helper.create_parameter(
        attr=ParamAttr.to_attr(bias_attr), shape=bias_size, dtype=dtype,
        is_bias=True,
    )
    projection = helper.create_tmp_variable(dtype, lod_level=1)
    cell = helper.create_tmp_variable(dtype, lod_level=1)
    helper.append_op(
        type="lstmp",
        inputs={"Input": [input], "Weight": [weight],
                "ProjWeight": [proj_weight], "Bias": [bias]},
        outputs={"Projection": [projection], "Cell": [cell]},
        attrs={
            "use_peepholes": use_peepholes, "is_reverse": is_reverse,
            "gate_activation": gate_activation,
            "cell_activation": cell_activation,
            "candidate_activation": candidate_activation,
            "proj_activation": proj_activation,
        },
    )
    return projection, cell


def ctc_greedy_decoder(input, blank, name=None, **kwargs):
    """CTC best-path decode: per-step argmax, collapse repeats, drop
    blanks (reference nn.py ctc_greedy_decoder, ctc_align_op)."""
    helper = LayerHelper("ctc_align", name=name, **kwargs)
    out = helper.create_tmp_variable("int32", lod_level=1)
    helper.append_op(
        type="ctc_align", inputs={"Input": [input]},
        outputs={"Output": [out]}, attrs={"blank": blank},
    )
    return out


def cumsum(x, axis=None, exclusive=False, reverse=False, name=None,
           **kwargs):
    """Cumulative sum (reference cum_op)."""
    helper = LayerHelper("cumsum", name=name, **kwargs)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(
        type="cumsum", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"axis": -1 if axis is None else axis,
               "exclusive": exclusive, "reverse": reverse},
    )
    return out


def _logical2(op_type):
    def layer(x, y, out=None, name=None, **kwargs):
        helper = LayerHelper(op_type, name=name, **kwargs)
        out_var = out or helper.create_tmp_variable("bool")
        helper.append_op(
            type=op_type, inputs={"X": [x], "Y": [y]},
            outputs={"Out": [out_var]},
        )
        return out_var

    layer.__name__ = op_type
    layer.__doc__ = "Elementwise %s (reference logical_op.cc)." % op_type
    return layer


logical_and = _logical2("logical_and")
logical_or = _logical2("logical_or")
logical_xor = _logical2("logical_xor")


def logical_not(x, out=None, name=None, **kwargs):
    """Elementwise NOT (reference logical_op.cc)."""
    helper = LayerHelper("logical_not", name=name, **kwargs)
    out_var = out or helper.create_tmp_variable("bool")
    helper.append_op(
        type="logical_not", inputs={"X": [x]}, outputs={"Out": [out_var]},
    )
    return out_var


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0,
                   name=None, **kwargs):
    """Uniform random tensor (reference uniform_random_op)."""
    helper = LayerHelper("uniform_random", name=name, **kwargs)
    out = helper.create_tmp_variable(dtype)
    helper.append_op(
        type="uniform_random", inputs={}, outputs={"Out": [out]},
        attrs={"shape": list(shape), "min": min, "max": max, "seed": seed,
               "dtype": dtype},
    )
    return out


def lod_rank_table(x, level=0, name=None, **kwargs):
    """Rank table: sequences sorted by length descending, rows
    [original_index, length] (reference control_flow.py lod_rank_table)."""
    helper = LayerHelper("lod_rank_table", name=name, **kwargs)
    out = helper.create_tmp_variable("int32")
    helper.append_op(
        type="lod_rank_table", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"level": level},
    )
    return out


def max_sequence_len(rank_table, name=None, **kwargs):
    """Longest sequence length from a rank table (reference
    max_sequence_len_op)."""
    helper = LayerHelper("max_sequence_len", name=name, **kwargs)
    out = helper.create_tmp_variable("int64")
    helper.append_op(
        type="max_sequence_len", inputs={"RankTable": [rank_table]},
        outputs={"Out": [out]},
    )
    return out


def reorder_lod_tensor_by_rank(x, rank_table, name=None, **kwargs):
    """Reorder sequences into rank-table order (reference
    reorder_lod_tensor_by_rank_op)."""
    helper = LayerHelper("reorder_lod_tensor_by_rank", name=name, **kwargs)
    out = helper.create_tmp_variable(x.dtype, lod_level=1)
    helper.append_op(
        type="reorder_lod_tensor_by_rank",
        inputs={"X": [x], "RankTable": [rank_table]},
        outputs={"Out": [out]},
    )
    return out


def split_lod_tensor(input, mask, level=0, name=None, **kwargs):
    """Route rows into (true, false) branches by boolean mask (reference
    split_lod_tensor_op; the IfElse scatter half)."""
    helper = LayerHelper("split_lod_tensor", name=name, **kwargs)
    out_true = helper.create_tmp_variable(input.dtype, lod_level=1)
    out_false = helper.create_tmp_variable(input.dtype, lod_level=1)
    helper.append_op(
        type="split_lod_tensor",
        inputs={"X": [input], "Mask": [mask]},
        outputs={"OutTrue": [out_true], "OutFalse": [out_false]},
        attrs={"level": level},
    )
    return out_true, out_false


def merge_lod_tensor(in_true, in_false, x, mask, level=0, name=None,
                     **kwargs):
    """Inverse of split_lod_tensor (reference merge_lod_tensor_op)."""
    helper = LayerHelper("merge_lod_tensor", name=name, **kwargs)
    out = helper.create_tmp_variable(in_true.dtype)
    helper.append_op(
        type="merge_lod_tensor",
        inputs={"InTrue": [in_true], "InFalse": [in_false],
                "X": [x], "Mask": [mask]},
        outputs={"Out": [out]},
        attrs={"level": level},
    )
    return out


def lod_tensor_to_array(x, table, name=None, **kwargs):
    """Scatter a ragged batch into a time-step TensorArray in rank-table
    order (reference lod_tensor_to_array_op). Entries keep static [n, D]
    shapes with ended sequences masked to zero."""
    helper = LayerHelper("lod_tensor_to_array", name=name, **kwargs)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(
        type="lod_tensor_to_array",
        inputs={"X": [x], "RankTable": [table]},
        outputs={"Out": [out]},
    )
    return out


def array_to_lod_tensor(x, table, name=None, **kwargs):
    """Gather a time-step TensorArray back into packed ragged layout
    (reference array_to_lod_tensor_op)."""
    helper = LayerHelper("array_to_lod_tensor", name=name, **kwargs)
    out = helper.create_tmp_variable("float32", lod_level=1)
    helper.append_op(
        type="array_to_lod_tensor",
        inputs={"X": [x], "RankTable": [table]},
        outputs={"Out": [out]},
    )
    return out


def shrink_memory(x, i, table, name=None, **kwargs):
    """Mask RNN state rows of sequences finished before step i
    (reference shrink_rnn_memory_op; static-shape masked variant)."""
    helper = LayerHelper("shrink_rnn_memory", name=name, **kwargs)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(
        type="shrink_rnn_memory",
        inputs={"X": [x], "I": [i], "RankTable": [table]},
        outputs={"Out": [out]},
    )
    return out


__all__ += [
    "dynamic_lstmp", "ctc_greedy_decoder", "cumsum", "logical_and",
    "logical_or", "logical_xor", "logical_not", "uniform_random",
    "lod_rank_table", "max_sequence_len", "reorder_lod_tensor_by_rank",
    "split_lod_tensor", "merge_lod_tensor", "lod_tensor_to_array",
    "array_to_lod_tensor", "shrink_memory",
]
