"""Parameter initializers (reference python/paddle/v2/fluid/initializer.py:
Constant, Uniform, Normal, Xavier, MSRA, Bilinear). Each appends an init op
to the startup program; the startup run executes them as one traced XLA
computation with a deterministic per-op PRNG stream."""

from __future__ import annotations

import numpy as np

__all__ = [
    "Initializer",
    "Constant",
    "Uniform",
    "Normal",
    "TruncatedNormal",
    "Xavier",
    "MSRA",
    "Bilinear",
    "ConstantInitializer",
    "UniformInitializer",
    "NormalInitializer",
    "XavierInitializer",
    "MSRAInitializer",
    "force_init_on_cpu",
    "init_on_cpu",
]


def force_init_on_cpu():
    # placement is XLA's problem on TPU; kept for API parity
    return False


import contextlib


@contextlib.contextmanager
def init_on_cpu():
    yield


class Initializer(object):
    def __call__(self, var, block):
        raise NotImplementedError

    def _fan_in_out(self, var):
        shape = var.shape
        if len(shape) < 2:
            return int(shape[0]) if shape else 1, int(shape[0]) if shape else 1
        receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
        fan_in = shape[1] * receptive
        fan_out = shape[0] * receptive
        return fan_in, fan_out


class Constant(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self.value = value

    def __call__(self, var, block):
        block.append_op(
            type="fill_constant",
            outputs={"Out": var},
            attrs={"shape": var.shape, "dtype": var.dtype, "value": float(self.value)},
        )


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        block.append_op(
            type="uniform_random",
            outputs={"Out": var},
            attrs={
                "shape": var.shape,
                "dtype": var.dtype,
                "min": float(self.low),
                "max": float(self.high),
                "seed": self.seed,
            },
        )


class Normal(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op(
            type="gaussian_random",
            outputs={"Out": var},
            attrs={
                "shape": var.shape,
                "dtype": var.dtype,
                "mean": float(self.loc),
                "std": float(self.scale),
                "seed": self.seed,
            },
        )


class TruncatedNormal(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op(
            type="truncated_gaussian_random",
            outputs={"Out": var},
            attrs={
                "shape": var.shape,
                "dtype": var.dtype,
                "mean": float(self.loc),
                "std": float(self.scale),
                "seed": self.seed,
            },
        )


class Xavier(Initializer):
    """Glorot init (reference initializer.py XavierInitializer)."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform = uniform
        self.fan_in = fan_in
        self.fan_out = fan_out
        self.seed = seed

    def __call__(self, var, block):
        fi, fo = self._fan_in_out(var)
        fan_in = self.fan_in if self.fan_in is not None else fi
        fan_out = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = float(np.sqrt(6.0 / (fan_in + fan_out)))
            Uniform(-limit, limit, self.seed)(var, block)
        else:
            std = float(np.sqrt(2.0 / (fan_in + fan_out)))
            Normal(0.0, std, self.seed)(var, block)


class MSRA(Initializer):
    """He init (reference initializer.py MSRAInitializer)."""

    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform = uniform
        self.fan_in = fan_in
        self.seed = seed

    def __call__(self, var, block):
        fi, _ = self._fan_in_out(var)
        fan_in = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = float(np.sqrt(6.0 / fan_in))
            Uniform(-limit, limit, self.seed)(var, block)
        else:
            std = float(np.sqrt(2.0 / fan_in))
            Normal(0.0, std, self.seed)(var, block)


class Bilinear(Initializer):
    """For conv2d_transpose upsampling kernels (reference BilinearInitializer)."""

    def __call__(self, var, block):
        shape = var.shape
        if len(shape) != 4:
            raise ValueError("Bilinear initializer needs a 4-D weight")
        c_out, c_in, h, w = shape
        f = np.ceil(w / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        weight = np.zeros(shape, dtype=np.float32)
        for i in range(h):
            for j in range(w):
                v = (1 - abs(i / f - c)) * (1 - abs(j / f - c))
                weight[:, :, i, j] = v
        block.append_op(
            type="assign_value",
            outputs={"Out": var},
            attrs={
                "shape": shape,
                "dtype": var.dtype,
                "values": weight.reshape(-1).tolist(),
            },
        )


# reference-compatible aliases
ConstantInitializer = Constant
UniformInitializer = Uniform
NormalInitializer = Normal
XavierInitializer = Xavier
MSRAInitializer = MSRA
