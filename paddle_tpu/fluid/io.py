"""Model save/load (reference python/paddle/v2/fluid/io.py:129-400 —
save/load_vars, save/load_params, save/load_persistables,
save/load_inference_model; C++ side inference/io.cc).

Persistables are saved one .npy per variable (name-escaped) plus the
program as a language-neutral JSON IR (core/serialization.py — the
counterpart of the reference's __model__ ProgramDesc protobuf,
inference/io.cc:108). The bundle is readable without this codebase: the
native C inference runner (native/inference.cc) loads and forwards it
directly, matching capi/gradient_machine.h:36,73. TPU-side state lives in
the Scope as device arrays; save pulls to host, load pushes back lazily
at the next executor run.
"""

from __future__ import annotations

import json
import os
import pickle

import numpy as np

from .core.program import Parameter, Program, default_main_program
from .executor import global_scope

__all__ = [
    "save_vars",
    "save_params",
    "save_persistables",
    "load_vars",
    "load_params",
    "load_persistables",
    "save_inference_model",
    "load_inference_model",
    "get_inference_program",
]

_MODEL_FILE = "__model__"


def _escape(name: str) -> str:
    return name.replace("/", "%2F")


def is_persistable(var):
    return var.persistable


def is_parameter(var):
    return isinstance(var, Parameter)


def save_vars(executor, dirname, main_program=None, vars=None, predicate=None):
    os.makedirs(dirname, exist_ok=True)
    if vars is None:
        if main_program is None:
            main_program = default_main_program()
        vars = list(filter(predicate, main_program.list_vars()))
    scope = global_scope()
    for var in vars:
        name = var if isinstance(var, str) else var.name
        if name not in scope:
            continue
        # device arrays can materialise Fortran-ordered (transposed TPU
        # layouts); the on-disk format is always C-order so the native
        # loader (inference.cc load_npy) can mmap-read it directly
        np.save(
            os.path.join(dirname, _escape(name) + ".npy"),
            np.ascontiguousarray(np.asarray(scope.get(name))),
        )


def save_params(executor, dirname, main_program=None):
    save_vars(executor, dirname, main_program, predicate=is_parameter)


def save_persistables(executor, dirname, main_program=None):
    save_vars(executor, dirname, main_program, predicate=is_persistable)


def load_vars(executor, dirname, main_program=None, vars=None, predicate=None):
    if vars is None:
        if main_program is None:
            main_program = default_main_program()
        vars = list(filter(predicate, main_program.list_vars()))
    scope = global_scope()
    for var in vars:
        name = var if isinstance(var, str) else var.name
        path = os.path.join(dirname, _escape(name) + ".npy")
        if not os.path.exists(path):
            raise IOError("no saved value for variable %r at %s" % (name, path))
        scope.set(name, np.load(path))


def load_params(executor, dirname, main_program=None):
    load_vars(executor, dirname, main_program, predicate=is_parameter)


def load_persistables(executor, dirname, main_program=None):
    load_vars(executor, dirname, main_program, predicate=is_persistable)


def get_inference_program(target_vars, main_program=None):
    if main_program is None:
        main_program = default_main_program()
    if not isinstance(target_vars, list):
        target_vars = [target_vars]
    pruned = main_program.prune(target_vars)
    return pruned


def save_inference_model(
    dirname,
    feeded_var_names,
    target_vars,
    executor,
    main_program=None,
    model_filename=None,
    params_filename=None,
    export_for_deployment=True,
):
    """Prune to the inference subgraph, pickle the program, save params
    (reference io.py:297 + pruning via core.prune/pybind.cc:270)."""
    if main_program is None:
        main_program = default_main_program()
    if not isinstance(target_vars, list):
        target_vars = [target_vars]
    os.makedirs(dirname, exist_ok=True)

    inference_program = main_program.prune(target_vars).clone(for_test=True)
    fetch_names = [v.name for v in target_vars]

    from .core.serialization import program_to_dict

    bundle = program_to_dict(inference_program)
    bundle["meta"] = {
        "feed_names": list(feeded_var_names),
        "fetch_names": fetch_names,
    }
    with open(os.path.join(dirname, model_filename or _MODEL_FILE), "w") as f:
        json.dump(bundle, f)
    save_persistables(executor, dirname, inference_program)
    return fetch_names


def load_inference_model(dirname, executor, model_filename=None, params_filename=None):
    path = os.path.join(dirname, model_filename or _MODEL_FILE)
    from .core.serialization import program_from_dict

    with open(path, "rb") as f:
        head = f.read(1)
    if head != b"{":  # pre-r2 pickle bundles
        with open(path, "rb") as f:
            bundle = pickle.load(f)
        program: Program = bundle["program"]
        meta = bundle["meta"]
        if not hasattr(program, "uid"):  # pickled before Program.uid existed
            from .core.program import _program_uid_counter

            program.uid = next(_program_uid_counter)
    else:
        with open(path, "r") as f:
            bundle = json.load(f)
        program = program_from_dict(bundle)
        meta = bundle["meta"]
    load_persistables(executor, dirname, program)
    fetch_vars = [program.global_block().var(n) for n in meta["fetch_names"]]
    return program, meta["feed_names"], fetch_vars
