"""LayerHelper: shared plumbing for layer functions (reference
python/paddle/v2/fluid/layer_helper.py). Creates parameters in the main
program + their init ops in the startup program, temp variables, bias add
and activation tails."""

from __future__ import annotations

import copy
import itertools

from .core.program import (
    Variable,
    default_main_program,
    default_startup_program,
    unique_name,
)
from .initializer import Constant, Xavier
from .param_attr import ParamAttr


class LayerHelper(object):
    def __init__(self, layer_type, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = self.kwargs.get("name")
        if name is None:
            self.kwargs["name"] = unique_name(self.layer_type)

    @property
    def name(self):
        return self.kwargs["name"]

    @property
    def main_program(self):
        return self.kwargs.get("main_program") or default_main_program()

    @property
    def startup_program(self):
        return self.kwargs.get("startup_program") or default_startup_program()

    def append_op(self, *args, **kwargs):
        return self.main_program.current_block().append_op(*args, **kwargs)

    # --- inputs ---------------------------------------------------------
    def multiple_input(self, input_param_name="input"):
        inputs = self.kwargs.get(input_param_name, [])
        if isinstance(inputs, Variable):
            return [inputs]
        return list(inputs)

    def input(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        if len(inputs) != 1:
            raise ValueError("%s layer needs exactly one input" % self.layer_type)
        return inputs[0]

    @property
    def param_attr(self):
        return ParamAttr.to_attr(self.kwargs.get("param_attr"))

    @property
    def bias_attr(self):
        return ParamAttr.to_attr(self.kwargs.get("bias_attr"))

    def multiple_param_attr(self, length):
        attr = self.param_attr
        if isinstance(attr, ParamAttr):
            attr = [attr]
        if len(attr) != 1 and len(attr) != length:
            raise ValueError("parameter number mismatch")
        if len(attr) == 1 and length != 1:
            extra = []
            for i in range(length - 1):
                a = copy.deepcopy(attr[0])
                # a named attr shared across N inputs would collide: each
                # copy gets a _i suffix (weight per input, reference fc)
                if a.name is not None:
                    a.name = "%s_%d" % (a.name, i)
                extra.append(a)
            attr = [attr[0]] + extra
        return attr

    def iter_inputs_and_params(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        attrs = self.multiple_param_attr(len(inputs))
        return zip(inputs, attrs)

    def input_dtype(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        dtype = None
        for each in inputs:
            if dtype is None:
                dtype = each.dtype
            elif dtype != each.dtype:
                raise ValueError("all inputs must have the same dtype")
        return dtype

    # --- variable creation ---------------------------------------------
    def create_parameter(self, attr, shape, dtype, is_bias=False, default_initializer=None):
        assert isinstance(attr, ParamAttr)
        suffix = "b" if is_bias else "w"
        if attr.name is None:
            attr.name = unique_name(".".join([self.name, suffix]))
        if default_initializer is None:
            if is_bias:
                attr.set_default_bias_initializer()
            else:
                attr.set_default_param_initializer()
        else:
            attr.set_default_initializer(default_initializer)

        # startup program gets the var + its init op
        startup_block = self.startup_program.global_block()
        startup_block.create_parameter(
            dtype=dtype,
            shape=shape,
            **attr.to_kwargs(with_initializer=True),
        )
        # main program gets the var only
        return self.main_program.global_block().create_parameter(
            dtype=dtype, shape=shape, **attr.to_kwargs()
        )

    def get_parameter(self, name):
        """Look up an existing Parameter by name (reference layer_helper
        get_parameter; used by crf_decoding to share the CRF transitions)."""
        param = self.main_program.global_block().var(name)
        from .core.program import Parameter

        if not isinstance(param, Parameter):
            raise ValueError("variable %r is not a Parameter" % name)
        return param

    def create_tmp_variable(self, dtype, stop_gradient=False, shape=None, lod_level=0):
        return self.main_program.current_block().create_var(
            name=unique_name(".".join([self.name, "tmp"])),
            dtype=dtype,
            shape=shape,
            lod_level=lod_level,
            persistable=False,
            stop_gradient=stop_gradient,
        )

    def create_variable(self, *args, **kwargs):
        return self.main_program.current_block().create_var(*args, **kwargs)

    def create_global_variable(self, persistable=False, *args, **kwargs):
        return self.main_program.global_block().create_var(
            *args, persistable=persistable, **kwargs
        )

    def set_variable_initializer(self, var, initializer):
        self.startup_program.global_block().create_var(
            name=var.name,
            dtype=var.dtype,
            shape=var.shape,
            persistable=True,
            initializer=initializer,
        )

    # --- tails ----------------------------------------------------------
    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        """Add a bias over dims [dim_start, dim_end) of the input
        (reference layer_helper.py append_bias_op)."""
        size = list(input_var.shape[dim_start:dim_end])
        bias_attr = self.bias_attr
        if not bias_attr:
            return input_var
        b = self.create_parameter(
            attr=bias_attr, shape=size, dtype=input_var.dtype, is_bias=True
        )
        tmp = self.create_tmp_variable(dtype=input_var.dtype)
        self.append_op(
            type="elementwise_add",
            inputs={"X": [input_var], "Y": [b]},
            outputs={"Out": [tmp]},
            attrs={"axis": dim_start},
        )
        return tmp

    def append_activation(self, input_var):
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        else:
            act = dict(act)
        act_type = act.pop("type")
        tmp = self.create_tmp_variable(dtype=input_var.dtype)
        self.append_op(
            type=act_type,
            inputs={"X": [input_var]},
            outputs={"Out": [tmp]},
            attrs=act,
        )
        return tmp
