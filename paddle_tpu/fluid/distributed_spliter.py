"""paddle.v2.fluid.distributed_spliter (reference
distributed_spliter.py): assign variables to parameter-server endpoints
by name hash or round robin. On this core the transpiler path is an
SPMD shim, but the assignment functions keep their exact semantics for
code that partitions by them."""

from __future__ import annotations

import hashlib

__all__ = ["hash_name", "round_robin"]


def hash_name(varlist, pserver_endpoints):
    """Stable name-hash assignment: returns a per-variable endpoint list
    (reference hash_name)."""
    def _hash_block(block_str, total):
        return int(
            hashlib.md5(block_str.encode()).hexdigest(), 16
        ) % total

    eplist = []
    for var in varlist:
        server_id = _hash_block(var.name, len(pserver_endpoints))
        eplist.append(pserver_endpoints[server_id])
    return eplist


def round_robin(varlist, pserver_endpoints):
    """Cyclic assignment (reference round_robin)."""
    if len(varlist) <= len(pserver_endpoints):
        raise AssertionError(
            "round_robin expects more variables than endpoints"
        )
    eplist = []
    idx = 0
    for _ in varlist:
        eplist.append(pserver_endpoints[idx])
        idx = (idx + 1) % len(pserver_endpoints)
    return eplist
