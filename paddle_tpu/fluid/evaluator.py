"""Evaluators: metric accumulation across minibatches (reference
python/paddle/v2/fluid/evaluator.py + legacy paddle/gserver/evaluators/).

State vars are persistable scope arrays updated by ops inside the fused
train step; `eval()` reads them host-side."""

from __future__ import annotations

import numpy as np

from . import layers
from .core.program import Program, Variable, unique_name
from .executor import global_scope
from .initializer import Constant
from .layer_helper import LayerHelper

__all__ = ["Accuracy", "ChunkEvaluator", "DetectionMAP", "Evaluator"]


class Evaluator(object):
    def __init__(self, name, **kwargs):
        self.states = []
        self.metrics = []
        self.helper = LayerHelper(name, **kwargs)

    def reset(self, executor, reset_program=None):
        scope = global_scope()
        for var in self.states:
            scope.set(var.name, np.zeros(var.shape, var.dtype))

    def eval(self, executor, eval_program=None):
        raise NotImplementedError

    def create_state(self, suffix, dtype, shape):
        state = self.helper.create_global_variable(
            name=unique_name(self.helper.name + "_" + suffix),
            persistable=True,
            dtype=dtype,
            shape=shape,
        )
        self.helper.set_variable_initializer(state, Constant(0.0))
        self.states.append(state)
        return state


class Accuracy(Evaluator):
    """Streaming accuracy (reference evaluator.py Accuracy)."""

    def __init__(self, input, label, k=1, **kwargs):
        super().__init__("accuracy", **kwargs)
        main_program = self.helper.main_program
        if main_program.current_block_idx != 0:
            raise ValueError("You can only invoke Evaluator in root block")

        self.total = self.create_state(dtype="int64", shape=[1], suffix="total")
        self.correct = self.create_state(dtype="int64", shape=[1], suffix="correct")
        total = self.helper.create_tmp_variable(dtype="int64")
        correct = self.helper.create_tmp_variable(dtype="int64")
        acc = layers.accuracy(input=input, label=label, k=k, correct=correct, total=total)
        self.helper.append_op(
            type="sum",
            inputs={"X": [self.total, total]},
            outputs={"Out": [self.total]},
        )
        self.helper.append_op(
            type="sum",
            inputs={"X": [self.correct, correct]},
            outputs={"Out": [self.correct]},
        )
        self.metrics.append(acc)

    def eval(self, executor, eval_program=None):
        scope = global_scope()
        total = float(np.asarray(scope.get(self.total.name))[0])
        correct = float(np.asarray(scope.get(self.correct.name))[0])
        return np.array(correct / total if total else 0.0, dtype=np.float32)


class ChunkEvaluator(Evaluator):
    """Streaming chunk precision/recall/F1 (reference evaluator.py
    ChunkEvaluator; per-batch counts from layers.chunk_eval accumulated in
    persistable state vars)."""

    def __init__(
        self, input, label, chunk_scheme, num_chunk_types,
        excluded_chunk_types=None,
    ):
        super().__init__("chunk_eval")
        main_program = self.helper.main_program
        if main_program.current_block_idx != 0:
            raise ValueError("You can only invoke Evaluator in root block")
        self.num_infer_chunks = self.create_state(
            dtype="int64", shape=[1], suffix="num_infer_chunks"
        )
        self.num_label_chunks = self.create_state(
            dtype="int64", shape=[1], suffix="num_label_chunks"
        )
        self.num_correct_chunks = self.create_state(
            dtype="int64", shape=[1], suffix="num_correct_chunks"
        )
        precision, recall, f1, num_infer, num_label, num_correct = (
            layers.chunk_eval(
                input=input,
                label=label,
                chunk_scheme=chunk_scheme,
                num_chunk_types=num_chunk_types,
                excluded_chunk_types=excluded_chunk_types,
            )
        )
        for state, batch in (
            (self.num_infer_chunks, num_infer),
            (self.num_label_chunks, num_label),
            (self.num_correct_chunks, num_correct),
        ):
            self.helper.append_op(
                type="sum", inputs={"X": [state, batch]}, outputs={"Out": [state]}
            )
        self.metrics.extend((precision, recall, f1))

    def eval(self, executor, eval_program=None):
        scope = global_scope()
        infer = float(np.asarray(scope.get(self.num_infer_chunks.name))[0])
        label = float(np.asarray(scope.get(self.num_label_chunks.name))[0])
        correct = float(np.asarray(scope.get(self.num_correct_chunks.name))[0])
        precision = correct / infer if infer else 0.0
        recall = correct / label if label else 0.0
        f1 = (
            2 * precision * recall / (precision + recall)
            if correct
            else 0.0
        )
        return np.array([precision, recall, f1], dtype=np.float32)


class DetectionMAP(object):
    """VOC-style mean Average Precision over detection outputs
    (reference gserver/evaluators/DetectionMAPEvaluator.cpp and fluid
    operators/detection_map_op.cc).

    Host-side accumulator by design: matching ragged per-image detection
    lists against ragged ground truth is control-flow-heavy host work in
    the reference too (a CPU evaluator fed from the device). Feed it the
    fetched `multiclass_nms` rows per image.

    detections per image: [k, 6] rows = [label, score, x1, y1, x2, y2]
    ground truth per image: boxes [m, 4], labels [m], difficult [m] bool.
    """

    def __init__(self, overlap_threshold=0.5, evaluate_difficult=False,
                 ap_version="integral"):
        if ap_version not in ("integral", "11point"):
            raise ValueError("ap_version must be 'integral' or '11point'")
        self.overlap_threshold = float(overlap_threshold)
        self.evaluate_difficult = bool(evaluate_difficult)
        self.ap_version = ap_version
        self.reset()

    def reset(self, executor=None, reset_program=None):
        self._scored = {}    # class -> [(score, is_tp)]
        self._gt_count = {}  # class -> #non-difficult gt boxes

    @staticmethod
    def _iou(box, boxes):
        x1 = np.maximum(box[0], boxes[:, 0])
        y1 = np.maximum(box[1], boxes[:, 1])
        x2 = np.minimum(box[2], boxes[:, 2])
        y2 = np.minimum(box[3], boxes[:, 3])
        inter = np.clip(x2 - x1, 0, None) * np.clip(y2 - y1, 0, None)
        a = (box[2] - box[0]) * (box[3] - box[1])
        b = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
        union = a + b - inter
        return np.where(union > 0, inter / np.maximum(union, 1e-12), 0.0)

    def update(self, detections, gt_boxes, gt_labels, difficult=None):
        """One batch: each argument is a list with one entry per image."""
        n = len(detections)
        if difficult is None:
            difficult = [np.zeros(len(np.atleast_1d(l)), bool)
                         for l in gt_labels]
        for i in range(n):
            det = np.asarray(detections[i], np.float64).reshape(-1, 6)
            boxes = np.asarray(gt_boxes[i], np.float64).reshape(-1, 4)
            labels = np.asarray(gt_labels[i]).reshape(-1).astype(int)
            diff = np.asarray(difficult[i], bool).reshape(-1)
            for c in np.unique(labels):
                count = int(np.sum((labels == c) & ~diff))
                if self.evaluate_difficult:
                    count = int(np.sum(labels == c))
                self._gt_count[c] = self._gt_count.get(c, 0) + count
            # match per class, best score first (VOC protocol)
            det = det[det[:, 0] >= 0]  # drop padding rows
            order = np.argsort(-det[:, 1], kind="stable")
            matched = np.zeros(len(labels), bool)
            for j in order:
                c = int(det[j, 0])
                score = float(det[j, 1])
                cand = np.nonzero(labels == c)[0]
                bucket = self._scored.setdefault(c, [])
                if cand.size == 0:
                    bucket.append((score, False))
                    continue
                ious = self._iou(det[j, 2:6], boxes[cand])
                best = int(np.argmax(ious))
                gt_idx = cand[best]
                if ious[best] >= self.overlap_threshold:
                    if diff[gt_idx] and not self.evaluate_difficult:
                        continue  # matched a difficult gt: ignore
                    if not matched[gt_idx]:
                        matched[gt_idx] = True
                        bucket.append((score, True))
                    else:
                        bucket.append((score, False))  # duplicate
                else:
                    bucket.append((score, False))

    def _ap(self, scored, n_gt):
        if n_gt == 0:
            return None
        if not scored:
            return 0.0
        arr = np.asarray(sorted(scored, key=lambda t: -t[0]), np.float64)
        tp = np.cumsum(arr[:, 1])
        fp = np.cumsum(1.0 - arr[:, 1])
        recall = tp / n_gt
        precision = tp / np.maximum(tp + fp, 1e-12)
        if self.ap_version == "11point":
            ap = 0.0
            for t in np.linspace(0, 1, 11):
                p = precision[recall >= t]
                ap += (p.max() if p.size else 0.0) / 11.0
            return float(ap)
        # integral: sum precision * delta-recall over detections
        prev_r = np.concatenate([[0.0], recall[:-1]])
        return float(np.sum(precision * (recall - prev_r)))

    def eval(self, executor=None, eval_program=None):
        """mAP over classes that have ground truth."""
        aps = []
        for c, n_gt in self._gt_count.items():
            ap = self._ap(self._scored.get(c, []), n_gt)
            if ap is not None:
                aps.append(ap)
        return float(np.mean(aps)) if aps else 0.0
