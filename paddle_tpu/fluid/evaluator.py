"""Evaluators: metric accumulation across minibatches (reference
python/paddle/v2/fluid/evaluator.py + legacy paddle/gserver/evaluators/).

State vars are persistable scope arrays updated by ops inside the fused
train step; `eval()` reads them host-side."""

from __future__ import annotations

import numpy as np

from . import layers
from .core.program import Program, Variable, unique_name
from .executor import global_scope
from .initializer import Constant
from .layer_helper import LayerHelper

__all__ = ["Accuracy", "ChunkEvaluator", "Evaluator"]


class Evaluator(object):
    def __init__(self, name, **kwargs):
        self.states = []
        self.metrics = []
        self.helper = LayerHelper(name, **kwargs)

    def reset(self, executor, reset_program=None):
        scope = global_scope()
        for var in self.states:
            scope.set(var.name, np.zeros(var.shape, var.dtype))

    def eval(self, executor, eval_program=None):
        raise NotImplementedError

    def create_state(self, suffix, dtype, shape):
        state = self.helper.create_global_variable(
            name=unique_name(self.helper.name + "_" + suffix),
            persistable=True,
            dtype=dtype,
            shape=shape,
        )
        self.helper.set_variable_initializer(state, Constant(0.0))
        self.states.append(state)
        return state


class Accuracy(Evaluator):
    """Streaming accuracy (reference evaluator.py Accuracy)."""

    def __init__(self, input, label, k=1, **kwargs):
        super().__init__("accuracy", **kwargs)
        main_program = self.helper.main_program
        if main_program.current_block_idx != 0:
            raise ValueError("You can only invoke Evaluator in root block")

        self.total = self.create_state(dtype="int64", shape=[1], suffix="total")
        self.correct = self.create_state(dtype="int64", shape=[1], suffix="correct")
        total = self.helper.create_tmp_variable(dtype="int64")
        correct = self.helper.create_tmp_variable(dtype="int64")
        acc = layers.accuracy(input=input, label=label, k=k, correct=correct, total=total)
        self.helper.append_op(
            type="sum",
            inputs={"X": [self.total, total]},
            outputs={"Out": [self.total]},
        )
        self.helper.append_op(
            type="sum",
            inputs={"X": [self.correct, correct]},
            outputs={"Out": [self.correct]},
        )
        self.metrics.append(acc)

    def eval(self, executor, eval_program=None):
        scope = global_scope()
        total = float(np.asarray(scope.get(self.total.name))[0])
        correct = float(np.asarray(scope.get(self.correct.name))[0])
        return np.array(correct / total if total else 0.0, dtype=np.float32)


class ChunkEvaluator(Evaluator):
    """Streaming chunk precision/recall/F1 (reference evaluator.py
    ChunkEvaluator; per-batch counts from layers.chunk_eval accumulated in
    persistable state vars)."""

    def __init__(
        self, input, label, chunk_scheme, num_chunk_types,
        excluded_chunk_types=None,
    ):
        super().__init__("chunk_eval")
        main_program = self.helper.main_program
        if main_program.current_block_idx != 0:
            raise ValueError("You can only invoke Evaluator in root block")
        self.num_infer_chunks = self.create_state(
            dtype="int64", shape=[1], suffix="num_infer_chunks"
        )
        self.num_label_chunks = self.create_state(
            dtype="int64", shape=[1], suffix="num_label_chunks"
        )
        self.num_correct_chunks = self.create_state(
            dtype="int64", shape=[1], suffix="num_correct_chunks"
        )
        precision, recall, f1, num_infer, num_label, num_correct = (
            layers.chunk_eval(
                input=input,
                label=label,
                chunk_scheme=chunk_scheme,
                num_chunk_types=num_chunk_types,
                excluded_chunk_types=excluded_chunk_types,
            )
        )
        for state, batch in (
            (self.num_infer_chunks, num_infer),
            (self.num_label_chunks, num_label),
            (self.num_correct_chunks, num_correct),
        ):
            self.helper.append_op(
                type="sum", inputs={"X": [state, batch]}, outputs={"Out": [state]}
            )
        self.metrics.extend((precision, recall, f1))

    def eval(self, executor, eval_program=None):
        scope = global_scope()
        infer = float(np.asarray(scope.get(self.num_infer_chunks.name))[0])
        label = float(np.asarray(scope.get(self.num_label_chunks.name))[0])
        correct = float(np.asarray(scope.get(self.num_correct_chunks.name))[0])
        precision = correct / infer if infer else 0.0
        recall = correct / label if label else 0.0
        f1 = (
            2 * precision * recall / (precision + recall)
            if correct
            else 0.0
        )
        return np.array([precision, recall, f1], dtype=np.float32)
