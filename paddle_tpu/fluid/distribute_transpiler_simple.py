"""paddle.v2.fluid.distribute_transpiler_simple (reference
distribute_transpiler_simple.py:65 SimpleDistributeTranspiler — the
unsplit whole-variable pserver transpile). Delegates to the same SPMD
shim as DistributeTranspiler: on TPU both transpiles lower to mesh
data-parallel execution with XLA collectives."""

from .distribute_transpiler import DistributeTranspiler

__all__ = ["SimpleDistributeTranspiler"]


class SimpleDistributeTranspiler(DistributeTranspiler):
    pass
