"""paddle.v2.fluid.memory_optimization_transpiler (reference
memory_optimization_transpiler.py:270 memory_optimize — a liveness
analysis that rewrites var reuse in the op-at-a-time interpreter).

On this core the whole block compiles to ONE fused XLA computation and
XLA's buffer assignment already performs liveness-based reuse plus
donation of the parameter buffers (executor.py), so the transpile is a
semantic no-op by design — kept as the API with that contract stated,
the same stance as DistributeTranspiler.memory_optimize."""

from __future__ import annotations

__all__ = ["memory_optimize", "release_memory"]


def memory_optimize(input_program):
    """No-op by design: XLA buffer assignment does the reuse."""
    return input_program


def release_memory(input_program):
    """No-op by design: buffers are freed by XLA/PJRT liveness."""
    return input_program
