"""paddle.v2.fluid.memory_optimization_transpiler (reference
memory_optimization_transpiler.py:270 memory_optimize — a liveness
analysis that rewrites var reuse in the op-at-a-time interpreter).

On this core the whole block compiles to ONE fused XLA computation whose
buffer assignment already performs liveness-based reuse plus donation of
the parameter buffers (executor.py). The transpile therefore maps to the
memory lever XLA does NOT take on its own: rematerialization. Marking a
program with `memory_optimize` makes the lowering wrap the forward region
in `jax.checkpoint`, so the cotangent pass recomputes activations instead
of keeping them live across forward+backward — the same peak-memory
reduction the reference's var-reuse rewrite bought its interpreter,
expressed the TPU way (FLOPs traded for HBM residency). Training results
match the un-optimized program to fusion-level rounding; only the
schedule changes."""

from __future__ import annotations

__all__ = ["memory_optimize", "release_memory"]


def memory_optimize(input_program, print_log=False, **kwargs):
    """Enable forward-region rematerialization for `input_program`.

    Reference semantics: rewrite the program so activation memory is
    reused once dead (memory_optimization_transpiler.py:270). Here the
    equivalent peak-memory reduction comes from `jax.checkpoint` around
    the traced forward region (core/lowering.py), which drops activations
    after the primal pass and recomputes them inside the backward.
    """
    input_program.remat = True
    if print_log:
        print("memory_optimize: forward-region rematerialization enabled")
    return input_program


def release_memory(input_program):
    """No-op by design: buffers are freed by XLA/PJRT liveness."""
    return input_program
