"""ParamAttr (reference python/paddle/v2/fluid/param_attr.py)."""

from __future__ import annotations

from .initializer import Constant, Initializer, Xavier

__all__ = ["ParamAttr", "WeightNormParamAttr"]


class ParamAttr(object):
    def __init__(
        self,
        name=None,
        initializer=None,
        learning_rate=1.0,
        regularizer=None,
        trainable=True,
        gradient_clip=None,
        do_model_average=None,
        update_hook=None,
    ):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.gradient_clip = gradient_clip
        self.do_model_average = do_model_average
        self.update_hook = update_hook

    def set_default_initializer(self, initializer):
        if self.initializer is None:
            self.initializer = initializer

    def set_default_param_initializer(self):
        self.set_default_initializer(Xavier())

    def set_default_bias_initializer(self):
        self.set_default_initializer(Constant(0.0))

    @staticmethod
    def to_attr(arg):
        if arg is None:
            return ParamAttr()
        if isinstance(arg, (list, tuple)):
            return [ParamAttr.to_attr(a) for a in arg]
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, Initializer):
            return ParamAttr(initializer=arg)
        if isinstance(arg, bool):
            return ParamAttr() if arg else None
        raise TypeError("cannot interpret %r as ParamAttr" % (arg,))

    def to_kwargs(self, with_initializer=False):
        kwargs = {
            "name": self.name,
            "optimize_attr": {"learning_rate": self.learning_rate},
            "regularizer": self.regularizer,
            "trainable": self.trainable,
            "gradient_clip_attr": self.gradient_clip,
            "do_model_average": self.do_model_average,
            "update_hook": self.update_hook,
        }
        if with_initializer:
            kwargs["initializer"] = self.initializer
        return kwargs


class WeightNormParamAttr(ParamAttr):
    def __init__(self, dim=None, **kwargs):
        super().__init__(**kwargs)
        self.dim = dim
