"""Raw operator factory (reference python/paddle/v2/fluid/op.py).

The reference builds OpDesc protobufs from the C++ OpInfoMap
(get_all_op_protos, OperatorFactory, op.py:19,167); its unit tests use
`Operator("sgd", Param=..., ...)` to make one op outside any layer
helper. Here the registry is the kernel table (core/registry.py), and an
Operator appends to a Block — same raw-construction surface over the
traced executor.
"""

from __future__ import annotations

from .core.registry import has_kernel, registered_ops

__all__ = ["Operator", "get_all_op_protos"]


def get_all_op_protos():
    """Names of every registered op type (the reference returns OpProto
    messages; the kernel registry is the single source of truth here)."""
    return list(registered_ops())


# Output-slot resolution. The reference resolves a slot's direction from
# the op's OpProto (op.py:19 get_all_op_protos); name existence in the
# block says nothing — in-place ops (sgd ParamOut="w") name an EXISTING
# var as output. Here the conventions of the kernel registry stand in
# for OpProto: in-place update outputs all use the "<Name>Out" suffix
# (ParamOut, MomentOut, VelocityOut, ...), plain "Out" is the canonical
# dense output, and the remaining multi-output ops are tabled explicitly.
_OUTPUT_SLOT_TABLE = {
    # auc reads predictions through a slot literally named "Out"
    # (reference auc_op.cc input slot) — the one "Out"-as-input op.
    "auc": frozenset(["AUC"]),
    "batch_norm": frozenset(
        ["Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"]
    ),
    "top_k": frozenset(["Out", "Indices"]),
    "accuracy": frozenset(["Accuracy", "Correct", "Total"]),
    "dropout": frozenset(["Out", "Mask"]),
    "conv2d": frozenset(["Output"]),
    "conv2d_transpose": frozenset(["Output"]),
    "conv3d": frozenset(["Output"]),
    "depthwise_conv2d": frozenset(["Output"]),
}

# slot names that are always outputs when no per-op table entry applies
_GENERIC_OUTPUT_SLOTS = frozenset(["Out", "Output"])


def _is_output_slot(op_type, slot):
    table = _OUTPUT_SLOT_TABLE.get(op_type)
    if table is not None:
        return slot in table
    return slot in _GENERIC_OUTPUT_SLOTS or (
        slot.endswith("Out") and slot != "Out"
    )


class Operator(object):
    """Build one raw op: `Operator("scale", X=["x"], Out=["y"], scale=2.0)`.
    Slot arguments (capitalised, list-or-str of var names) become
    inputs/outputs according to the op's known output slots (falling back
    to block-membership for slots the table doesn't decide); remaining
    kwargs are attributes. Call `append_to(block)` to attach."""

    def __init__(self, type, **kwargs):
        if not has_kernel(type):
            raise ValueError(
                "no kernel registered for op type %r (have %d)"
                % (type, len(registered_ops()))
            )
        self.type = type
        self.slots = {}
        self.attrs = {}
        for k, v in kwargs.items():
            if k[:1].isupper():
                self.slots[k] = [v] if isinstance(v, str) else list(v)
            else:
                self.attrs[k] = v

    def append_to(self, block):
        ins, outs = {}, {}
        for slot, names in self.slots.items():
            if _is_output_slot(self.type, slot):
                # output (possibly in-place onto an existing var — sgd
                # ParamOut names the param itself); create fresh vars on
                # demand
                for n in names:
                    if n not in block.vars:
                        block.create_var(name=n)
                outs[slot] = names
            elif all(n in block.vars for n in names):
                ins[slot] = names
            else:
                # fallback for untabled slots: fresh names are outputs
                for n in names:
                    if n not in block.vars:
                        block.create_var(name=n)
                outs[slot] = names
        return block.append_op(
            type=self.type, inputs=ins, outputs=outs, attrs=self.attrs
        )
