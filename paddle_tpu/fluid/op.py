"""Raw operator factory (reference python/paddle/v2/fluid/op.py).

The reference builds OpDesc protobufs from the C++ OpInfoMap
(get_all_op_protos, OperatorFactory, op.py:19,167); its unit tests use
`Operator("sgd", Param=..., ...)` to make one op outside any layer
helper. Here the registry is the kernel table (core/registry.py), and an
Operator appends to a Block — same raw-construction surface over the
traced executor.
"""

from __future__ import annotations

from .core.registry import has_kernel, registered_ops

__all__ = ["Operator", "get_all_op_protos"]


def get_all_op_protos():
    """Names of every registered op type (the reference returns OpProto
    messages; the kernel registry is the single source of truth here)."""
    return list(registered_ops())


class Operator(object):
    """Build one raw op: `Operator("scale", X=["x"], Out=["y"], scale=2.0)`.
    Slot arguments (capitalised, list-or-str of var names) become
    inputs/outputs according to the target block's variables; remaining
    kwargs are attributes. Call `append_to(block)` to attach."""

    def __init__(self, type, **kwargs):
        if not has_kernel(type):
            raise ValueError(
                "no kernel registered for op type %r (have %d)"
                % (type, len(registered_ops()))
            )
        self.type = type
        self.slots = {}
        self.attrs = {}
        for k, v in kwargs.items():
            if k[:1].isupper():
                self.slots[k] = [v] if isinstance(v, str) else list(v)
            else:
                self.attrs[k] = v

    def append_to(self, block):
        ins, outs = {}, {}
        for slot, names in self.slots.items():
            # a name already defined in the block is an input; fresh
            # names are outputs (created on demand)
            if all(n in block.vars for n in names):
                ins[slot] = names
            else:
                for n in names:
                    if n not in block.vars:
                        block.create_var(name=n)
                outs[slot] = names
        return block.append_op(
            type=self.type, inputs=ins, outputs=outs, attrs=self.attrs
        )
