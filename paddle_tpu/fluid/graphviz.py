"""paddle.v2.fluid.graphviz (reference graphviz.py): dot-source
emission for program blocks; the implementation lives in debugger.py
(draw_block_graphviz)."""

from .debugger import draw_block_graphviz  # noqa: F401

__all__ = ["draw_block_graphviz"]
