"""Executor: run a Program with feed/fetch, compiling whole blocks to XLA.

API parity with reference python/paddle/v2/fluid/executor.py (Executor:166,
run:221, global_scope:27, scope_guard:39, fetch_var:137) — but the engine
is different by design: instead of injecting feed/fetch ops and interpreting
op-by-op in C++ (reference executor.cc:80), `run` compiles the block ONCE
per (program-version, feed-signature) into a single XLA computation via
jax.jit with donated parameter buffers, then replays it. See
core/lowering.py for the story.
"""

from __future__ import annotations

import collections
import contextlib
import os
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import core
from .core.kernels_control import LOD_SRC
from .core.kernels_sequence import LOD_SUFFIX, bucket_pow2, lod_key
from .core.lowering import build_step_fn
from .core.program import Program, Variable


class _TensorView(object):
    """Minimal stand-in for the reference's LoDTensor handle returned by
    scope.find_var(name).get_tensor()."""

    def __init__(self, scope, name):
        self._scope = scope
        self._name = name

    def get_tensor(self):
        return self

    def __array__(self, dtype=None):
        arr = np.asarray(self._scope.get(self._name))
        return arr.astype(dtype) if dtype else arr

    def set(self, value, place=None):
        self._scope.set(self._name, np.asarray(value))

    def shape(self):
        return list(np.asarray(self).shape)


class Scope(object):
    """name -> device array storage for persistables (params, optimizer
    state, BN running stats). Replaces the reference's C++ Scope tree
    (framework/scope.h); no hierarchy is needed because non-persistable
    intermediates live only inside the traced computation."""

    def __init__(self):
        self._vars: Dict[str, Any] = {}

    def get(self, name):
        return self._vars[name]

    def set(self, name, value):
        self._vars[name] = value

    def __contains__(self, name):
        return name in self._vars

    def keys(self):
        return self._vars.keys()

    def drop(self, name):
        self._vars.pop(name, None)

    # reference-compatible surface
    def find_var(self, name):
        return _TensorView(self, name) if name in self._vars else None

    def var(self, name):
        self._vars.setdefault(name, None)
        return _TensorView(self, name)


_global_scope = Scope()
_current_scope = _global_scope


def global_scope() -> Scope:
    return _current_scope


def switch_scope(scope: Scope) -> Scope:
    global _current_scope
    prev, _current_scope = _current_scope, scope
    return prev


@contextlib.contextmanager
def scope_guard(scope: Scope):
    prev = switch_scope(scope)
    try:
        yield
    finally:
        switch_scope(prev)


def as_numpy(tensor):
    if isinstance(tensor, (list, tuple)):
        return [as_numpy(t) for t in tensor]
    return np.asarray(tensor)


def fetch_var(name, scope: Optional[Scope] = None, return_numpy: bool = True):
    scope = scope or global_scope()
    val = scope.get(name if isinstance(name, str) else name.name)
    return np.asarray(val) if return_numpy else val


def _feed_name(f):
    return f.name if isinstance(f, Variable) else str(f)


class CompileCache(object):
    """Bounded LRU over compiled step entries, keyed by (program,
    feed-signature, ...) tuples. A long-lived serving or supervisor
    process walks many shape buckets over its lifetime; the old
    unbounded dict grew a compiled XLA executable per signature forever.
    Capacity counts ENTRIES (signatures), not bytes — each entry pins
    one compiled executable. Hit/miss/eviction counters are exposed via
    Executor.cache_stats() so occupancy is observable, not guessed.

    get() returns None on miss (the dict.get contract every call site
    already uses) and refreshes recency on hit; insertion evicts the
    least-recently-used entry past capacity. An evicted signature is
    not an error — the next run recompiles, exactly like first contact.
    """

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            capacity = int(
                os.environ.get("PADDLE_TPU_EXECUTOR_CACHE_CAP", "64")
            )
        if capacity < 1:
            raise ValueError("cache capacity must be >= 1")
        self.capacity = capacity
        self._od: "collections.OrderedDict[Any, Any]" = (
            collections.OrderedDict()
        )
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key):
        try:
            entry = self._od[key]
        except KeyError:
            self.misses += 1
            return None
        self._od.move_to_end(key)
        self.hits += 1
        return entry

    def __setitem__(self, key, entry):
        self._od[key] = entry
        self._od.move_to_end(key)
        while len(self._od) > self.capacity:
            self._od.popitem(last=False)
            self.evictions += 1

    def __contains__(self, key):
        return key in self._od

    def __len__(self):
        return len(self._od)

    def clear(self):
        self._od.clear()

    def stats(self) -> Dict[str, int]:
        return {
            "size": len(self._od),
            "capacity": self.capacity,
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
        }


class Executor(object):
    """Single-chip by default. Pass `mesh=jax.sharding.Mesh(...)` (or set a
    default via paddle_tpu.parallel.set_default_mesh) to run data/tensor-
    parallel: feeds shard on the mesh 'data' axis, params place per
    program.shardings (replicated unless annotated), and XLA SPMD inserts
    the gradient allreduce over ICI — replacing the reference's
    MultiGradientMachine / NCCL / pserver paths with identical global-batch
    semantics."""

    def __init__(self, places=None, mesh=None, cache_capacity=None):
        if isinstance(places, (list, tuple)):
            places = places[0] if places else None
        self.place = places
        self.mesh = mesh
        # bounded LRU (PADDLE_TPU_EXECUTOR_CACHE_CAP, default 64): a
        # long-lived serving/supervisor process must not grow a compiled
        # executable per shape bucket without limit
        self._cache = CompileCache(cache_capacity)
        self._run_counter = 0
        # (jitted entry, arg avals, host-arg snapshot) of last run
        self._last_exec = None
        self._capture_avals = False  # set by profiler.compiled_profile

    def _resolve_mesh(self):
        if self.mesh is not None:
            return self.mesh
        from ..parallel.mesh import get_default_mesh

        return get_default_mesh()

    def _maybe_preflight(self, program, feed, fetch_list, force=False):
        """Program-verifier pre-flight shared by EVERY run entry point
        (run / run_repeated / run_grad_accum / run_async_local), so
        PADDLE_TPU_VALIDATE=1 means what it says regardless of which
        loop drives the program."""
        if force or os.environ.get(
                "PADDLE_TPU_VALIDATE", "") not in ("", "0"):
            from ..analysis.program_lint import preflight

            preflight(
                program if program is not None
                else core.default_main_program(),
                feeds=list(feed or ()),
                fetches=fetch_list or (),
            )

    # ------------------------------------------------------------------
    def run(
        self,
        program: Optional[Program] = None,
        feed: Optional[Dict[str, Any]] = None,
        fetch_list: Optional[List[Any]] = None,
        feed_var_name: str = "feed",
        fetch_var_name: str = "fetch",
        scope: Optional[Scope] = None,
        return_numpy: bool = True,
        use_program_cache: bool = True,
        validate: bool = False,
    ):
        """`validate=True` (or env PADDLE_TPU_VALIDATE=1) runs the
        paddle_tpu.analysis program verifier as a pre-flight: a
        malformed program (dangling input, dtype clash, duplicate
        parameter, unpaired grad var) raises ProgramVerifyError with
        P-coded findings BEFORE lowering, instead of surfacing as a
        cryptic tracer error inside the compiled step. Memoized per
        (program version, feed/fetch signature), so a cached training
        loop pays one dict lookup per run."""
        self._maybe_preflight(program, feed, fetch_list, force=validate)
        return self._execute(
            program, feed, fetch_list, scope, return_numpy,
            use_cache=use_program_cache, steps=None, scan_feeds=False,
        )

    def run_repeated(
        self,
        program: Optional[Program] = None,
        feed: Optional[Dict[str, Any]] = None,
        fetch_list: Optional[List[Any]] = None,
        steps: int = 1,
        scan_feeds: bool = False,
        scope: Optional[Scope] = None,
        return_numpy: bool = True,
    ):
        """Run `steps` training iterations in ONE compiled computation
        (lax.scan) — the host leaves the step loop entirely. With
        scan_feeds=True every feed must carry a leading [steps] dim holding
        per-step batches (LoD side-bands are always broadcast); otherwise
        the same feed is reused each step. Fetches return stacked
        [steps, ...]."""
        self._maybe_preflight(program, feed, fetch_list)
        return self._execute(
            program, feed, fetch_list, scope, return_numpy,
            use_cache=True, steps=int(steps), scan_feeds=scan_feeds,
        )

    def run_grad_accum(
        self,
        program: Optional[Program] = None,
        feed: Optional[Dict[str, Any]] = None,
        fetch_list: Optional[List[Any]] = None,
        micro_batches: int = 2,
        scope: Optional[Scope] = None,
        return_numpy: bool = True,
    ):
        """ONE optimizer step over `micro_batches` forward/backward
        passes (gradient accumulation): the feed batch splits into
        equal chunks, a lax.scan accumulates the mean of chunk
        gradients, and the update applies once — activations live one
        micro-batch at a time, so the effective batch is bounded by
        step count, not HBM (core/lowering.py build_accum_step_fn).

        Exactness caveat: chunk gradients are AVERAGED, which matches
        the full-batch step only for mean-reduced losses. A sum-reduced
        loss trains with gradients scaled by 1/micro_batches (a warning
        fires when the loss producer is a detectable sum reduction)."""
        self._maybe_preflight(program, feed, fetch_list)
        from .core.lowering import build_accum_step_fn

        if self._resolve_mesh() is not None:
            raise NotImplementedError(
                "run_grad_accum is single-chip; compose large batches "
                "on a mesh with the data axis instead"
            )
        if program is None:
            program = core.default_main_program()
        feed = dict(feed or {})
        scope = scope or global_scope()
        block = program.global_block()
        fetch_names = [_feed_name(f) for f in fetch_list or []]
        persist_names = sorted(
            v.name for v in program.list_vars() if v.persistable
        )
        feed_arrays = {}
        for name, value in feed.items():
            var = block.var(name) if block.has_var(name) else None
            data, lod = _split_lod_feed(value)
            if lod is not None:
                raise NotImplementedError(
                    "gradient accumulation with ragged (LoD) feeds is "
                    "not supported"
                )
            feed_arrays[name] = _to_device_dtype(data, var)
        persist_in = {n: scope.get(n) for n in persist_names if n in scope}
        feed_sig = tuple(
            (n, tuple(a.shape), str(a.dtype))
            for n, a in sorted(feed_arrays.items())
        )
        key = (
            "grad_accum", program.uid, program.version, program.amp,
            program.remat, feed_sig, tuple(fetch_names),
            tuple(sorted(persist_in)), int(micro_batches),
        )
        entry = self._cache.get(key)
        if entry is None:
            fn, _ = build_accum_step_fn(
                program,
                feed_names=list(feed_arrays),
                fetch_names=fetch_names,
                persist_names=persist_names,
                micro_batches=int(micro_batches),
                persist_in=list(persist_in),
            )
            entry = jax.jit(fn, donate_argnums=(0,))
            self._cache[key] = entry
        self._run_counter += 1
        rng = jax.random.fold_in(
            jax.random.PRNGKey(program.random_seed), self._run_counter
        )
        fetches, new_persist = entry(persist_in, feed_arrays, rng)
        _flush_print_effects(program)
        return _finish_run(
            scope, fetch_names, fetches, new_persist, return_numpy
        )

    def run_async_local(
        self,
        program: Optional[Program] = None,
        feed: Optional[Dict[str, Any]] = None,
        fetch_list: Optional[List[Any]] = None,
        steps: int = 1,
        sync_every: int = 1,
        scope: Optional[Scope] = None,
        return_numpy: bool = True,
    ):
        """AsyncSGD equivalent (reference ParameterServer2.h:127 /
        go/pserver SendGrad): local-SGD redesign — every 'data'-axis
        replica trains its OWN parameter + optimizer-state copy for
        `sync_every` steps with zero inter-chip traffic, then replicas
        average their models (one pmean per round). See
        parallel/async_sgd.py for the semantics argument. Feeds must be
        dense arrays with a leading [steps] dim then the global batch
        dim; fetches return stacked [steps, ...], replica-averaged.
        Parameters land back in the scope as ordinary consensus arrays
        (checkpoint/save need no special handling)."""
        self._maybe_preflight(program, feed, fetch_list)
        from ..parallel.async_sgd import build_local_sgd_fn

        if program is None:
            program = core.default_main_program()
        scope = scope or global_scope()
        mesh = self._resolve_mesh()
        if mesh is None or "data" not in mesh.axis_names:
            raise ValueError(
                "run_async_local needs a mesh with a 'data' axis "
                "(Executor(mesh=...) or parallel.set_default_mesh)"
            )
        from ..parallel.mesh import spans_processes

        if spans_processes(mesh):
            raise NotImplementedError(
                "run_async_local is single-controller for now: feeds "
                "enter as whole global arrays, not per-process shards "
                "(the _globalize_feeds assembly the sync path does is "
                "not wired here yet)"
            )
        if program.shardings:
            raise ValueError(
                "run_async_local composes with data parallelism only; "
                "drop the tensor-parallel shard_parameter annotations "
                "(replicas must own complete models): %r"
                % sorted(program.shardings)
            )
        block = program.global_block()
        fetch_names = [_feed_name(f) for f in fetch_list or []]
        persist_names = sorted(
            v.name for v in program.list_vars() if v.persistable
        )
        feed_arrays: Dict[str, Any] = {}
        for name, value in (feed or {}).items():
            data, lod = _split_lod_feed(value)
            if lod is not None:
                raise NotImplementedError(
                    "run_async_local supports dense feeds only (LoD "
                    "batches change shape per step)"
                )
            var = block.var(name) if block.has_var(name) else None
            feed_arrays[name] = _to_device_dtype(data, var)
        persist_in = {n: scope.get(n) for n in persist_names if n in scope}

        feed_sig = tuple(
            (n, tuple(a.shape), str(a.dtype))
            for n, a in sorted(feed_arrays.items())
        )
        key = (
            "async_local", program.uid, program.version, program.amp,
            program.remat,
            feed_sig, tuple(fetch_names),
            tuple(sorted(persist_in.keys())),
            int(steps), int(sync_every), mesh,
        )
        entry = self._cache.get(key)
        if entry is None:
            step, persist_out = build_step_fn(
                program,
                feed_names=list(feed_arrays.keys()),
                fetch_names=fetch_names,
                persist_names=persist_names,
                persist_in=list(persist_in.keys()),
            )
            if set(persist_out) != set(persist_in.keys()):
                raise ValueError(
                    "run_async_local requires the program to update (not "
                    "create) persistables; missing from scope: %r"
                    % sorted(set(persist_out) - set(persist_in))
                )
            fn = build_local_sgd_fn(
                step, mesh,
                feed_names=list(feed_arrays.keys()),
                steps=int(steps), sync_every=int(sync_every),
            )
            entry = jax.jit(fn, donate_argnums=(0,))
            self._cache[key] = entry

        self._run_counter += 1
        rng = jax.random.fold_in(
            jax.random.PRNGKey(program.random_seed), self._run_counter
        )
        fetches, new_persist = entry(persist_in, feed_arrays, rng)
        _flush_print_effects(program)
        return _finish_run(
            scope, fetch_names, fetches, new_persist, return_numpy
        )

    # ------------------------------------------------------------------
    def _execute(
        self,
        program,
        feed,
        fetch_list,
        scope,
        return_numpy,
        use_cache: bool,
        steps: Optional[int],
        scan_feeds: bool,
    ):
        from .core.lowering import build_multi_step_fn

        if program is None:
            program = core.default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []
        scope = scope or global_scope()

        block = program.global_block()
        fetch_names = [_feed_name(f) for f in fetch_list]
        persist_names = sorted(v.name for v in program.list_vars() if v.persistable)

        feed_arrays: Dict[str, Any] = {}
        for name, value in feed.items():
            var = block.var(name) if block.has_var(name) else None
            data, lod = _split_lod_feed(value)
            feed_arrays[name] = _to_device_dtype(data, var)
            if lod is not None:
                # rows are described by the FINEST level; a coarser outer
                # level (2-level beam-search feeds) rides a second side-band
                feed_arrays[lod_key(name)] = np.asarray(lod[-1], np.int32)
                if len(lod) > 1:
                    feed_arrays[name + LOD_SRC] = np.asarray(lod[0], np.int32)
        # LoD side-band offsets are never scanned: their leading dim is the
        # offset count, not steps
        scanned = (
            set(n for n in feed_arrays if "@" not in n)
            if scan_feeds
            else set()
        )

        mesh = self._resolve_mesh()
        if mesh is not None:
            from ..parallel.mesh import spans_processes

            if spans_processes(mesh):
                feed_arrays = _globalize_feeds(mesh, feed_arrays, scanned)

        feed_sig = tuple(
            (n, tuple(a.shape), str(a.dtype)) for n, a in sorted(feed_arrays.items())
        )
        # static time extent for RNN padding: bucket the batch's true max
        # sequence length to a power of two so recompiles happen per bucket,
        # not per batch composition (kernels_rnn.py docstring). Per-feed
        # buckets let ops with very different raggedness (CTC frames vs
        # labels) each pad tightly.
        seq_maxlen, seq_buckets = _lod_bucket(feed_arrays)
        persist_in = {n: scope.get(n) for n in persist_names if n in scope}

        # profiler block active: interpret-mode timed run (per-op cost
        # table, reference profiler.cc:198 ParseEvents) — single-step,
        # single-chip only
        from .profiler import active_op_collector

        collector = active_op_collector()
        if collector is not None and steps is None and mesh is None:
            from .core.lowering import profile_ops

            self._run_counter += 1
            rng = jax.random.fold_in(
                jax.random.PRNGKey(program.random_seed), self._run_counter
            )
            env: Dict[str, Any] = {}
            env.update(persist_in)
            env.update(feed_arrays)
            fetches, new_persist = profile_ops(
                program, env, fetch_names, persist_names, collector,
                base_key=rng, seq_maxlen=seq_maxlen,
                seq_buckets=seq_buckets,
            )
            _flush_print_effects(program)
            return _finish_run(
                scope, fetch_names, fetches, new_persist, return_numpy
            )
        if mesh is not None:
            # place persistables on their target shardings up-front (no-op
            # when already placed; once after startup for TP params created
            # replicated by a startup program that has no annotations)
            from jax.sharding import NamedSharding

            from ..parallel.mesh import replicated

            rep = replicated(mesh)
            for n in list(persist_in.keys()):
                spec = program.shardings.get(n)
                target = NamedSharding(mesh, spec) if spec is not None else rep
                arr = persist_in[n]
                if getattr(arr, "sharding", None) != target:
                    persist_in[n] = jax.device_put(arr, target)
        # sharding annotations are part of the compiled artifact: fingerprint
        # them so shard_parameter() after a run is not silently ignored
        shard_fp = tuple(sorted((k, str(v)) for k, v in program.shardings.items()))
        key = (
            program.uid,
            program.version,
            program.amp,
            program.remat,
            feed_sig,
            tuple(fetch_names),
            tuple(sorted(persist_in.keys())),
            steps,
            scan_feeds,
            shard_fp,
            seq_maxlen,
            tuple(sorted(seq_buckets.items())),
        ) + ((mesh,) if mesh is not None else ())  # Mesh hashes by devices+axes
        entry = self._cache.get(key) if use_cache else None
        if entry is None:
            if steps is None:
                fn, persist_out = build_step_fn(
                    program,
                    feed_names=list(feed_arrays.keys()),
                    fetch_names=fetch_names,
                    persist_names=persist_names,
                    persist_in=list(persist_in.keys()),
                    seq_maxlen=seq_maxlen,
                    seq_buckets=seq_buckets,
                )
            else:
                fn, persist_out = build_multi_step_fn(
                    program,
                    feed_names=list(feed_arrays.keys()),
                    fetch_names=fetch_names,
                    persist_names=persist_names,
                    steps=steps,
                    persist_in=list(persist_in.keys()),
                    scanned_feeds=scanned,
                    seq_maxlen=seq_maxlen,
                    seq_buckets=seq_buckets,
                )
            jit_kwargs = {}
            if mesh is not None:
                jit_kwargs = _mesh_jit_kwargs(
                    mesh,
                    program,
                    feed_arrays,
                    list(persist_in.keys()),
                    persist_out,
                    fetch_names,
                    scanned_feeds=scanned,
                )
            entry = jax.jit(fn, donate_argnums=(0,), **jit_kwargs)
            if use_cache:
                self._cache[key] = entry

        self._run_counter += 1
        rng = jax.random.fold_in(
            jax.random.PRNGKey(program.random_seed), self._run_counter
        )
        # aval snapshot BEFORE the call (args are donated): lets the
        # compiled-step profiler re-lower this exact signature to read
        # the scheduled HLO. Gated — the tree_map over every param is
        # wasted work on ordinary training steps.
        if self._capture_avals:
            # host snapshot BEFORE the call (args are donated): lets the
            # compiled-step profiler rebuild fresh device args per timed
            # run and measure pure device time (ADVICE r4: exe.run()
            # end-to-end folds host feed/fetch overhead into op rows)
            host_snap = jax.tree_util.tree_map(
                lambda a: np.asarray(a) if hasattr(a, "shape") else a,
                (persist_in, feed_arrays, rng),
            )
            self._last_exec = (
                entry,
                jax.tree_util.tree_map(
                    lambda a: jax.ShapeDtypeStruct(
                        getattr(a, "shape", ()), getattr(a, "dtype", None)
                    ),
                    host_snap,
                ),
                host_snap,
            )
        fetches, new_persist = entry(persist_in, feed_arrays, rng)
        _flush_print_effects(program)
        return _finish_run(
            scope, fetch_names, fetches, new_persist, return_numpy
        )

    # convenience used by inference/serving paths ----------------------
    def close(self):
        self._cache.clear()
        # the profiler's aval/host-arg snapshot pins a compiled entry
        # plus a full host copy of the params — the LRU bound must not
        # be exceeded by a stale capture after close
        self._last_exec = None

    def cache_stats(self) -> Dict[str, int]:
        """Compilation-cache occupancy counters: size/capacity/hits/
        misses/evictions (observability for long-lived processes)."""
        return self._cache.stats()


def _flush_print_effects(program):
    """If the program contains a print op, block on pending jax.debug
    callbacks so debug output lands before run() returns (they would
    otherwise be dropped at interpreter teardown). The answer is
    memoized ON the program (version-keyed, dies with it) — no per-step
    op scan and no global cache to leak."""
    memo = getattr(program, "_print_flag", None)
    if memo is None or memo[0] != program.version:
        flag = any(
            op.type == "print" for blk in program.blocks for op in blk.ops
        )
        program._print_flag = memo = (program.version, flag)
    if memo[1]:
        jax.effects_barrier()


def _finish_run(scope, fetch_names, fetches, new_persist, return_numpy):
    """Shared run tail: persist write-back, NaN guard, numpy conversion."""
    for n, v in new_persist.items():
        scope.set(n, v)
    _maybe_check_nan_inf(fetch_names, fetches, new_persist)
    if return_numpy:
        return [np.asarray(f) for f in fetches]
    return fetches


def _maybe_check_nan_inf(fetch_names, fetches, new_persist):
    """Opt-in runtime numerics guard: set PADDLE_TPU_CHECK_NUMERICS=1
    (or the legacy FLAGS.check_nan_inf / PADDLE_FLAG_CHECK_NAN_INF)
    and every run scans the step's fetches and updated persistables for
    NaN/Inf, raising FloatingPointError that NAMES each offending var
    and whether it was a fetch or a persistable — the runtime
    counterpart of the static pre-flight (`validate=True`). Reference
    parity: executor.cc:30,132-140 scanned every op output per step;
    the fused XLA step has no per-op boundary, so the scan runs on the
    step's outputs after each run. Off by default: the scan forces a
    device->host copy of every fetched/updated array."""
    from ..utils import FLAGS

    if not (FLAGS.check_nan_inf or os.environ.get(
            "PADDLE_TPU_CHECK_NUMERICS", "") not in ("", "0")):
        return
    bad = []
    for kind, pairs in (("fetch", list(zip(fetch_names, fetches))),
                        ("persistable", list(new_persist.items()))):
        for name, v in pairs:
            arr = np.asarray(v)
            if (np.issubdtype(arr.dtype, np.floating)
                    and not np.isfinite(arr).all()):
                n_bad = int(arr.size - np.isfinite(arr).sum())
                bad.append("%s %r (%d/%d non-finite)"
                           % (kind, name, n_bad, arr.size))
    if bad:
        raise FloatingPointError(
            "check_numerics: non-finite values in %s" % "; ".join(bad)
        )


def _lod_bucket(feed_arrays):
    """Bucket each fed LoD's max sequence length up to the next power of
    two (min 8). Returns (global_max_bucket_or_None, {lod_name: bucket})."""
    bucket = bucket_pow2

    per_name = {}
    m = 0
    for n, a in feed_arrays.items():
        if n.endswith(LOD_SUFFIX):
            d = np.diff(np.asarray(a))
            if d.size and int(d.max()) > 0:
                per_name[n] = bucket(int(d.max()))
                m = max(m, int(d.max()))
    return (bucket(m) if m else None), per_name


def _split_lod_feed(value):
    """Accept numpy arrays, (data, lod) tuples, and objects exposing
    `.data/.lod` (our LoDTensor helper). Device-resident jax arrays
    pass through UNTOUCHED — np.asarray on them is a device->host copy
    that would defeat the device-resident fast path (_to_device_dtype)
    and, through a remote tunnel, re-cross the wire per run call."""
    if isinstance(value, tuple) and len(value) == 2 and not np.isscalar(value[0]):
        data, lod = value
        if not isinstance(data, jax.Array):
            data = np.asarray(data)
        return data, _flatten_lod(lod)
    if hasattr(value, "lod") and hasattr(value, "data"):
        return np.asarray(value.data), _flatten_lod(value.lod())
    if isinstance(value, jax.Array):
        return value, None
    return np.asarray(value), None


def _flatten_lod(lod):
    """Normalise a fed LoD to a list of levels (each an int32 offsets
    vector). Reference feeds lod as [[..level0..], [..level1..]]."""
    if lod is None:
        return None
    if len(lod) and isinstance(lod[0], (list, tuple, np.ndarray)):
        return [np.asarray(lv, np.int32) for lv in lod]
    return [np.asarray(lod, np.int32)]


def _globalize_feeds(mesh, feed_arrays, scanned_feeds=()):
    """Multi-controller (DCN) path: each process feeds its process-LOCAL
    batch; assemble the global jax.Array per feed so the jitted SPMD step
    sees one logical batch spanning the pod (replaces the reference's
    per-trainer DataProvider split + pserver/NCCL aggregation —
    RemoteParameterUpdater.h:55, distribute_transpiler.py:132).

    Dense feeds shard their batch dim (axis 0, or axis 1 for scanned
    multi-step feeds whose leading dim is [steps]) over the 'data' axis.
    A non-divisible batch is an error, not a silent fallback — replicas
    built from divergent per-process data would desynchronise training
    undetectably. On a mesh with NO 'data' axis (pure TP/SP serving),
    feeds replicate; every process must then feed identical values.

    Ragged (LoD) feeds: every process contributes its local packed rows
    + offsets through a host allgather, and the exact global packed
    array + global offsets are rebuilt and fed REPLICATED (see
    _globalize_ragged — the offsets-vector LoD contract cannot express
    the inter-block gaps a sharded-padded layout would need). Every
    process must feed the same NUMBER of sequences (equal local batch,
    the SPMD contract); lengths may diverge freely (reference:
    variable-length Arguments per trainer, parameter/Argument.h:84)."""
    import jax as _jax
    from jax.sharding import NamedSharding, PartitionSpec

    from ..parallel.mesh import data_parallel_axes

    data_axes, n_data = data_parallel_axes(mesh)
    has_data = bool(data_axes)
    out = {}
    lod_bases = {
        n[: -len(LOD_SUFFIX)] for n in feed_arrays if n.endswith(LOD_SUFFIX)
    }
    for name, arr in feed_arrays.items():
        if isinstance(arr, _jax.Array) and not arr.is_fully_addressable:
            out[name] = arr  # caller already built a global array
            continue
        if name in lod_bases:
            _globalize_ragged(mesh, feed_arrays, name, out)
            continue
        if "@" in name:
            if name.split("@")[0] in lod_bases:
                continue  # handled together with its base feed
            raise NotImplementedError(
                "feed %r: only @LOD side-bands are supported on a "
                "multi-process mesh" % name
            )
        arr = np.asarray(arr)
        batch_axis = 1 if name in scanned_feeds else 0
        if has_data and arr.ndim > batch_axis and arr.shape[batch_axis] > 0:
            spec = [None] * arr.ndim
            spec[batch_axis] = data_axes
            sharding = NamedSharding(mesh, PartitionSpec(*spec))
        else:
            sharding = NamedSharding(mesh, PartitionSpec())
        try:
            out[name] = _jax.make_array_from_process_local_data(sharding, arr)
        except ValueError as e:
            # NO silent replicate fallback: replicas assembled from
            # divergent per-process batches would desynchronise training
            # undetectably
            raise ValueError(
                "feed %r local shape %s does not shard over the mesh's "
                "data-parallel tiers %s (%d-way total, %d processes); "
                "pad the batch or drop the remainder on the host: %s"
                % (name, arr.shape, list(data_axes), n_data,
                   _jax.process_count(), e)
            )
    return out


def _globalize_ragged(mesh, feed_arrays, name, out):
    """Assemble a global ragged feed: every process contributes its local
    packed rows + offsets via a host allgather (transport-padded to a
    power-of-two bucket so shapes agree), and the TRUE global packed
    array + exact global offsets are rebuilt host-side and fed
    replicated. Exact semantics — the global batch is byte-identical to
    a single process feeding all sequences, so losses match the
    single-process oracle.

    Perf note: the ragged payload replicates across processes (token ids
    and LoD side-bands are small next to activations; the reference's
    pserver path likewise shipped whole Arguments per trainer,
    Argument.h:84). Sharding the packed rows over 'data' instead would
    need per-sequence (start, len) gaps that the offsets-vector LoD
    contract cannot express."""
    import jax as _jax
    from jax.experimental import multihost_utils

    data = np.asarray(feed_arrays[name])
    offsets = np.asarray(feed_arrays[lod_key(name)], np.int32)
    nproc = _jax.process_count()
    total = data.shape[0]
    n_seqs = offsets.shape[0] - 1

    # agree on shapes: [total, n_seqs] from every process
    gathered = np.asarray(
        multihost_utils.process_allgather(
            np.asarray([total, n_seqs], np.int64)
        )
    ).reshape(nproc, 2)
    if not (gathered[:, 1] == n_seqs).all():
        raise ValueError(
            "ragged feed %r: every process must feed the SAME number of "
            "sequences (got %s); lengths may differ, counts may not"
            % (name, gathered[:, 1].tolist())
        )
    bucket = 8
    while bucket < int(gathered[:, 0].max()):
        bucket *= 2

    pad = bucket - total
    padded = np.concatenate(
        [data, np.zeros((pad,) + data.shape[1:], data.dtype)]
    ) if pad else data
    all_data = np.asarray(
        multihost_utils.process_allgather(padded)
    ).reshape((nproc, bucket) + data.shape[1:])
    all_offsets = np.asarray(
        multihost_utils.process_allgather(offsets.astype(np.int64))
    ).reshape(nproc, n_seqs + 1)

    # strip transport padding; rebuild the exact global packed array
    out[name] = np.concatenate(
        [all_data[p, : int(all_offsets[p, -1])] for p in range(nproc)]
    )
    parts = [np.zeros((1,), np.int64)]
    base = 0
    for p in range(nproc):
        parts.append(all_offsets[p, 1:] + base)
        base += int(all_offsets[p, -1])
    out[lod_key(name)] = np.concatenate(parts).astype(np.int32)

    src_key = name + LOD_SRC
    if src_key in feed_arrays:
        src = np.asarray(feed_arrays[src_key], np.int64)
        all_src = np.asarray(
            multihost_utils.process_allgather(src)
        ).reshape(nproc, -1)
        sparts = [np.zeros((1,), np.int64)]
        sbase = 0
        for p in range(nproc):
            sparts.append(all_src[p, 1:] + sbase)
            sbase += int(all_src[p, -1])
        out[src_key] = np.concatenate(sparts).astype(np.int32)


def _mesh_jit_kwargs(
    mesh, program, feed_arrays, persist_in_keys, persist_out, fetch_names,
    scanned_feeds=(),
):
    """Build in/out shardings for the step function under a mesh.

    Feeds: batch dim over 'data' (replicated if not divisible or 0-d).
    Persistables: program.shardings[name] if annotated (TP), else
    replicated. Fetches: replicated (they are scalars/metrics in practice).
    LoD offset side-bands are replicated.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    from ..parallel.mesh import data_parallel_axes, replicated

    rep = replicated(mesh)
    # batch dim shards over the mesh's data-parallel tiers (dcn* across
    # slices outermost, 'data' within — one definition shared with
    # _globalize_feeds). XLA's sharding propagation inserts the gradient
    # reduction over every tier, riding DCN only for the slice-crossing
    # part.
    data_axes, n_data = data_parallel_axes(mesh)

    def feed_shard(name, arr):
        if "@" in name:  # LoD / beam side-bands are replicated
            return rep
        # scanned feeds carry a leading [steps] dim; the batch is axis 1
        batch_axis = 1 if name in scanned_feeds else 0
        if (
            data_axes
            and arr.ndim > batch_axis
            and arr.shape[batch_axis] > 0
            and arr.shape[batch_axis] % n_data == 0
        ):
            spec = [None] * arr.ndim
            spec[batch_axis] = data_axes
            return NamedSharding(mesh, PartitionSpec(*spec))
        return rep

    def persist_shard(name):
        spec = program.shardings.get(name)
        if spec is None:
            return rep
        return NamedSharding(mesh, spec)

    in_shardings = (
        {n: persist_shard(n) for n in persist_in_keys},
        {n: feed_shard(n, a) for n, a in feed_arrays.items()},
        rep,
    )
    out_shardings = (
        [rep for _ in fetch_names],
        {n: persist_shard(n) for n in persist_out},
    )
    return {"in_shardings": in_shardings, "out_shardings": out_shardings}


_DTYPE_MAP = {"float64": "float32", "int64": "int32"}


def _to_device_dtype(arr, var: Optional[Variable]):
    """Feeds are normalised to TPU-friendly dtypes: f64->f32, i64->i32
    (the TPU has no 64-bit compute path worth using). Device-resident
    arrays of the right dtype pass through untouched — no host round-trip."""
    if isinstance(arr, jax.Array):
        want = None
        if var is not None and var.dtype is not None:
            want = _DTYPE_MAP.get(var.dtype, var.dtype)
        if want is None or str(arr.dtype) == want:
            return arr
        return arr.astype(want)
    arr = np.asarray(arr)
    if var is not None and var.dtype is not None:
        want = _DTYPE_MAP.get(var.dtype, var.dtype)
        if str(arr.dtype) != want:
            arr = arr.astype(want)
    else:
        want = _DTYPE_MAP.get(str(arr.dtype))
        if want:
            arr = arr.astype(want)
    return arr
