"""Alias module: the reference package ships this (misspelled) name
(python/paddle/v2/fluid/debuger.py); the implementation lives in
debugger.py."""

from .debugger import *  # noqa: F401,F403
from .debugger import __all__  # noqa: F401
