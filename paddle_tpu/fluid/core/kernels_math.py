"""Math op kernels: elementwise family, mul/matmul, reductions, comparisons.

Capability parity with reference paddle/fluid/operators elementwise_*,
mul_op, matmul_op, reduce_op, scale_op, sum_op, clip ops, compare ops —
re-expressed as jnp/lax so XLA fuses them into neighbouring matmuls.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register_op


def _bcast_y(x, y, axis):
    """Fluid elementwise broadcast: align y's shape with x starting at `axis`
    (reference operators/elementwise_op_function.h trim-and-broadcast rule)."""
    if x.ndim == y.ndim:
        return y
    if axis is None or axis == -1:
        axis = x.ndim - y.ndim
    new_shape = [1] * x.ndim
    for i, s in enumerate(y.shape):
        new_shape[axis + i] = s
    return y.reshape(new_shape)


def _elementwise(fn):
    def kern(ctx, ins, attrs):
        x = ins["X"][0]
        y = _bcast_y(x, ins["Y"][0], attrs.get("axis", -1))
        out = fn(x, y)
        return {"Out": out}

    return kern


register_op("elementwise_add")(_elementwise(jnp.add))
register_op("elementwise_sub")(_elementwise(jnp.subtract))
register_op("elementwise_mul")(_elementwise(jnp.multiply))
register_op("elementwise_div")(_elementwise(jnp.divide))
register_op("elementwise_max")(_elementwise(jnp.maximum))
register_op("elementwise_min")(_elementwise(jnp.minimum))
register_op("elementwise_pow")(_elementwise(jnp.power))


def _compare(fn):
    def kern(ctx, ins, attrs):
        x = ins["X"][0]
        y = _bcast_y(x, ins["Y"][0], attrs.get("axis", -1))
        if isinstance(x, (np.ndarray, np.generic)) and isinstance(
            y, (np.ndarray, np.generic)
        ):
            # both host-concrete (loop counters): compare in numpy so While
            # conditions stay decidable at trace time (any jnp call would
            # stage into the trace and return a tracer)
            return {"Out": getattr(np, fn.__name__)(x, y)}
        return {"Out": fn(x, y)}

    return kern


register_op("less_than")(_compare(jnp.less))
register_op("less_equal")(_compare(jnp.less_equal))
register_op("greater_than")(_compare(jnp.greater))
register_op("greater_equal")(_compare(jnp.greater_equal))
register_op("equal")(_compare(jnp.equal))
register_op("not_equal")(_compare(jnp.not_equal))


def _logical2(fn):
    def kern(ctx, ins, attrs):
        return {"Out": fn(ins["X"][0], ins["Y"][0])}

    return kern


register_op("logical_and")(_logical2(jnp.logical_and))
register_op("logical_or")(_logical2(jnp.logical_or))
register_op("logical_xor")(_logical2(jnp.logical_xor))


@register_op("logical_not")
def _logical_not(ctx, ins, attrs):
    return {"Out": jnp.logical_not(ins["X"][0])}


@register_op("mul")
def _mul(ctx, ins, attrs):
    """Reference mul_op: flatten X by x_num_col_dims / Y by y_num_col_dims,
    2-D matmul, reshape back (operators/mul_op.cc)."""
    x, y = ins["X"][0], ins["Y"][0]
    xn = attrs.get("x_num_col_dims", 1)
    yn = attrs.get("y_num_col_dims", 1)
    x2 = x.reshape((int(np.prod(x.shape[:xn])), -1)) if x.ndim > 2 or xn != 1 else x
    y2 = y.reshape((int(np.prod(y.shape[:yn])), -1)) if y.ndim > 2 or yn != 1 else y
    out = x2 @ y2
    out_shape = x.shape[:xn] + y.shape[yn:]
    return {"Out": out.reshape(out_shape)}


@register_op("matmul")
def _matmul(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    tx = attrs.get("transpose_X", False)
    ty = attrs.get("transpose_Y", False)
    if x.ndim == 1:
        x = x.reshape(1, -1)
    if y.ndim == 1:
        y = y.reshape(-1, 1)
    if tx:
        x = jnp.swapaxes(x, -1, -2)
    if ty:
        y = jnp.swapaxes(y, -1, -2)
    out = jnp.matmul(x, y)
    alpha = attrs.get("alpha", 1.0)
    if alpha != 1.0:
        out = out * alpha
    return {"Out": out}


@register_op("sum")
def _sum(ctx, ins, attrs):
    xs = ins["X"]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": out}


@register_op("scale")
def _scale(ctx, ins, attrs):
    x = ins["X"][0]
    scale = attrs.get("scale", 1.0)
    bias = attrs.get("bias", 0.0)
    if attrs.get("bias_after_scale", True):
        return {"Out": x * scale + bias}
    return {"Out": (x + bias) * scale}


@register_op("mean")
def _mean(ctx, ins, attrs):
    # reference mean_op produces a [1] tensor
    return {"Out": jnp.mean(ins["X"][0]).reshape((1,))}


def _reduce(fn):
    def kern(ctx, ins, attrs):
        x = ins["X"][0]
        if attrs.get("reduce_all", False):
            out = fn(x)
            if attrs.get("keep_dim", False):
                out = out.reshape((1,) * x.ndim)
            else:
                out = out.reshape((1,))
            return {"Out": out}
        dim = attrs.get("dim", 0)
        dims = tuple(dim) if isinstance(dim, (list, tuple)) else (dim,)
        return {"Out": fn(x, axis=dims, keepdims=attrs.get("keep_dim", False))}

    return kern


register_op("reduce_sum")(_reduce(jnp.sum))
register_op("reduce_mean")(_reduce(jnp.mean))
register_op("reduce_max")(_reduce(jnp.max))
register_op("reduce_min")(_reduce(jnp.min))
register_op("reduce_prod")(_reduce(jnp.prod))


def _unary(fn):
    def kern(ctx, ins, attrs):
        return {"Out": fn(ins["X"][0])}

    return kern


register_op("square")(_unary(jnp.square))
register_op("sqrt")(_unary(jnp.sqrt))
register_op("rsqrt")(_unary(lambda x: jax.lax.rsqrt(x)))
register_op("exp")(_unary(jnp.exp))
register_op("log")(_unary(jnp.log))
register_op("abs")(_unary(jnp.abs))
register_op("ceil")(_unary(jnp.ceil))
register_op("floor")(_unary(jnp.floor))
register_op("round")(_unary(jnp.round))
register_op("reciprocal")(_unary(lambda x: 1.0 / x))
register_op("sin")(_unary(jnp.sin))
register_op("cos")(_unary(jnp.cos))
register_op("sign")(_unary(jnp.sign))


@register_op("pow")
def _pow(ctx, ins, attrs):
    return {"Out": jnp.power(ins["X"][0], attrs.get("factor", 1.0))}


@register_op("clip")
def _clip(ctx, ins, attrs):
    return {"Out": jnp.clip(ins["X"][0], attrs["min"], attrs["max"])}


@register_op("clip_by_norm")
def _clip_by_norm(ctx, ins, attrs):
    x = ins["X"][0]
    max_norm = attrs["max_norm"]
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    scale = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12), 1.0)
    return {"Out": x * scale}


@register_op("squared_l2_norm")
def _squared_l2_norm(ctx, ins, attrs):
    return {"Out": jnp.sum(jnp.square(ins["X"][0])).reshape((1,))}


@register_op("squared_l2_distance")
def _squared_l2_distance(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    sub = x - y
    return {
        "sub_result": sub,
        "Out": jnp.sum(jnp.square(sub), axis=-1, keepdims=True),
    }


@register_op("cos_sim")
def _cos_sim(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    xnorm = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
    ynorm = jnp.sqrt(jnp.sum(jnp.square(y), axis=-1, keepdims=True))
    out = jnp.sum(x * y, axis=-1, keepdims=True) / (xnorm * ynorm + 1e-12)
    return {"Out": out, "XNorm": xnorm, "YNorm": ynorm}


@register_op("increment")
def _increment(ctx, ins, attrs):
    # preserve X's dtype (reference increment_op keeps the variable type;
    # numpy would promote int + 1.0 to float64 and break loop counters)
    x = ins["X"][0]
    dt = x.dtype if hasattr(x, "dtype") else np.float32
    return {"Out": x + np.asarray(attrs.get("step", 1.0)).astype(dt)}


@register_op("cast")
def _cast(ctx, ins, attrs):
    return {"Out": ins["X"][0].astype(attrs["out_dtype"])}


@register_op("maxout")
def _maxout(ctx, ins, attrs):
    x = ins["X"][0]  # NCHW
    groups = attrs["groups"]
    n, c, h, w = x.shape
    return {"Out": jnp.max(x.reshape(n, c // groups, groups, h, w), axis=2)}


@register_op("l2_normalize")
def _l2_normalize(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    eps = attrs.get("epsilon", 1e-12)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True))
    norm = jnp.maximum(norm, eps)
    return {"Out": x / norm, "Norm": norm}


@register_op("isfinite")
def _isfinite(ctx, ins, attrs):
    flat = jnp.concatenate([jnp.ravel(jnp.isfinite(x)) for x in ins["X"]])
    return {"Out": jnp.all(flat).reshape((1,))}


@register_op("cumsum")
def _cumsum(ctx, ins, attrs):
    """Cumulative sum along an axis (reference cum_op.h): exclusive and
    reverse variants included."""
    x = ins["X"][0]
    axis = int(attrs.get("axis", -1))
    exclusive = bool(attrs.get("exclusive", False))
    rev = bool(attrs.get("reverse", False))
    if rev:
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis, dtype=x.dtype)
    if exclusive:
        out = out - x
    if rev:
        out = jnp.flip(out, axis)
    return {"Out": out}


@register_op("l1_norm")
def _l1_norm(ctx, ins, attrs):
    """Out = sum(|x|) (reference operators/l1_norm_op.h)."""
    return {"Out": jnp.sum(jnp.abs(ins["X"][0])).reshape((1,))}


@register_op("label_smooth")
def _label_smooth(ctx, ins, attrs):
    """out = (1-eps)*label + eps*prior (uniform 1/K without PriorDist) —
    reference operators/label_smooth_op.h."""
    x = ins["X"][0]
    eps = attrs.get("epsilon", 0.0)
    prior = ins.get("PriorDist")
    if prior and prior[0] is not None:
        smooth = eps * prior[0].reshape(1, -1)
    else:
        smooth = eps / x.shape[-1]
    return {"Out": (1.0 - eps) * x + smooth}
