"""NN op kernels: conv/pool/norm, activations, losses, dropout, metrics.

Parity targets: reference operators/conv_op.*, pool_op.*, batch_norm_op.*,
layer_norm_op.*, softmax/cross_entropy family, dropout_op, accuracy/top_k,
lrn_op — all expressed on NCHW layouts like the reference API, lowered to
`lax.conv_general_dilated` / `lax.reduce_window` so XLA tiles them onto the
MXU directly (no im2col: that is a GPU-ism the TPU backend does not need).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .registry import register_op


def _pair(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v), int(v))


def _triple(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * 3


@register_op("conv2d")
def _conv2d(ctx, ins, attrs):
    x = ins["Input"][0]  # NCHW
    w = ins["Filter"][0]  # OIHW
    strides = _pair(attrs.get("strides", [1, 1]))
    pads = _pair(attrs.get("paddings", [0, 0]))
    dil = _pair(attrs.get("dilations", [1, 1]))
    groups = int(attrs.get("groups", 1) or 1)
    out = lax.conv_general_dilated(
        x,
        w,
        window_strides=strides,
        padding=[(pads[0], pads[0]), (pads[1], pads[1])],
        rhs_dilation=dil,
        feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    return {"Output": out}


@register_op("depthwise_conv2d")
def _depthwise_conv2d(ctx, ins, attrs):
    attrs = dict(attrs)
    attrs["groups"] = ins["Input"][0].shape[1]
    return _conv2d(ctx, ins, attrs)


@register_op("conv2d_transpose")
def _conv2d_transpose(ctx, ins, attrs):
    x = ins["Input"][0]  # NCHW
    w = ins["Filter"][0]  # IOHW in reference conv2d_transpose
    if int(attrs.get("groups", 1) or 1) != 1:
        # reference conv_transpose_op.cc:101 enforces groups == 1
        raise NotImplementedError("conv2d_transpose requires groups == 1")
    strides = _pair(attrs.get("strides", [1, 1]))
    pads = _pair(attrs.get("paddings", [0, 0]))
    dil = _pair(attrs.get("dilations", [1, 1]))
    # Paddle's conv2d_transpose == conv2d's input-gradient (IOHW filter):
    # dilate the input by `stride`, pad by d*(k-1)-p, run a stride-1 conv
    # with the spatially-flipped, channel-swapped kernel. Output size is
    # (i-1)*s - 2p + d*(k-1) + 1, matching conv2d_transpose_op.cc.
    w = jnp.swapaxes(w, 0, 1)[:, :, ::-1, ::-1]  # IOHW -> OIHW, flipped
    kh = dil[0] * (w.shape[2] - 1)
    kw = dil[1] * (w.shape[3] - 1)
    out = lax.conv_general_dilated(
        x,
        w,
        window_strides=(1, 1),
        padding=[(kh - pads[0], kh - pads[0]), (kw - pads[1], kw - pads[1])],
        lhs_dilation=strides,
        rhs_dilation=dil,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    return {"Output": out}


@register_op("conv3d")
def _conv3d(ctx, ins, attrs):
    x = ins["Input"][0]  # NCDHW
    w = ins["Filter"][0]  # OIDHW
    strides = _triple(attrs.get("strides", [1, 1, 1]))
    pads = _triple(attrs.get("paddings", [0, 0, 0]))
    dil = _triple(attrs.get("dilations", [1, 1, 1]))
    out = lax.conv_general_dilated(
        x,
        w,
        window_strides=strides,
        padding=[(p, p) for p in pads],
        rhs_dilation=dil,
        feature_group_count=int(attrs.get("groups", 1) or 1),
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
    )
    return {"Output": out}


def _pool(x, pooling_type, ksize, strides, pads, global_pooling, ceil_mode=False,
          exclusive=True, nd=2):
    if global_pooling:
        ksize = x.shape[-nd:]
        pads = (0,) * nd
    window = (1, 1) + tuple(ksize)
    stride = (1, 1) + tuple(strides)
    padding = ((0, 0), (0, 0)) + tuple((p, p) for p in pads)
    if ceil_mode:
        # extend the upper pad so the last partial window is kept
        padding = list(padding)
        for i in range(nd):
            size = x.shape[2 + i] + 2 * pads[i]
            rem = (size - ksize[i]) % strides[i]
            extra = (strides[i] - rem) % strides[i] if rem else 0
            padding[2 + i] = (pads[i], pads[i] + extra)
        padding = tuple(padding)
    if pooling_type == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, init, lax.max, window, stride, padding)
    # avg pooling: exclusive counts only un-padded elements per window
    summed = lax.reduce_window(x.astype(jnp.float32), 0.0, lax.add, window, stride, padding)
    if exclusive and any(p[0] or p[1] for p in padding):
        ones = jnp.ones(x.shape, jnp.float32)
        counts = lax.reduce_window(ones, 0.0, lax.add, window, stride, padding)
        out = summed / counts
    else:
        out = summed / float(np.prod(ksize))
    return out.astype(x.dtype)


@register_op("pool2d")
def _pool2d(ctx, ins, attrs):
    x = ins["X"][0]
    out = _pool(
        x,
        attrs.get("pooling_type", "max"),
        _pair(attrs.get("ksize", [1, 1])),
        _pair(attrs.get("strides", [1, 1])),
        _pair(attrs.get("paddings", [0, 0])),
        attrs.get("global_pooling", False),
        attrs.get("ceil_mode", False),
        attrs.get("exclusive", True),
        nd=2,
    )
    return {"Out": out}


@register_op("pool3d")
def _pool3d(ctx, ins, attrs):
    x = ins["X"][0]
    out = _pool(
        x,
        attrs.get("pooling_type", "max"),
        _triple(attrs.get("ksize", [1, 1, 1])),
        _triple(attrs.get("strides", [1, 1, 1])),
        _triple(attrs.get("paddings", [0, 0, 0])),
        attrs.get("global_pooling", False),
        attrs.get("ceil_mode", False),
        attrs.get("exclusive", True),
        nd=3,
    )
    return {"Out": out}


@register_op("batch_norm")
def _batch_norm(ctx, ins, attrs):
    """Reference operators/batch_norm_op.cc: NCHW, per-channel affine,
    running stats updated in train mode with `momentum` EMA."""
    x = ins["X"][0]
    scale = ins["Scale"][0]
    bias = ins["Bias"][0]
    mean_in = ins["Mean"][0]
    var_in = ins["Variance"][0]
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    is_test = attrs.get("is_test", False) or ctx.is_test
    layout = attrs.get("data_layout", "NCHW")
    c_axis = 1 if layout == "NCHW" else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != c_axis)
    bshape = [1] * x.ndim
    bshape[c_axis] = x.shape[c_axis]

    # statistics ALWAYS accumulate in f32 (a bf16 E[x^2]-E[x]^2 loses
    # mass catastrophically); the convert fuses into the reduce so the
    # HBM read stays bf16. Only the per-channel apply runs in x.dtype.
    f32 = jnp.float32
    if is_test:
        mean = mean_in.astype(f32)
        var = var_in.astype(f32)
        mean_out, var_out = mean_in, var_in
        saved_mean = mean
        saved_var = 1.0 / jnp.sqrt(var + eps)
    else:
        xs = x.astype(f32)
        mean = jnp.mean(xs, axis=axes)
        var = jnp.mean(jnp.square(xs), axis=axes) - jnp.square(mean)
        mean_out = mean_in.astype(f32) * momentum + mean * (1.0 - momentum)
        var_out = var_in.astype(f32) * momentum + var * (1.0 - momentum)
        saved_mean = mean
        saved_var = 1.0 / jnp.sqrt(var + eps)
    # running-stat EMA must not leak gradients into scale/bias updates
    mean = lax.stop_gradient(mean) if is_test else mean
    inv = 1.0 / jnp.sqrt(var + eps)
    # fold (mean, inv, scale, bias) into ONE per-channel multiply-add in
    # x's dtype — tiny vectors, so the f32->bf16 cast costs nothing and
    # the big activation tensor never leaves bf16
    eff_scale = (inv * scale.astype(f32)).astype(x.dtype)
    eff_bias = (
        bias.astype(f32) - mean * inv * scale.astype(f32)
    ).astype(x.dtype)
    y = x * eff_scale.reshape(bshape) + eff_bias.reshape(bshape)
    return {
        "Y": y,
        "MeanOut": lax.stop_gradient(mean_out),
        "VarianceOut": lax.stop_gradient(var_out),
        "SavedMean": saved_mean,
        "SavedVariance": saved_var,
    }


@register_op("layer_norm")
def _layer_norm(ctx, ins, attrs):
    x = ins["X"][0]
    eps = attrs.get("epsilon", 1e-5)
    begin = attrs.get("begin_norm_axis", 1)
    axes = tuple(range(begin, x.ndim))
    # statistics in f32 (see batch_norm); apply in x.dtype
    xs = x.astype(jnp.float32)
    mean = jnp.mean(xs, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xs - mean), axis=axes, keepdims=True)
    y = ((xs - mean) / jnp.sqrt(var + eps)).astype(x.dtype)
    if ins.get("Scale"):
        y = y * ins["Scale"][0].reshape((1,) * begin + x.shape[begin:])
    if ins.get("Bias"):
        y = y + ins["Bias"][0].reshape((1,) * begin + x.shape[begin:])
    return {"Y": y, "Mean": mean.reshape(x.shape[:begin]), "Variance": var.reshape(x.shape[:begin])}


@register_op("lrn")
def _lrn(ctx, ins, attrs):
    x = ins["X"][0]  # NCHW
    n = attrs.get("n", 5)
    k = attrs.get("k", 2.0)
    alpha = attrs.get("alpha", 1e-4)
    beta = attrs.get("beta", 0.75)
    # accumulate the cross-channel sum of squares in f32 (bf16-safe)
    sq = jnp.square(x.astype(jnp.float32))
    half = n // 2
    acc = lax.reduce_window(
        sq, 0.0, lax.add, (1, n, 1, 1), (1, 1, 1, 1), ((0, 0), (half, n - 1 - half), (0, 0), (0, 0))
    )
    mid = k + alpha * acc
    return {"Out": x * jnp.power(mid, -beta).astype(x.dtype), "MidOut": mid}


# --- activations --------------------------------------------------------

def _act(fn):
    def kern(ctx, ins, attrs):
        return {"Out": fn(ins["X"][0])}

    return kern


register_op("relu")(_act(jax.nn.relu))
register_op("sigmoid")(_act(jax.nn.sigmoid))
register_op("tanh")(_act(jnp.tanh))
register_op("softsign")(_act(jax.nn.soft_sign))
register_op("softplus")(_act(jax.nn.softplus))
register_op("relu6")(_act(lambda x: jnp.clip(x, 0.0, 6.0)))
register_op("gelu")(_act(jax.nn.gelu))
register_op("elu")(_act(jax.nn.elu))
register_op("silu")(_act(jax.nn.silu))
register_op("logsigmoid")(_act(jax.nn.log_sigmoid))
register_op("tanh_shrink")(_act(lambda x: x - jnp.tanh(x)))
register_op("softshrink")(
    lambda ctx, ins, attrs: {
        "Out": jnp.sign(ins["X"][0])
        * jnp.maximum(jnp.abs(ins["X"][0]) - attrs.get("lambda", 0.5), 0.0)
    }
)
register_op("hard_shrink")(
    lambda ctx, ins, attrs: {
        "Out": jnp.where(
            jnp.abs(ins["X"][0]) > attrs.get("threshold", 0.5), ins["X"][0], 0.0
        )
    }
)
register_op("thresholded_relu")(
    lambda ctx, ins, attrs: {
        "Out": jnp.where(ins["X"][0] > attrs.get("threshold", 1.0), ins["X"][0], 0.0)
    }
)
register_op("hard_sigmoid")(
    lambda ctx, ins, attrs: {
        "Out": jnp.clip(
            ins["X"][0] * attrs.get("slope", 0.2) + attrs.get("offset", 0.5), 0.0, 1.0
        )
    }
)
register_op("leaky_relu")(
    lambda ctx, ins, attrs: {
        "Out": jax.nn.leaky_relu(ins["X"][0], attrs.get("alpha", 0.02))
    }
)
register_op("brelu")(
    lambda ctx, ins, attrs: {
        "Out": jnp.clip(ins["X"][0], attrs.get("t_min", 0.0), attrs.get("t_max", 24.0))
    }
)
register_op("stanh")(
    lambda ctx, ins, attrs: {
        "Out": attrs.get("scale_b", 1.7159)
        * jnp.tanh(ins["X"][0] * attrs.get("scale_a", 2.0 / 3.0))
    }
)
register_op("swish")(
    lambda ctx, ins, attrs: {
        "Out": ins["X"][0] * jax.nn.sigmoid(attrs.get("beta", 1.0) * ins["X"][0])
    }
)


@register_op("prelu")
def _prelu(ctx, ins, attrs):
    x = ins["X"][0]
    alpha = ins["Alpha"][0]
    if alpha.size > 1 and x.ndim >= 2:
        if alpha.size == int(np.prod(x.shape[1:])):
            # element mode: one alpha per element of a sample
            alpha = alpha.reshape((1,) + tuple(x.shape[1:]))
        else:  # channel mode: one alpha per channel (axis 1)
            alpha = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    return {"Out": jnp.where(x > 0, x, alpha * x)}


@register_op("softmax")
def _softmax(ctx, ins, attrs):
    return {"Out": jax.nn.softmax(ins["X"][0], axis=-1)}


@register_op("log_softmax")
def _log_softmax(ctx, ins, attrs):
    return {"Out": jax.nn.log_softmax(ins["X"][0], axis=-1)}


# --- losses -------------------------------------------------------------

@register_op("cross_entropy")
def _cross_entropy(ctx, ins, attrs):
    """Reference operators/cross_entropy_op.cc: hard labels are int64 [N,1],
    soft labels are a distribution with X's shape."""
    x = ins["X"][0]
    label = ins["Label"][0]
    eps = 1e-8
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * jnp.log(x + eps), axis=-1, keepdims=True)
    else:
        lbl = label.reshape(label.shape[0]).astype(jnp.int32)
        picked = jnp.take_along_axis(x, lbl[:, None], axis=-1)
        loss = -jnp.log(picked + eps)
    return {"Y": loss}


@register_op("softmax_with_cross_entropy")
def _softmax_with_cross_entropy(ctx, ins, attrs):
    logits = ins["Logits"][0]
    label = ins["Label"][0]
    logp = jax.nn.log_softmax(logits, axis=-1)
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * logp, axis=-1, keepdims=True)
    else:
        lbl = label.reshape(label.shape[0]).astype(jnp.int32)
        loss = -jnp.take_along_axis(logp, lbl[:, None], axis=-1)
    return {"Softmax": jnp.exp(logp), "Loss": loss}


@register_op("sigmoid_cross_entropy_with_logits")
def _sigmoid_ce(ctx, ins, attrs):
    x = ins["X"][0]
    label = ins["Label"][0]
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return {"Out": loss}


@register_op("hinge_loss")
def _hinge_loss(ctx, ins, attrs):
    logits = ins["Logits"][0]
    labels = ins["Labels"][0].astype(logits.dtype)
    return {"Loss": jnp.maximum(0.0, 1.0 - (2.0 * labels - 1.0) * logits)}


@register_op("huber_loss")
def _huber_loss(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    delta = attrs.get("delta", 1.0)
    r = y - x
    absr = jnp.abs(r)
    loss = jnp.where(absr <= delta, 0.5 * r * r, delta * (absr - 0.5 * delta))
    return {"Out": loss, "Residual": r}


@register_op("log_loss")
def _log_loss(ctx, ins, attrs):
    p = ins["Predicted"][0]
    l = ins["Labels"][0]
    eps = attrs.get("epsilon", 1e-4)
    return {"Loss": -l * jnp.log(p + eps) - (1 - l) * jnp.log(1 - p + eps)}


@register_op("smooth_l1_loss")
def _smooth_l1(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    sigma = attrs.get("sigma", 1.0)
    s2 = sigma * sigma
    diff = x - y
    if ins.get("InsideWeight"):
        diff = diff * ins["InsideWeight"][0]
    a = jnp.abs(diff)
    val = jnp.where(a < 1.0 / s2, 0.5 * s2 * diff * diff, a - 0.5 / s2)
    if ins.get("OutsideWeight"):
        val = val * ins["OutsideWeight"][0]
    out = jnp.sum(val.reshape(val.shape[0], -1), axis=1, keepdims=True)
    return {"Out": out, "Diff": diff}


@register_op("margin_rank_loss")
def _margin_rank_loss(ctx, ins, attrs):
    x1, x2 = ins["X1"][0], ins["X2"][0]
    label = ins["Label"][0]
    margin = attrs.get("margin", 0.0)
    act = jnp.maximum(0.0, -label * (x1 - x2) + margin)
    return {"Out": act, "Activated": (act > 0).astype(x1.dtype)}


@register_op("rank_loss")
def _rank_loss(ctx, ins, attrs):
    label = ins["Label"][0]
    left, right = ins["Left"][0], ins["Right"][0]
    d = left - right
    return {"Out": jnp.log1p(jnp.exp(d)) - label * d}


# --- dropout / noise ----------------------------------------------------

@register_op("dropout")
def _dropout(ctx, ins, attrs):
    x = ins["X"][0]
    p = attrs.get("dropout_prob", 0.5)
    if attrs.get("is_test", False) or ctx.is_test:
        # reference downscales at inference (dropout_op.cc upscale_in_train=False default)
        return {"Out": x * (1.0 - p), "Mask": jnp.ones_like(x)}
    key = ctx.next_key()
    mask = jax.random.bernoulli(key, 1.0 - p, x.shape).astype(x.dtype)
    return {"Out": x * mask, "Mask": mask}


@register_op("gaussian_random_noise")
def _gaussian_noise(ctx, ins, attrs):
    x = ins["X"][0]
    key = ctx.next_key()
    return {"Out": x + jax.random.normal(key, x.shape, x.dtype) * attrs.get("std", 1.0)}


# --- metrics ------------------------------------------------------------

@register_op("top_k")
def _top_k(ctx, ins, attrs):
    x = ins["X"][0]
    k = attrs.get("k", 1)
    vals, idx = lax.top_k(x, k)
    return {"Out": vals, "Indices": idx.astype(jnp.int32)}


@register_op("accuracy")
def _accuracy(ctx, ins, attrs):
    indices = ins["Indices"][0]
    label = ins["Label"][0]
    lbl = label.reshape(label.shape[0], 1).astype(indices.dtype)
    correct = jnp.any(indices == lbl, axis=1)
    num_correct = jnp.sum(correct.astype(jnp.int32))
    total = jnp.asarray(label.shape[0], jnp.int32)
    acc = num_correct.astype(jnp.float32) / total.astype(jnp.float32)
    return {
        "Accuracy": acc.reshape((1,)),
        "Correct": num_correct.reshape((1,)),
        "Total": total.reshape((1,)),
    }


@register_op("auc")
def _auc(ctx, ins, attrs):
    """Batch-local AUC by threshold bucketing (reference auc_op.cc uses the
    trapezoidal rule over score thresholds)."""
    pred = ins["Out"][0]
    label = ins["Label"][0].reshape(-1)
    score = pred[:, 1] if pred.ndim == 2 and pred.shape[1] == 2 else pred.reshape(-1)
    num_thresholds = attrs.get("num_thresholds", 200)
    thresholds = jnp.linspace(0.0, 1.0, num_thresholds)
    pos = (label > 0).astype(jnp.float32)
    neg = 1.0 - pos
    above = score[None, :] >= thresholds[:, None]
    tp = jnp.sum(above * pos[None, :], axis=1)
    fp = jnp.sum(above * neg[None, :], axis=1)
    tpr = tp / jnp.maximum(jnp.sum(pos), 1.0)
    fpr = fp / jnp.maximum(jnp.sum(neg), 1.0)
    auc = -jnp.trapezoid(tpr, fpr)
    return {"AUC": auc.reshape((1,))}


@register_op("flash_attention")
def _flash_attention(ctx, ins, attrs):
    """Fused blockwise attention on [B, T, H, D] (pallas kernel,
    parallel/flash_attention.py — 2.2x faster than XLA full-matrix
    attention at T=4096 bf16 on chip; interpret mode on CPU). The fluid
    surface's door to the hot kernel: the compute runs through the same
    custom-vjp flash path the transformer flagship uses."""
    from ...parallel.flash_attention import flash_attention as _flash

    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    out = _flash(
        q, k, v,
        causal=bool(attrs.get("causal", False)),
        scale=attrs.get("scale") or None,
        interpret=jax.default_backend() == "cpu",
    )
    return {"Out": out}


# --- r4 op-tail: pooling-with-index / unpool / spp / conv3d_transpose ---


def _pool_with_index(x, ksize, strides, pads, global_pooling, nd):
    """Max pooling that also returns the argmax's flat index within the
    UNPADDED input plane (reference math/pooling.cc
    MaxPool2dWithIndexFunctor: index = h * input_w + w; windows are
    clipped to the input, so a padding position can never win). Static
    shapes throughout: windows are materialised as a gather (XLA folds
    it), argmax ties break on the first element in window scan order —
    the same (h, w[, d]) order the reference loop visits."""
    spatial = x.shape[2:]
    if global_pooling:
        ksize = spatial
        pads = (0,) * nd
    neg = jnp.finfo(x.dtype).min if jnp.issubdtype(x.dtype, jnp.floating) \
        else jnp.iinfo(x.dtype).min
    pad_cfg = ((0, 0), (0, 0)) + tuple((p, p) for p in pads)
    xp = jnp.pad(x, pad_cfg, constant_values=neg)
    out_dims = [
        (spatial[i] + 2 * pads[i] - ksize[i]) // strides[i] + 1
        for i in range(nd)
    ]
    # per-axis window index grids: idx[i] has shape [out_i, k_i]
    grids = [
        np.arange(out_dims[i])[:, None] * strides[i] + np.arange(ksize[i])
        for i in range(nd)
    ]
    # broadcast to [N, C, out..., k...]: axis layout (o1..on, k1..kn)
    ix = []
    for i in range(nd):
        shape = [1] * (2 * nd)
        shape[i] = out_dims[i]
        shape[nd + i] = ksize[i]
        ix.append(grids[i].reshape(shape))
    windows = xp[(slice(None), slice(None)) + tuple(ix)]
    # -> [N, C, o..., kprod]
    kprod = int(np.prod(ksize))
    windows = windows.reshape(windows.shape[: 2 + nd] + (kprod,))
    arg = jnp.argmax(windows, axis=-1)
    out = jnp.take_along_axis(windows, arg[..., None], axis=-1)[..., 0]
    # flat index in the unpadded plane: per window element, its padded
    # coordinate minus pad, row-majored over the input spatial dims
    coord = np.zeros((int(np.prod(out_dims)), kprod), np.int32)
    flat_mult = np.cumprod((spatial[1:] + (1,))[::-1])[::-1]  # row-major
    o_grid = np.meshgrid(*[np.arange(o) for o in out_dims], indexing="ij")
    k_grid = np.meshgrid(*[np.arange(k) for k in ksize], indexing="ij")
    for i in range(nd):
        c = (
            o_grid[i].reshape(-1, 1) * strides[i]
            + k_grid[i].reshape(1, -1)
            - pads[i]
        )
        coord += c.astype(np.int32) * int(flat_mult[i])
    coord = jnp.asarray(coord.reshape(tuple(out_dims) + (kprod,)))
    mask = jnp.take_along_axis(
        jnp.broadcast_to(coord, arg.shape + (kprod,)), arg[..., None],
        axis=-1,
    )[..., 0]
    return out, mask


@register_op("max_pool2d_with_index")
def _max_pool2d_with_index(ctx, ins, attrs):
    """Reference operators/pool_with_index_op.cc (2-D)."""
    out, mask = _pool_with_index(
        ins["X"][0],
        _pair(attrs.get("ksize", [1, 1])),
        _pair(attrs.get("strides", [1, 1])),
        _pair(attrs.get("paddings", [0, 0])),
        attrs.get("global_pooling", False),
        nd=2,
    )
    return {"Out": out, "Mask": mask}


@register_op("max_pool3d_with_index")
def _max_pool3d_with_index(ctx, ins, attrs):
    """Reference operators/pool_with_index_op.cc (3-D, NCDHW)."""
    out, mask = _pool_with_index(
        ins["X"][0],
        _triple(attrs.get("ksize", [1, 1, 1])),
        _triple(attrs.get("strides", [1, 1, 1])),
        _triple(attrs.get("paddings", [0, 0, 0])),
        attrs.get("global_pooling", False),
        nd=3,
    )
    return {"Out": out, "Mask": mask}


@register_op("unpool")
def _unpool(ctx, ins, attrs):
    """Max unpooling (reference operators/unpool_op.cc +
    math/unpooling.cc): scatter each input element to the output-plane
    position its Indices entry names; everything else is zero. Output
    size = (in-1)*stride - 2*pad + ksize per spatial dim."""
    x = ins["X"][0]  # [N, C, H, W]
    idx = ins["Indices"][0].astype(jnp.int32)
    ksize = _pair(attrs.get("ksize", [1, 1]))
    strides = _pair(attrs.get("strides", [1, 1]))
    pads = _pair(attrs.get("paddings", [0, 0]))
    n, c, h, w = x.shape
    oh = (h - 1) * strides[0] - 2 * pads[0] + ksize[0]
    ow = (w - 1) * strides[1] - 2 * pads[1] + ksize[1]
    flat = jnp.zeros((n, c, oh * ow), x.dtype)
    bi = jnp.arange(n).reshape(n, 1, 1)
    ci = jnp.arange(c).reshape(1, c, 1)
    out = flat.at[bi, ci, idx.reshape(n, c, -1)].set(
        x.reshape(n, c, -1), mode="drop"
    )
    return {"Out": out.reshape(n, c, oh, ow)}


@register_op("spp")
def _spp(ctx, ins, attrs):
    """Spatial pyramid pooling (reference operators/spp_op.cc): levels
    p = 0..H-1 pool to 2^p x 2^p bins (ksize = ceil(in/bins), stride =
    ksize, pad centers the grid), flatten and concatenate along
    channels*bins^2."""
    x = ins["X"][0]
    height = int(attrs.get("pyramid_height", 1))
    ptype = attrs.get("pooling_type", "max")
    n, c, h, w = x.shape
    parts = []
    for p in range(height):
        bins = 2 ** p
        kh = -(-h // bins)
        kw = -(-w // bins)
        ph = (kh * bins - h + 1) // 2
        pw = (kw * bins - w + 1) // 2
        lvl = _pool(
            x, ptype, (kh, kw), (kh, kw), (ph, pw),
            global_pooling=False, exclusive=True,
        )
        parts.append(lvl.reshape(n, c * bins * bins))
    return {"Out": jnp.concatenate(parts, axis=1)}


@register_op("conv3d_transpose")
def _conv3d_transpose(ctx, ins, attrs):
    """Reference operators/conv_transpose_op.cc (3-D): conv3d's
    input-gradient with an IODHW filter — dilate the input by stride and
    run a stride-1 conv with the flipped, channel-swapped kernel. Output
    size = (i-1)*s - 2p + d*(k-1) + 1 per spatial dim."""
    x = ins["Input"][0]  # NCDHW
    w = ins["Filter"][0]  # IODHW
    if int(attrs.get("groups", 1) or 1) != 1:
        # reference conv_transpose_op.cc:101 enforces groups == 1
        raise NotImplementedError("conv3d_transpose requires groups == 1")
    strides = _triple(attrs.get("strides", [1, 1, 1]))
    pads = _triple(attrs.get("paddings", [0, 0, 0]))
    dil = _triple(attrs.get("dilations", [1, 1, 1]))
    w = jnp.swapaxes(w, 0, 1)[:, :, ::-1, ::-1, ::-1]
    ks = [dil[i] * (w.shape[2 + i] - 1) for i in range(3)]
    out = lax.conv_general_dilated(
        x,
        w,
        window_strides=(1, 1, 1),
        padding=[(ks[i] - pads[i], ks[i] - pads[i]) for i in range(3)],
        lhs_dilation=strides,
        rhs_dilation=dil,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
    )
    return {"Output": out}


@register_op("norm")
def _norm(ctx, ins, attrs):
    """SSD-style cross-channel L2 normalisation with learned per-channel
    scale (reference operators/norm_op.h): out[n,c,h,w] =
    x / sqrt(eps + sum_c x^2) * scale[c]."""
    x = ins["X"][0]
    scale = ins["Scale"][0].reshape(-1)
    eps = attrs.get("epsilon", 1e-10)
    denom = jnp.sqrt(eps + jnp.sum(
        jnp.square(x.astype(jnp.float32)), axis=1, keepdims=True
    ))
    out = (x / denom) * scale.reshape(1, -1, *([1] * (x.ndim - 2)))
    return {"Out": out.astype(x.dtype)}


@register_op("bilinear_tensor_product")
def _bilinear_tensor_product(ctx, ins, attrs):
    """out[b,k] = x[b,:] @ W[k] @ y[b,:] + bias[k] (reference
    operators/bilinear_tensor_product_op.h)."""
    x, y = ins["X"][0], ins["Y"][0]
    w = ins["Weight"][0]  # [size, M, N]
    out = jnp.einsum("bm,kmn,bn->bk", x, w, y)
    if ins.get("Bias") and ins["Bias"][0] is not None:
        out = out + ins["Bias"][0].reshape(1, -1)
    return {"Out": out}


@register_op("modified_huber_loss")
def _modified_huber_loss(ctx, ins, attrs):
    """Reference operators/modified_huber_loss_op.h: a = x * (2y - 1);
    loss = -4a for a < -1, (1-a)^2 for a < 1, else 0. Y in {0, 1}."""
    x = ins["X"][0]
    y = ins["Y"][0].astype(x.dtype)
    a = x * (2.0 * y - 1.0)
    loss = jnp.where(
        a < -1.0, -4.0 * a,
        jnp.where(a < 1.0, jnp.square(1.0 - a), jnp.zeros_like(a)),
    )
    return {"IntermediateVal": a, "Out": loss}


@register_op("soft_relu")
def _soft_relu(ctx, ins, attrs):
    """out = log(1 + exp(clip(x, -t, t))) (reference activation_op.h
    SoftReluFunctor). The clip is straight-through for the gradient:
    the reference backward is dx = dout * (1 - exp(-out)) = sigmoid of
    the CLIPPED input everywhere — a plain jnp.clip would instead kill
    the gradient outside [-t, t]."""
    x = ins["X"][0]
    t = attrs.get("threshold", 40.0)
    xc = x + lax.stop_gradient(jnp.clip(x, -t, t) - x)
    return {"Out": jnp.log1p(jnp.exp(xc))}
