"""Optimizer-update op kernels.

Parity with the reference's optimizer *ops* (operators/sgd_op.cc,
momentum_op, adagrad_op, adam_op, adamax_op, decayed_adagrad_op,
rmsprop_op, adadelta_op, ftrl_op) and with the legacy optimizer math in
paddle/parameter/FirstOrderOptimizer.h:24-346. Each is a pure function
(param, grad, state...) -> (param', state...); the executor threads the
updated persistables back into the scope, and because the whole step is one
traced computation, XLA fuses these updates with the backward pass — the
TPU version of the reference's fused TrainingAlgorithmOp.cu kernels.
"""

from __future__ import annotations

import jax.numpy as jnp

from .registry import register_op
from .selected_rows import SelectedRows, as_dense


def _lr(ins):
    lr = ins["LearningRate"][0]
    return lr.reshape(()) if hasattr(lr, "reshape") else lr


@register_op("sgd")
def _sgd(ctx, ins, attrs):
    g = ins["Grad"][0]
    p = ins["Param"][0]
    if isinstance(g, SelectedRows):
        # reference sgd_op.cc SelectedRows branch: row scatter-add.
        # Duplicate rows accumulate, so this is bit-equal to the dense
        # update on touched rows and a no-op elsewhere.
        upd = (-_lr(ins)) * g.values.astype(p.dtype)
        return {"ParamOut": p.at[g.rows].add(upd, mode="drop")}
    return {"ParamOut": p - _lr(ins) * g}


@register_op("momentum")
def _momentum(ctx, ins, attrs):
    p, g, v = ins["Param"][0], ins["Grad"][0], ins["Velocity"][0]
    mu = attrs["mu"]
    lr = _lr(ins)
    if isinstance(g, SelectedRows):
        # lazy update (reference momentum SelectedRows branch): velocity
        # decays only on touched rows
        r, gv = g.merged()
        gv = gv.astype(p.dtype)
        v_r = jnp.take(v, r, axis=0, mode="clip")
        v_new = mu * v_r + gv
        if attrs.get("use_nesterov", False):
            step = (gv + mu * v_new) * lr
        else:
            step = lr * v_new
        p_new = jnp.take(p, r, axis=0, mode="clip") - step
        return {
            "ParamOut": p.at[r].set(p_new, mode="drop"),
            "VelocityOut": v.at[r].set(v_new, mode="drop"),
        }
    v_out = mu * v + g
    if attrs.get("use_nesterov", False):
        p_out = p - (g + mu * v_out) * lr
    else:
        p_out = p - lr * v_out
    return {"ParamOut": p_out, "VelocityOut": v_out}


@register_op("adagrad")
def _adagrad(ctx, ins, attrs):
    p, g, m = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    eps = attrs.get("epsilon", 1e-6)
    if isinstance(g, SelectedRows):
        # reference adagrad_op.h SelectedRows branch: duplicates merged
        # (MergeAdd), then per-touched-row moment + param update
        r, gv = g.merged()
        gv = gv.astype(p.dtype)
        m_new = jnp.take(m, r, axis=0, mode="clip") + gv * gv
        p_new = jnp.take(p, r, axis=0, mode="clip") - _lr(ins) * gv / (
            jnp.sqrt(m_new) + eps
        )
        return {
            "ParamOut": p.at[r].set(p_new, mode="drop"),
            "MomentOut": m.at[r].set(m_new, mode="drop"),
        }
    m_out = m + g * g
    p_out = p - _lr(ins) * g / (jnp.sqrt(m_out) + eps)
    return {"ParamOut": p_out, "MomentOut": m_out}


@register_op("adam")
def _adam(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    m1, m2 = ins["Moment1"][0], ins["Moment2"][0]
    b1p = ins["Beta1Pow"][0].reshape(())
    b2p = ins["Beta2Pow"][0].reshape(())
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    lr = _lr(ins) * jnp.sqrt(1.0 - b2p) / (1.0 - b1p)
    if isinstance(g, SelectedRows):
        # lazy adam (reference adam_op.h SparseAdamFunctor): moments decay
        # and the param moves only on touched rows; untouched rows keep
        # their state bit-exact. Documented divergence from dense adam,
        # same as the reference's sparse branch.
        r, gv = g.merged()
        gv = gv.astype(p.dtype)
        m1_new = b1 * jnp.take(m1, r, axis=0, mode="clip") + (1.0 - b1) * gv
        m2_new = b2 * jnp.take(m2, r, axis=0, mode="clip") + (
            1.0 - b2
        ) * gv * gv
        p_new = jnp.take(p, r, axis=0, mode="clip") - lr * m1_new / (
            jnp.sqrt(m2_new) + eps
        )
        return {
            "ParamOut": p.at[r].set(p_new, mode="drop"),
            "Moment1Out": m1.at[r].set(m1_new, mode="drop"),
            "Moment2Out": m2.at[r].set(m2_new, mode="drop"),
        }
    m1_out = b1 * m1 + (1.0 - b1) * g
    m2_out = b2 * m2 + (1.0 - b2) * g * g
    p_out = p - lr * m1_out / (jnp.sqrt(m2_out) + eps)
    return {"ParamOut": p_out, "Moment1Out": m1_out, "Moment2Out": m2_out}


@register_op("adamax")
def _adamax(ctx, ins, attrs):
    # no sparse branch for this rule (matches the reference op set):
    # an arriving SelectedRows densifies to the exact dense gradient
    p, g = ins["Param"][0], as_dense(ins["Grad"][0])
    m, inf = ins["Moment"][0], ins["InfNorm"][0]
    b1p = ins["Beta1Pow"][0].reshape(())
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    m_out = b1 * m + (1.0 - b1) * g
    inf_out = jnp.maximum(b2 * inf, jnp.abs(g) + eps)
    lr = _lr(ins) / (1.0 - b1p)
    p_out = p - lr * m_out / inf_out
    return {"ParamOut": p_out, "MomentOut": m_out, "InfNormOut": inf_out}


@register_op("decayed_adagrad")
def _decayed_adagrad(ctx, ins, attrs):
    # no sparse branch for this rule (matches the reference op set):
    # an arriving SelectedRows densifies to the exact dense gradient
    p, g, m = ins["Param"][0], as_dense(ins["Grad"][0]), ins["Moment"][0]
    decay = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    m_out = decay * m + (1.0 - decay) * g * g
    p_out = p - _lr(ins) * g / (jnp.sqrt(m_out) + eps)
    return {"ParamOut": p_out, "MomentOut": m_out}


@register_op("rmsprop")
def _rmsprop(ctx, ins, attrs):
    # no sparse branch for this rule (matches the reference op set):
    # an arriving SelectedRows densifies to the exact dense gradient
    p, g = ins["Param"][0], as_dense(ins["Grad"][0])
    ms, mom = ins["MeanSquare"][0], ins["Moment"][0]
    eps = attrs.get("epsilon", 1e-10)
    decay = attrs.get("decay", 0.9)
    mu = attrs.get("momentum", 0.0)
    ms_out = decay * ms + (1.0 - decay) * g * g
    mom_out = mu * mom + _lr(ins) * g / jnp.sqrt(ms_out + eps)
    p_out = p - mom_out
    return {"ParamOut": p_out, "MomentOut": mom_out, "MeanSquareOut": ms_out}


@register_op("adadelta")
def _adadelta(ctx, ins, attrs):
    # no sparse branch for this rule (matches the reference op set):
    # an arriving SelectedRows densifies to the exact dense gradient
    p, g = ins["Param"][0], as_dense(ins["Grad"][0])
    ag, au = ins["AvgSquaredGrad"][0], ins["AvgSquaredUpdate"][0]
    rho = attrs.get("rho", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    ag_out = rho * ag + (1.0 - rho) * g * g
    update = -jnp.sqrt((au + eps) / (ag_out + eps)) * g
    au_out = rho * au + (1.0 - rho) * update * update
    return {"ParamOut": p + update, "AvgSquaredGradOut": ag_out, "AvgSquaredUpdateOut": au_out}


@register_op("ftrl")
def _ftrl(ctx, ins, attrs):
    # no sparse branch for this rule (matches the reference op set):
    # an arriving SelectedRows densifies to the exact dense gradient
    p, g = ins["Param"][0], as_dense(ins["Grad"][0])
    sq, lin = ins["SquaredAccumulator"][0], ins["LinearAccumulator"][0]
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    lr_power = attrs.get("lr_power", -0.5)
    lr = _lr(ins)
    new_sq = sq + g * g
    if lr_power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq)) / lr
    else:
        sigma = (jnp.power(new_sq, -lr_power) - jnp.power(sq, -lr_power)) / lr
    new_lin = lin + g - sigma * p
    if lr_power == -0.5:
        denom = l2 + jnp.sqrt(new_sq) / lr
    else:
        denom = l2 + jnp.power(new_sq, -lr_power) / lr
    pre = jnp.sign(new_lin) * l1 - new_lin
    p_out = jnp.where(jnp.abs(new_lin) > l1, pre / denom, jnp.zeros_like(p))
    return {"ParamOut": p_out, "SquaredAccumOut": new_sq, "LinearAccumOut": new_lin}


def _prox_project(prox, lr, attrs):
    """Soft-threshold by lr*l1 then shrink by 1/(1+lr*l2) — the shared
    projection of proximal_gd/proximal_adagrad (reference
    proximal_gd_op.h / proximal_adagrad_op.h)."""
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    if l1 > 0:
        return jnp.sign(prox) * (
            jnp.maximum(jnp.abs(prox) - lr * l1, 0.0) / (1.0 + lr * l2)
        )
    return prox / (1.0 + lr * l2)


@register_op("proximal_gd")
def _proximal_gd(ctx, ins, attrs):
    """Proximal gradient descent (reference operators/proximal_gd_op.h)."""
    p, g = ins["Param"][0], as_dense(ins["Grad"][0])
    lr = _lr(ins)
    return {"ParamOut": _prox_project(p - lr * g, lr, attrs)}


@register_op("proximal_adagrad")
def _proximal_adagrad(ctx, ins, attrs):
    """Reference operators/proximal_adagrad_op.h: adagrad moment, then
    the same proximal projection as proximal_gd."""
    p, g = ins["Param"][0], as_dense(ins["Grad"][0])
    m = ins["Moment"][0]
    lr = _lr(ins)
    m_out = m + g * g
    prox = p - lr * g / jnp.sqrt(m_out)
    return {"ParamOut": _prox_project(prox, lr, attrs),
            "MomentOut": m_out}


@register_op("average_accumulates")
def _average_accumulates(ctx, ins, attrs):
    """Sliding-window parameter averaging accumulator (reference
    parameter/AverageOptimizer.cpp:60-115 needSpecialTraversal/
    finishBatch; proto TrainerConfig.proto:70-75: "between
    average_window*N and 2*average_window*N parameters are used").

    Per step: sum_1 += param, counters advance; every kMaxNumAccumulates
    steps sum_1 folds into sum_2 (precision); when the accumulated
    window exceeds min(max_average_window, num_updates*average_window)
    the sums shift into sum_3 and the window restarts. The averaged
    parameter is (sum_1+sum_2+sum_3)/(num_accumulates +
    old_num_accumulates) — an exact arithmetic mean over the last
    [W, 2W] iterates, unlike an EMA.

    All branches lower to jnp.where selects: no data-dependent control
    flow enters the compiled step.
    """
    p = ins["Param"][0]
    s1, s2, s3 = ins["InSum1"][0], ins["InSum2"][0], ins["InSum3"][0]
    na = ins["InNumAccumulates"][0]
    ona = ins["InOldNumAccumulates"][0]
    nu = ins["InNumUpdates"][0]
    rate = float(attrs.get("average_window", 0.0))
    max_w = int(attrs.get("max_average_window", 10000))
    min_w = int(attrs.get("min_average_window", 10000))
    k_max = int(attrs.get("k_max_num_accumulates", 16384))

    nu = nu + 1
    na = na + 1
    s1 = s1 + p.astype(s1.dtype)
    fold = (nu % k_max) == 0
    s2 = jnp.where(fold, s2 + s1, s2)
    s1 = jnp.where(fold, jnp.zeros_like(s1), s1)
    window = jnp.minimum(
        jnp.asarray(float(max_w), jnp.float32),
        nu.astype(jnp.float32) * rate,
    )
    shift = (na >= min_w) & (na.astype(jnp.float32) >= window)
    s3 = jnp.where(shift, s1 + s2, s3)
    s1 = jnp.where(shift, jnp.zeros_like(s1), s1)
    s2 = jnp.where(shift, jnp.zeros_like(s2), s2)
    ona = jnp.where(shift, na, ona)
    na = jnp.where(shift, jnp.zeros_like(na), na)
    return {
        "OutSum1": s1, "OutSum2": s2, "OutSum3": s3,
        "OutNumAccumulates": na, "OutOldNumAccumulates": ona,
        "OutNumUpdates": nu,
    }
