"""Linear-chain CRF kernels: log-likelihood, Viterbi decode, chunk eval.

Parity: reference operators/linear_chain_crf_op.{h,cc} (forward alpha
recursion in normalised-probability space with explicit grad kernel),
operators/crf_decoding_op.h (host-loop Viterbi per sequence),
operators/chunk_eval_op.{h,cc} (host chunk parsing), and the legacy
gserver/layers/LinearChainCRF.cpp.

TPU-first re-design: the ragged batch is padded to [B, T, n] once, the
alpha/delta recursions are one `lax.scan` in LOG space (numerically safer
than the reference's prob-space + per-row normalisation), finished
sequences carry state under a mask, and the backward pass is jax.vjp of
the forward — no hand-written grad kernel. Chunk evaluation is expressed
with vectorised begin/end markers + a running-max chunk-start index
instead of per-sequence host loops.

Transition layout (reference linear_chain_crf_op.h): Transition[0] = start
weights, Transition[1] = end weights, Transition[2:] = [n, n] transition
matrix w[from, to].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op
from .kernels_sequence import lod_key, seg_ids, seg_lengths
from .kernels_rnn import packed_to_padded, padded_to_packed, _seq_T


def _emission_lod(ctx):
    name = ctx.op.inputs["Emission"][0]
    key = lod_key(name)
    if key not in ctx.env:
        raise ValueError("linear_chain_crf needs a LoD (ragged) Emission input")
    return ctx.env[key]


@register_op("linear_chain_crf")
def _linear_chain_crf(ctx, ins, attrs):
    em = ins["Emission"][0]  # [total, n] packed
    tr = ins["Transition"][0]  # [n+2, n]
    label = ins["Label"][0].reshape(-1)  # [total]
    offsets = _emission_lod(ctx)
    total, n = em.shape
    T = _seq_T(ctx, total, offsets)
    B = offsets.shape[0] - 1

    a, b, w = tr[0], tr[1], tr[2:]  # start, end, transitions
    em_p, mask = packed_to_padded(em, offsets, T)  # [B,T,n], [B,T]
    lab_p, _ = packed_to_padded(label, offsets, T)  # [B,T]
    lens = seg_lengths(offsets)  # [B]

    em_t = jnp.moveaxis(em_p, 1, 0)  # [T,B,n]
    mask_t = jnp.moveaxis(mask, 1, 0).astype(em.dtype)  # [T,B]
    lab_t = jnp.moveaxis(lab_p, 1, 0)  # [T,B]

    # --- log partition: alpha recursion --------------------------------
    alpha0 = a[None, :] + em_t[0]  # [B,n]

    def alpha_step(alpha, xs):
        e_t, m_t = xs
        # logsumexp over 'from' axis: alpha [B,n,1] + w [n,n] -> [B,n]
        nxt = jax.nn.logsumexp(alpha[:, :, None] + w[None, :, :], axis=1) + e_t
        keep = m_t[:, None]
        return alpha * (1 - keep) + nxt * keep, alpha

    alpha_last, alphas = lax.scan(alpha_step, alpha0, (em_t[1:], mask_t[1:]))
    log_z = jax.nn.logsumexp(alpha_last + b[None, :], axis=1)  # [B]

    # --- gold path score ------------------------------------------------
    bidx = jnp.arange(B)
    em_score = jnp.sum(
        jnp.take_along_axis(em_t, lab_t[:, :, None], axis=2)[:, :, 0] * mask_t,
        axis=0,
    )  # [B]
    trans_score = jnp.sum(
        w[lab_t[:-1], lab_t[1:]] * mask_t[1:], axis=0
    )  # [B]
    y_first = lab_t[0]  # [B] (every sequence has >= 1 token)
    y_last = lab_p[bidx, jnp.maximum(lens - 1, 0)]
    gold = em_score + trans_score + a[y_first] + b[y_last]

    nll = (log_z - gold).reshape(B, 1)
    # Alpha / *Exps outputs exist for reference-API parity (the reference's
    # grad kernel consumes them; here backward is jax.vjp of this forward)
    return {
        "LogLikelihood": nll,
        "Alpha": jnp.concatenate([alpha0[None], alphas], axis=0),
        "EmissionExps": jnp.exp(em_p),
        "TransitionExps": jnp.exp(tr),
    }


@register_op("crf_decoding")
def _crf_decoding(ctx, ins, attrs):
    em = ins["Emission"][0]  # [total, n]
    tr = ins["Transition"][0]
    offsets = _emission_lod(ctx)
    total, n = em.shape
    T = _seq_T(ctx, total, offsets)
    B = offsets.shape[0] - 1

    a, b, w = tr[0], tr[1], tr[2:]
    em_p, mask = packed_to_padded(em, offsets, T)
    em_t = jnp.moveaxis(em_p, 1, 0)  # [T,B,n]
    mask_t = jnp.moveaxis(mask, 1, 0)  # [T,B] bool
    lens = seg_lengths(offsets)

    delta0 = a[None, :] + em_t[0]

    def viterbi_step(delta, xs):
        e_t, m_t = xs
        scores = delta[:, :, None] + w[None, :, :]  # [B,from,to]
        best = jnp.max(scores, axis=1) + e_t  # [B,n]
        bp = jnp.argmax(scores, axis=1).astype(jnp.int32)  # [B,n]
        keep = m_t[:, None]
        return jnp.where(keep, best, delta), (jnp.where(keep, best, delta), bp)

    _, (deltas_rest, bps) = lax.scan(
        viterbi_step, delta0, (em_t[1:], mask_t[1:])
    )
    deltas = jnp.concatenate([delta0[None], deltas_rest], axis=0)  # [T,B,n]
    # bps[t] holds backpointers INTO step t (from step t+1's perspective):
    # bps[t][b, y_{t+1}] = argmax_from(delta_t[from] + w[from, y_{t+1}])
    bidx = jnp.arange(B)

    def back_step(cur, xs):
        t, delta_t, bp_t = xs
        at_end = t == (lens - 1)
        cand_end = jnp.argmax(delta_t + b[None, :], axis=1).astype(jnp.int32)
        inside = t < (lens - 1)
        cand_in = bp_t[bidx, cur]
        cur = jnp.where(at_end, cand_end, jnp.where(inside, cand_in, cur))
        return cur, cur

    ts = jnp.arange(T - 1, -1, -1)
    # xs aligned reversed: for position t we need bps entering from t+1,
    # i.e. bps[t] (bps has length T-1; pad one dummy tail for t = T-1)
    bp_pad = jnp.concatenate([bps, jnp.zeros((1, B, n), jnp.int32)], axis=0)
    _, path_rev = lax.scan(
        back_step,
        jnp.zeros((B,), jnp.int32),
        (ts, deltas[::-1], bp_pad[::-1][: T]),
    )
    path_padded = jnp.moveaxis(path_rev[::-1], 0, 1)  # [B,T]
    path = padded_to_packed(path_padded, offsets, total).astype(jnp.int64)

    out_name = ctx.op.outputs["ViterbiPath"][0]
    ctx.env[lod_key(out_name)] = offsets
    if ctx.op.inputs.get("Label"):
        lab = ins["Label"][0].reshape(-1)
        # with a Label input the output flips to per-token correctness
        # (reference crf_decoding_op.h:54-62)
        path = (lab == path).astype(jnp.int64)
    return {"ViterbiPath": path.reshape(total, 1)}


# ---------------------------------------------------------------------------
# chunk_eval — operators/chunk_eval_op (IOB/IOE/IOBES/plain schemes)
# ---------------------------------------------------------------------------


def _chunk_markers(labels, seg, first, last, scheme, num_types, ntag, excluded):
    """(in_chunk, begin, end, type) boolean/int vectors per position."""
    in_range = labels < num_types * ntag
    typ = jnp.where(in_range, labels // ntag, num_types)
    tag = jnp.where(in_range, labels % ntag, -1)
    in_chunk = in_range
    if excluded:
        for e in excluded:
            in_chunk = jnp.logical_and(in_chunk, typ != int(e))

    prev_in = jnp.concatenate([jnp.zeros((1,), bool), in_chunk[:-1]])
    prev_typ = jnp.concatenate([jnp.full((1,), -1, typ.dtype), typ[:-1]])
    next_in = jnp.concatenate([in_chunk[1:], jnp.zeros((1,), bool)])
    next_typ = jnp.concatenate([typ[1:], jnp.full((1,), -1, typ.dtype)])
    prev_in = jnp.logical_and(prev_in, jnp.logical_not(first))
    next_in = jnp.logical_and(next_in, jnp.logical_not(last))

    if scheme == "IOB":  # tag 0 = B, 1 = I
        begin = jnp.logical_or(
            tag == 0,
            jnp.logical_or(jnp.logical_not(prev_in), prev_typ != typ),
        )
        nb = jnp.concatenate([tag[1:] == 0, jnp.zeros((1,), bool)])
        end = jnp.logical_or(
            jnp.logical_or(jnp.logical_not(next_in), next_typ != typ), nb
        )
    elif scheme == "IOE":  # tag 0 = I, 1 = E
        pe = jnp.concatenate([jnp.zeros((1,), bool), tag[:-1] == 1])
        begin = jnp.logical_or(
            jnp.logical_or(jnp.logical_not(prev_in), prev_typ != typ), pe
        )
        end = jnp.logical_or(
            tag == 1,
            jnp.logical_or(jnp.logical_not(next_in), next_typ != typ),
        )
    elif scheme == "IOBES":  # 0=B,1=I,2=E,3=S
        begin = jnp.logical_or(
            jnp.logical_or(tag == 0, tag == 3),
            jnp.logical_or(jnp.logical_not(prev_in), prev_typ != typ),
        )
        end = jnp.logical_or(
            jnp.logical_or(tag == 2, tag == 3),
            jnp.logical_or(jnp.logical_not(next_in), next_typ != typ),
        )
    elif scheme == "plain":
        begin = jnp.logical_or(jnp.logical_not(prev_in), prev_typ != typ)
        end = jnp.logical_or(jnp.logical_not(next_in), next_typ != typ)
    else:
        raise ValueError("unknown chunk scheme %r" % scheme)
    begin = jnp.logical_and(begin, in_chunk)
    end = jnp.logical_and(end, in_chunk)
    return in_chunk, begin, end, typ


def _chunk_start_index(begin, in_chunk, total):
    """Running chunk-start position per token (valid where in_chunk):
    chunks are contiguous, so the latest begin <= i is i's chunk start."""
    idx = jnp.arange(total, dtype=jnp.int32)
    starts = jnp.where(begin, idx, -1)
    return lax.associative_scan(jnp.maximum, starts)


@register_op("chunk_eval")
def _chunk_eval(ctx, ins, attrs):
    infer = ins["Inference"][0].reshape(-1)
    label = ins["Label"][0].reshape(-1)
    offsets = ctx.env[lod_key(ctx.op.inputs["Label"][0])]
    total = label.shape[0]
    scheme = attrs["chunk_scheme"]
    num_types = int(attrs["num_chunk_types"])
    excluded = attrs.get("excluded_chunk_types") or []
    ntag = {"IOB": 2, "IOE": 2, "IOBES": 4, "plain": 1}[scheme]

    seg = seg_ids(offsets, total)
    idx = jnp.arange(total, dtype=offsets.dtype)
    first = idx == offsets[seg]
    last = idx == (offsets[seg + 1] - 1)

    _, lb, le, lt = _chunk_markers(
        label, seg, first, last, scheme, num_types, ntag, excluded
    )
    _, ib, ie, it = _chunk_markers(
        infer, seg, first, last, scheme, num_types, ntag, excluded
    )

    ls = _chunk_start_index(lb, None, total)
    is_ = _chunk_start_index(ib, None, total)
    correct = jnp.logical_and(
        jnp.logical_and(le, ie),
        jnp.logical_and(ls == is_, lt == it),
    )
    num_label = jnp.sum(lb).astype(jnp.int64)
    num_infer = jnp.sum(ib).astype(jnp.int64)
    num_correct = jnp.sum(correct).astype(jnp.int64)

    f_infer = jnp.maximum(num_infer, 1).astype(jnp.float32)
    f_label = jnp.maximum(num_label, 1).astype(jnp.float32)
    precision = num_correct.astype(jnp.float32) / f_infer
    recall = num_correct.astype(jnp.float32) / f_label
    f1 = jnp.where(
        num_correct > 0,
        2 * precision * recall / jnp.maximum(precision + recall, 1e-12),
        0.0,
    )
    return {
        "Precision": precision.reshape(1),
        "Recall": recall.reshape(1),
        "F1-Score": f1.reshape(1),
        "NumInferChunks": num_infer.reshape(1),
        "NumLabelChunks": num_label.reshape(1),
        "NumCorrectChunks": num_correct.reshape(1),
    }


@register_op("precision_recall")
def _precision_recall(ctx, ins, attrs):
    """Multi-class precision/recall/F1, batch + accumulated (reference
    operators/precision_recall_op.h: per-class TP/FP/FN states, macro and
    micro averages over 6 metric slots)."""
    idx = ins["Indices"][0].reshape(-1)  # predicted class per example
    labels = ins["Labels"][0].reshape(-1)
    C = int(attrs["class_number"])
    weights = (
        ins["Weights"][0].reshape(-1)
        if ins.get("Weights")
        else jnp.ones_like(idx, dtype=jnp.float32)
    )
    states_in = (
        ins["StatesInfo"][0]
        if ins.get("StatesInfo")
        else jnp.zeros((C, 4), jnp.float32)
    )

    correct = (idx == labels).astype(jnp.float32) * weights
    tp = jax.ops.segment_sum(correct, labels, num_segments=C)
    pred_count = jax.ops.segment_sum(weights, idx, num_segments=C)
    label_count = jax.ops.segment_sum(weights, labels, num_segments=C)
    fp = pred_count - tp
    fn = label_count - tp
    tn = jnp.sum(weights) - tp - fp - fn
    batch_states = jnp.stack([tp, fp, tn, fn], axis=1)  # [C,4]

    def metrics(states):
        tp_, fp_, _, fn_ = (states[:, i] for i in range(4))
        prec = tp_ / jnp.maximum(tp_ + fp_, 1e-12)
        rec = tp_ / jnp.maximum(tp_ + fn_, 1e-12)
        f1 = 2 * prec * rec / jnp.maximum(prec + rec, 1e-12)
        macro = jnp.stack([prec.mean(), rec.mean(), f1.mean()])
        stp, sfp, sfn = tp_.sum(), fp_.sum(), fn_.sum()
        mp = stp / jnp.maximum(stp + sfp, 1e-12)
        mr = stp / jnp.maximum(stp + sfn, 1e-12)
        mf = 2 * mp * mr / jnp.maximum(mp + mr, 1e-12)
        return jnp.concatenate([macro, jnp.stack([mp, mr, mf])])

    accum_states = states_in + batch_states
    return {
        "BatchMetrics": metrics(batch_states),
        "AccumMetrics": metrics(accum_states),
        "AccumStatesInfo": accum_states,
    }
