"""Recurrent-network kernels: dynamic LSTM/GRU over ragged batches.

The reference implements dynamic RNNs by re-ordering a ragged (LoD) batch
into per-timestep dense slices on the fly (gserver/layers/SequenceToBatch.cpp,
fluid operators/math/sequence2batch.*, lstm via operators/math/lstm_compute)
and looping timesteps on the host. TPU-first re-design: the packed batch is
gathered once into a padded ``[batch, T_bucket, ...]`` block (T_bucket is a
static power-of-two bucket of the true max length, chosen by the Executor at
feed time so XLA compiles once per bucket, not per batch), the recurrence is
a single ``lax.scan`` over time-major data — each step is one dense GEMM on
the MXU over the whole batch — and the result is scattered back to packed
layout. Finished sequences carry their state forward unchanged under a mask,
which reproduces the reference's "shrinking active batch" semantics without
dynamic shapes.

Parity targets: operators/lstm_op.{cc,h}, operators/gru_op.{cc,h},
operators/lstm_unit_op, operators/gru_unit_op, operators/sequence_conv_op,
gserver/layers/LstmLayer.cpp, GruLayer.cpp, SequenceConvLayer.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .registry import register_op
from .kernels_sequence import lod_key, seg_ids, seg_lengths

_ACTS = {
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "identity": lambda x: x,
    "linear": lambda x: x,
}


def _act(name):
    return _ACTS[name]


def _seq_T(ctx, total, offsets=None):
    """Static time extent for padded RNN compute. Preference order:

    1. `offsets` when they are trace-time CONSTANTS (e.g. the uniform
       LoD im2sequence emits from static image geometry): the EXACT max
       length — fed-LoD buckets know nothing about graph-produced
       sequences (a too-small bucket would silently truncate the scan),
       and constants can never vary within a compiled program, so
       power-of-two bucketing would only pad the scan with dead steps.
    2. the Executor's bucketed max FED sequence length (ctx.seq_maxlen).
    3. the packed total (correct for any batch, just wasteful — only
       hit on direct build_step_fn uses)."""
    if offsets is not None and not isinstance(offsets, jax.core.Tracer):
        d = np.diff(np.asarray(offsets))
        if d.size and int(d.max()) > 0:
            return int(d.max())
    T = getattr(ctx, "seq_maxlen", None)
    return int(T) if T else int(total)


def packed_to_padded(x, offsets, T, reverse=False):
    """[total, ...] packed -> ([n, T, ...] padded, [n, T] bool mask).

    With reverse=True each sequence is time-flipped into the padded block
    (so a forward scan implements the reference's is_reverse=True)."""
    lens = seg_lengths(offsets)  # [n]
    t = jnp.arange(T, dtype=offsets.dtype)
    if reverse:
        rel = lens[:, None] - 1 - t[None, :]
    else:
        rel = jnp.broadcast_to(t[None, :], (lens.shape[0], T))
    mask = (t[None, :] < lens[:, None]) if not reverse else (rel >= 0)
    idx = offsets[:-1, None] + jnp.clip(rel, 0, None)
    idx = jnp.clip(idx, 0, x.shape[0] - 1)
    return x[idx], mask


def padded_to_packed(h, offsets, total, reverse=False):
    """[n, T, ...] padded -> [total, ...] packed (inverse of the above)."""
    s = seg_ids(offsets, total)  # [total]
    t = jnp.arange(total, dtype=offsets.dtype) - offsets[s]
    if reverse:
        t = seg_lengths(offsets)[s] - 1 - t
    return h[s, jnp.clip(t, 0, h.shape[1] - 1)]


# ---------------------------------------------------------------------------
# dynamic_lstm — operators/lstm_op.h LSTMKernel; gate layout [i, f, c̃, o]
# ---------------------------------------------------------------------------


@register_op("lstm")
def _lstm(ctx, ins, attrs):
    x = ins["Input"][0]           # [total, 4H] (pre-projected by the fc)
    w = ins["Weight"][0]          # [H, 4H] recurrent weight
    bias = ins["Bias"][0] if ins.get("Bias") else None  # [1, 4H] or [1, 7H]
    offsets = ctx.env[lod_key(ctx.op.inputs["Input"][0])]
    n = offsets.shape[0] - 1
    H = w.shape[0]
    total = x.shape[0]
    reverse = bool(attrs.get("is_reverse", False))
    peephole = bool(attrs.get("use_peepholes", True))
    gate_act = _act(attrs.get("gate_activation", "sigmoid"))
    cell_act = _act(attrs.get("cell_activation", "tanh"))
    cand_act = _act(attrs.get("candidate_activation", "tanh"))

    if bias is not None:
        x = x + bias[:, : 4 * H]
    if peephole and bias is not None:
        w_ic = bias[0, 4 * H : 5 * H]
        w_fc = bias[0, 5 * H : 6 * H]
        w_oc = bias[0, 6 * H : 7 * H]
    else:
        w_ic = w_fc = w_oc = None

    T = _seq_T(ctx, total, offsets)
    xp, mask = packed_to_padded(x, offsets, T, reverse=reverse)  # [n,T,4H]
    xp = jnp.swapaxes(xp, 0, 1)          # [T, n, 4H] time-major
    mask_t = jnp.swapaxes(mask, 0, 1)[..., None].astype(x.dtype)  # [T,n,1]

    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((n, H), x.dtype)
    c0 = ins["C0"][0] if ins.get("C0") else jnp.zeros((n, H), x.dtype)

    def step(carry, xm):
        h, c = carry
        xt, m = xm
        g = xt + h @ w                              # [n, 4H] — MXU GEMM
        gi, gf, gc, go = jnp.split(g, 4, axis=1)
        if w_ic is not None:
            gi = gi + c * w_ic
            gf = gf + c * w_fc
        i = gate_act(gi)
        f = gate_act(gf)
        c_new = f * c + i * cand_act(gc)
        if w_oc is not None:
            go = go + c_new * w_oc
        o = gate_act(go)
        h_new = o * cell_act(c_new)
        h_new = m * h_new + (1 - m) * h
        c_new = m * c_new + (1 - m) * c
        return (h_new, c_new), (h_new, c_new)

    (_, _), (hs, cs) = lax.scan(step, (h0, c0), (xp, mask_t))
    hs = jnp.swapaxes(hs, 0, 1)  # [n, T, H]
    cs = jnp.swapaxes(cs, 0, 1)
    hidden = padded_to_packed(hs, offsets, total, reverse=reverse)
    cell = padded_to_packed(cs, offsets, total, reverse=reverse)
    return {"Hidden": hidden, "Cell": cell}


# ---------------------------------------------------------------------------
# dynamic_gru — operators/gru_op.h; weight [H, 3H]: [:, :2H]=update|reset,
# [:, 2H:]=candidate. h' = (1-u)*h + u*c̃ (reference gru_compute convention,
# operators/math/detail/gru_kernel.h:62, gru_unit_op.cc:122).
# ---------------------------------------------------------------------------


@register_op("gru")
def _gru(ctx, ins, attrs):
    x = ins["Input"][0]            # [total, 3H]
    w = ins["Weight"][0]           # [H, 3H]
    bias = ins["Bias"][0] if ins.get("Bias") else None  # [1, 3H]
    offsets = ctx.env[lod_key(ctx.op.inputs["Input"][0])]
    n = offsets.shape[0] - 1
    H = w.shape[0]
    total = x.shape[0]
    reverse = bool(attrs.get("is_reverse", False))
    gate_act = _act(attrs.get("gate_activation", "sigmoid"))
    cand_act = _act(attrs.get("activation", "tanh"))

    if bias is not None:
        x = x + bias
    w_ur = w[:, : 2 * H]   # update|reset
    w_c = w[:, 2 * H :]    # candidate

    T = _seq_T(ctx, total, offsets)
    xp, mask = packed_to_padded(x, offsets, T, reverse=reverse)
    xp = jnp.swapaxes(xp, 0, 1)
    mask_t = jnp.swapaxes(mask, 0, 1)[..., None].astype(x.dtype)
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((n, H), x.dtype)

    def step(h, xm):
        xt, m = xm
        xu, xr, xc = jnp.split(xt, 3, axis=1)
        ur = gate_act(jnp.concatenate([xu, xr], 1) + h @ w_ur)
        u, r = jnp.split(ur, 2, axis=1)
        c = cand_act(xc + (r * h) @ w_c)
        h_new = (1.0 - u) * h + u * c
        h_new = m * h_new + (1 - m) * h
        return h_new, h_new

    _, hs = lax.scan(step, h0, (xp, mask_t))
    hs = jnp.swapaxes(hs, 0, 1)
    hidden = padded_to_packed(hs, offsets, total, reverse=reverse)
    return {"Hidden": hidden}


# ---------------------------------------------------------------------------
# single-step cells (operators/lstm_unit_op.cc, gru_unit_op.cc) — dense,
# used by DynamicRNN-style user loops
# ---------------------------------------------------------------------------


@register_op("lstm_unit")
def _lstm_unit(ctx, ins, attrs):
    x = ins["X"][0]          # [n, 4H] pre-activations
    c_prev = ins["C_prev"][0]
    forget_bias = float(attrs.get("forget_bias", 0.0))
    H = c_prev.shape[-1]
    gi, gf, gc, go = jnp.split(x, 4, axis=1)
    i = jax.nn.sigmoid(gi)
    f = jax.nn.sigmoid(gf + forget_bias)
    c = f * c_prev + i * jnp.tanh(gc)
    h = jax.nn.sigmoid(go) * jnp.tanh(c)
    return {"C": c, "H": h}


@register_op("gru_unit")
def _gru_unit(ctx, ins, attrs):
    x = ins["Input"][0]              # [n, 3H]
    h_prev = ins["HiddenPrev"][0]    # [n, H]
    w = ins["Weight"][0]             # [H, 3H]
    bias = ins["Bias"][0] if ins.get("Bias") else None
    H = h_prev.shape[-1]
    gate_act = _act(attrs.get("gate_activation", "sigmoid"))
    cand_act = _act(attrs.get("activation", "tanh"))
    if bias is not None:
        x = x + bias
    xu, xr, xc = jnp.split(x, 3, axis=1)
    ur = gate_act(jnp.concatenate([xu, xr], 1) + h_prev @ w[:, : 2 * H])
    u, r = jnp.split(ur, 2, axis=1)
    reset_h = r * h_prev
    c = cand_act(xc + reset_h @ w[:, 2 * H :])
    h = (1.0 - u) * h_prev + u * c
    gate = jnp.concatenate([u, r, c], axis=1)
    return {"Gate": gate, "ResetHiddenPrev": reset_h, "Hidden": h}


# ---------------------------------------------------------------------------
# sequence_conv — operators/sequence_conv_op; context window gather + GEMM
# ---------------------------------------------------------------------------


@register_op("sequence_conv")
def _sequence_conv(ctx, ins, attrs):
    x = ins["X"][0]              # [total, D]
    filt = ins["Filter"][0]      # [context_length * D, M]
    offsets = ctx.env[lod_key(ctx.op.inputs["X"][0])]
    total, D = x.shape
    cl = int(attrs.get("contextLength", attrs.get("context_length", 3)))
    cs = int(attrs.get("contextStart", attrs.get("context_start", -(cl // 2))))

    # context window per packed row, zero beyond sequence bounds
    s = seg_ids(offsets, total)                          # [total]
    pos = jnp.arange(total, dtype=offsets.dtype)
    cols = []
    for j in range(cl):
        src = pos + cs + j
        valid = (src >= offsets[s]) & (src < offsets[s + 1])
        src_c = jnp.clip(src, 0, total - 1)
        cols.append(jnp.where(valid[:, None], x[src_c], 0.0))
    ctxmat = jnp.concatenate(cols, axis=1)               # [total, cl*D]
    return {"Out": ctxmat @ filt}


@register_op("lstmp")
def _lstmp(ctx, ins, attrs):
    """LSTM with recurrent projection (reference operators/lstmp_op.h,
    python dynamic_lstmp nn.py:339): the recurrence runs on the
    PROJECTED state r = proj_act(h @ W_proj) [P wide], so the recurrent
    GEMM is [P, 4H] — the classic LSTMP memory/compute saving. Outputs
    the projection sequence and the cell sequence."""
    x = ins["Input"][0]            # [total, 4H]
    w = ins["Weight"][0]           # [P, 4H] recurrent weight over r
    w_proj = ins["ProjWeight"][0]  # [H, P]
    bias = ins["Bias"][0] if ins.get("Bias") else None
    offsets = ctx.env[lod_key(ctx.op.inputs["Input"][0])]
    n = offsets.shape[0] - 1
    H = w_proj.shape[0]
    Pdim = w_proj.shape[1]
    total = x.shape[0]
    reverse = bool(attrs.get("is_reverse", False))
    peephole = bool(attrs.get("use_peepholes", True))
    gate_act = _act(attrs.get("gate_activation", "sigmoid"))
    cell_act = _act(attrs.get("cell_activation", "tanh"))
    cand_act = _act(attrs.get("candidate_activation", "tanh"))
    proj_act = _act(attrs.get("proj_activation", "tanh"))

    if bias is not None:
        x = x + bias[:, : 4 * H]
    if peephole and bias is not None:
        w_ic = bias[0, 4 * H : 5 * H]
        w_fc = bias[0, 5 * H : 6 * H]
        w_oc = bias[0, 6 * H : 7 * H]
    else:
        w_ic = w_fc = w_oc = None

    T = _seq_T(ctx, total, offsets)
    xp, mask = packed_to_padded(x, offsets, T, reverse=reverse)
    xp = jnp.swapaxes(xp, 0, 1)
    mask_t = jnp.swapaxes(mask, 0, 1)[..., None].astype(x.dtype)

    r0 = jnp.zeros((n, Pdim), x.dtype)
    c0 = jnp.zeros((n, H), x.dtype)

    def step(carry, xm):
        r, c = carry
        xt, m = xm
        g = xt + r @ w
        gi, gf, gc, go = jnp.split(g, 4, axis=1)
        if w_ic is not None:
            gi = gi + c * w_ic
            gf = gf + c * w_fc
        i = gate_act(gi)
        f = gate_act(gf)
        c_new = f * c + i * cand_act(gc)
        if w_oc is not None:
            go = go + c_new * w_oc
        o = gate_act(go)
        h_new = o * cell_act(c_new)
        r_new = proj_act(h_new @ w_proj)
        r_new = m * r_new + (1 - m) * r
        c_new = m * c_new + (1 - m) * c
        return (r_new, c_new), (r_new, c_new)

    (_, _), (rs, cs) = lax.scan(step, (r0, c0), (xp, mask_t))
    rs = jnp.swapaxes(rs, 0, 1)
    cs = jnp.swapaxes(cs, 0, 1)
    proj = padded_to_packed(rs, offsets, total, reverse=reverse)
    cell = padded_to_packed(cs, offsets, total, reverse=reverse)
    out_name = ctx.op.outputs["Projection"][0]
    ctx.env[lod_key(out_name)] = offsets
    ctx.env[lod_key(ctx.op.outputs["Cell"][0])] = offsets
    return {"Projection": proj, "Cell": cell}
