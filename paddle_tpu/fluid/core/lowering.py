"""Block -> XLA lowering.

This replaces the reference's op-at-a-time C++ interpreter
(paddle/fluid/framework/executor.cc:80-141, which re-creates every op on
every `Executor::Run`) with whole-block tracing: the op list of a Block is
executed once symbolically under `jax.jit`, producing ONE fused XLA
computation per (program-version, feed-signature). Subsequent steps replay
the compiled artifact; parameters are donated so updates are in-place in
HBM.

Backward: `append_backward` (fluid/backward.py) inserts a single `autodiff`
marker op recording the loss and the (param -> grad-var) map. At lowering
time the ops *before* the marker become the primal function of one
`jax.vjp` call — the vjp primal pass IS the forward pass (no recompute),
its cotangent pass materialises every `X@GRAD` value, and the ops after the
marker (regularizers, clip, optimizer updates) consume those gradients
inside the same traced computation. This is the TPU-native equivalent of
the reference's desc-level `AppendBackward` (framework/backward.cc:523)
without per-op grad kernels.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .registry import LoweringContext, get_kernel
from .selected_rows import SelectedRows, as_dense

AUTODIFF_OP = "autodiff"
# ops handled by the executor itself, not kernels
_SKIP_OPS = frozenset(["feed", "fetch"])

# AMP f32 deny-list: numerically sensitive ops that compute in f32 even
# inside the bf16 forward region (softmax/CE exponentials saturate and
# reductions lose mass in bf16). Float inputs are upcast, float outputs
# downcast back to bf16 so the surrounding region stays bf16. These are
# loss-head / small-tensor ops, so the upcast costs nothing; the
# normalisation layers (batch_norm/layer_norm/lrn) instead compute their
# STATISTICS in f32 inside their kernels (kernels_nn.py) — upcasting the
# whole op there would break conv+BN fusion and tax HBM on the main
# activation path. Mirrors the reference-era AMP black/white lists
# (contrib/mixed_precision in later Paddle; capability parity).
_AMP_F32_OPS = frozenset(
    [
        "softmax", "log_softmax", "sequence_softmax",
        "cross_entropy", "softmax_with_cross_entropy",
        "sigmoid_cross_entropy_with_logits",
        "mean", "reduce_mean", "reduce_sum",
        "exp", "log",
        "warpctc", "linear_chain_crf", "nce", "hsigmoid",
    ]
)
# deny-listed ops whose outputs STAY f32: loss-head values (tiny tensors
# whose bf16 re-quantisation would throw away exactly the precision the
# deny-list bought — cross_entropy -> mean chains keep f32 end to end).
# Mid-network ops (softmax in attention, exp/log) still downcast so the
# surrounding bf16 dataflow is uninterrupted.
_AMP_F32_STICKY = frozenset(
    [
        "cross_entropy", "softmax_with_cross_entropy",
        "sigmoid_cross_entropy_with_logits",
        "mean", "reduce_mean", "reduce_sum",
        "warpctc", "linear_chain_crf", "nce", "hsigmoid",
    ]
)


# ops that read env directly (tensor arrays, sub-blocks): inputs may be
# names with no env binding yet (e.g. the first array_write of an array)
_ENV_OPS = frozenset(
    ["while", "array_write", "array_read", "array_length", "dynamic_rnn",
     "beam_search_decode"]
)


def run_op(ctx: LoweringContext, op, env: Dict[str, Any]):
    """Execute one op symbolically: gather named inputs from env, call the
    kernel, bind named outputs back into env."""
    kernel = get_kernel(op.type)
    ins = {}
    lazy = op.type in _ENV_OPS
    for slot, names in op.inputs.items():
        if lazy:
            ins[slot] = [env.get(n) for n in names]
            continue
        try:
            ins[slot] = [env[n] for n in names]
        except KeyError as e:
            raise RuntimeError(
                "op %r input %s=%r is not available: variable %r was "
                "neither fed nor produced by an earlier op. Common cause:"
                " fetching predictions from the TRAINING program without "
                "feeding labels — optimizer ops keep the loss subgraph "
                "alive; clone(for_test=True) BEFORE optimizer.minimize() "
                "and run the clone instead." % (op.type, slot, names,
                                                e.args[0])
            ) from e
    # sequence kernels read LoD offsets / write output LoD via ctx.env
    ctx.op = op
    ctx.env = env
    # the scope tag rides into HLO metadata (op_name="...op:<type>/...")
    # and survives fusion — the compiled-step profiler maps fused
    # instructions back to op provenance through it (fluid/profiler.py
    # compiled_profile; reference profiler.cc:198 ParseEvents parity)
    with jax.named_scope("op:%s" % op.type):
        outs = kernel(ctx, ins, op.attrs)
    find_var = getattr(ctx.block, "_find_var_recursive", None)
    for slot, names in op.outputs.items():
        if slot not in outs:
            continue
        vals = outs[slot]
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        for name, val in zip(names, vals):
            # honor Variable.stop_gradient (reference backward prunes
            # grad flow at such vars): cut the vjp here so e.g. frozen
            # feature extractors really receive no gradient. Recursive
            # lookup: a sub-block op may write an ancestor block's var.
            var = (
                find_var(name)
                if find_var is not None
                else getattr(ctx.block, "vars", {}).get(name)
            )
            if (
                var is not None
                and getattr(var, "stop_gradient", False)
                and isinstance(val, jax.core.Tracer)
            ):
                val = jax.lax.stop_gradient(val)
            env[name] = val
    _share_lod(op, env)


# ops whose outputs are dense even when inputs are ragged
_LOD_BARRIER_OPS = frozenset(
    [
        "sequence_pool",
        "mean",
        "accuracy",
        "auc",
        "top_k",
        "reduce_sum",
        "reduce_mean",
        "reduce_max",
        "reduce_min",
        "reduce_prod",
        "fill_constant_batch_size_like",
        "shape",
        "isfinite",
        "squared_l2_norm",
    ]
)


def _share_lod(op, env):
    """Default LoD propagation (reference: ShareLoD in each op's InferShape):
    row-wise ops keep their input's raggedness, so any output that hasn't
    set its own @LOD0 inherits the first input's. Sequence kernels that
    compute a new LoD set it explicitly before this runs; reductions that
    collapse the ragged (row) axis are barriers — a reduce over FEATURE
    axes only (dim excludes 0, no reduce_all) stays row-wise and
    propagates (e.g. the per-row dot product feeding an attention's
    sequence_softmax)."""
    from .kernels_sequence import lod_key

    if op.type in _LOD_BARRIER_OPS:
        if not op.type.startswith("reduce_"):
            return
        dims = op.attrs.get("dim", 0)
        dims = list(dims) if isinstance(dims, (list, tuple)) else [dims]
        # negative dims can address the row axis without containing 0
        # (dim=-2 on 2-D); rank is unknown here, so treat any negative
        # dim conservatively as a barrier
        if op.attrs.get("reduce_all", False) or 0 in dims or any(
            d < 0 for d in dims
        ):
            return
    src = None
    for names in op.inputs.values():
        for n in names:
            if lod_key(n) in env:
                src = env[lod_key(n)]
                break
        if src is not None:
            break
    if src is None:
        return
    for names in op.outputs.values():
        for n in names:
            key = lod_key(n)
            if key not in env:
                env[key] = src


def _run_op_f32(ctx: LoweringContext, op, env: Dict[str, Any]):
    """Run one deny-listed op in f32 inside a bf16 region: upcast bf16
    float inputs, run, downcast float outputs back to bf16 so the
    surrounding region stays bf16."""
    saved = {}
    for names in op.inputs.values():
        for n in names:
            v = env.get(n)
            if v is not None and hasattr(v, "dtype") and v.dtype == jnp.bfloat16:
                saved[n] = v
                env[n] = v.astype(jnp.float32)
    run_op(ctx, op, env)
    env.update(saved)  # inputs keep their bf16 values for other readers
    if op.type in _AMP_F32_STICKY:
        return
    for slot, names in op.outputs.items():
        for n in names:
            v = env.get(n)
            if v is not None and hasattr(v, "dtype") and v.dtype == jnp.float32:
                env[n] = v.astype(jnp.bfloat16)


def run_ops(ctx: LoweringContext, ops, env: Dict[str, Any]):
    amp_region = getattr(ctx, "amp_region", False)
    for op in ops:
        if op.type in _SKIP_OPS:
            continue
        if op.type == AUTODIFF_OP:
            _run_autodiff(ctx, op, env)
        elif amp_region and op.type in _AMP_F32_OPS:
            _run_op_f32(ctx, op, env)
        else:
            run_op(ctx, op, env)


def _run_autodiff(ctx, op, env):
    """Fallback path when an autodiff op is executed mid-stream (eager-style
    startup runs). The fast path in `build_step_fn` splits at the marker so
    the vjp wraps the whole forward region instead."""
    raise RuntimeError(
        "autodiff op reached sequential execution; programs with "
        "append_backward must run through build_step_fn"
    )


# optimizer ops with a SelectedRows-aware update branch (reference: the
# SelectedRows specialisations in operators/sgd_op.cc, adam_op.h,
# adagrad_op.h, momentum in later snapshots). A gradient may stay sparse
# only if EVERY tail op consuming it is one of these.
_SPARSE_OPT_OPS = frozenset(["sgd", "momentum", "adagrad", "adam"])


def _find_sparse_sites(fwd_ops, tail_ops, param_names, grad_names, base_env):
    """Select params whose gradient can flow as SelectedRows instead of a
    dense [vocab, dim] cotangent. A param qualifies when every forward
    reader is a `lookup_table` op with is_sparse=True whose Ids are
    leaves (fed or persisted — their static shape sizes the per-site
    cotangent leaf), and every tail consumer of its grad var has a
    sparse update branch (_SPARSE_OPT_OPS). Anything else — shared with
    a dense op, regularized/clipped grads, exotic optimizers — falls
    back to the exact dense path.

    Returns {param_name: [lookup-output var name per site]}.
    """
    pset = set(param_names)
    readers: Dict[str, list] = {}
    for op in fwd_ops:
        for names in op.inputs.values():
            for n in names:
                if n in pset:
                    readers.setdefault(n, []).append(op)
    sites = {}
    for p in param_names:
        ops_r = readers.get(p, [])
        if not ops_r:
            continue
        ok = all(
            op.type == "lookup_table"
            and op.attrs.get("is_sparse", False)
            and op.inputs.get("W") == [p]
            and all(i in base_env for i in op.inputs.get("Ids", []))
            for op in ops_r
        )
        if not ok:
            continue
        gname = grad_names[p]
        consumers = [o for o in tail_ops if gname in o.input_arg_names]
        if not consumers or any(
            o.type not in _SPARSE_OPT_OPS for o in consumers
        ):
            continue
        sites[p] = [op.outputs["Out"][0] for op in ops_r]
    return sites


def _split_at_autodiff(ops) -> Tuple[list, Optional[Any], list]:
    for i, op in enumerate(ops):
        if op.type == AUTODIFF_OP:
            return list(ops[:i]), op, list(ops[i + 1:])
    return list(ops), None, []


def _backward_slice(block, fetch_names, persist_names):
    """Keep only ops that (transitively) contribute to a fetch or write a
    persistable. This is the executor-side equivalent of the reference's
    Prune pass (framework/prune.cc) and means e.g. a for_test clone fetched
    only for predictions never traces its label-dependent loss ops."""
    needed = set(fetch_names)
    kept = []
    for op in reversed(block.ops):
        out_names = set(op.output_arg_names)
        if op.type == AUTODIFF_OP:
            out_names |= set(op.attrs.get("grad_names", []))
        if out_names & needed or out_names & persist_names:
            kept.append(op)
            needed |= set(op.input_arg_names)
            if op.type == AUTODIFF_OP:
                needed.add(op.attrs["loss_name"])
                needed |= set(op.attrs.get("param_names", []))
    return list(reversed(kept))


def lower_block(
    block,
    env: Dict[str, Any],
    base_key=None,
    is_test: bool = False,
    seq_maxlen=None,
    seq_buckets=None,
) -> Dict[str, Any]:
    """Symbolically execute a whole block (including an autodiff marker if
    present) over `env` and return the final environment."""
    return _lower_ops(
        block, block.ops, env, base_key=base_key, is_test=is_test,
        seq_maxlen=seq_maxlen, seq_buckets=seq_buckets,
    )


def _lower_ops(
    block,
    ops,
    env: Dict[str, Any],
    base_key=None,
    is_test: bool = False,
    seq_maxlen=None,
    seq_buckets=None,
    fetch_names=(),
) -> Dict[str, Any]:
    ctx = LoweringContext(block, base_key, is_test=is_test, seq_maxlen=seq_maxlen,
                          seq_buckets=seq_buckets)
    # fetched names are observed by the caller: the While early-exit
    # gate treats them as downstream reads (kernels_control.py)
    ctx.fetch_names = frozenset(fetch_names)
    fwd_ops, ad_op, tail_ops = _split_at_autodiff(ops)

    if ad_op is None:
        run_ops(ctx, fwd_ops, env)
        return env

    loss_name = ad_op.attrs["loss_name"]
    param_names = [p for p in ad_op.attrs["param_names"] if p in env]
    grad_names = dict(zip(ad_op.attrs["param_names"], ad_op.attrs["grad_names"]))
    amp = bool(getattr(block.program, "amp", False))

    base_env = dict(env)
    # SelectedRows sparse-grad path: qualifying embedding params leave the
    # vjp leaf set; their cotangent is captured per lookup site through a
    # zero "delta" leaf of the site's [n_ids, dim] output shape instead of
    # a dense [vocab, dim] array (design note in selected_rows.py)
    sparse_sites = _find_sparse_sites(
        fwd_ops, tail_ops, param_names, grad_names, base_env
    )
    site_delta = {}  # lookup-out var name -> delta leaf name
    for p, outs in sparse_sites.items():
        for o in outs:
            site_delta[o] = o + "@sparse_delta"
    ctx.sparse_sites = site_delta
    dense_param_names = [p for p in param_names if p not in sparse_sites]
    if amp:
        # mixed precision: cast ONLY what the forward region reads (feeds,
        # params, BN state) to bf16 — optimizer state and scalar
        # hyper-accumulators stay f32. A blanket cast would e.g. round
        # Adam's beta2^t accumulator 0.999 -> 1.0 in bf16 and zero the
        # update entirely.
        fwd_inputs = set()
        for op in fwd_ops:
            fwd_inputs |= set(op.input_arg_names)
        for k in fwd_inputs:
            v = base_env.get(k)
            if v is not None and hasattr(v, "dtype") and v.dtype == jnp.float32:
                base_env[k] = v.astype(jnp.bfloat16)

    def fwd(pvals: Dict[str, Any]):
        fenv = dict(base_env)
        if amp:
            pvals = {
                k: v.astype(jnp.bfloat16)
                if hasattr(v, "dtype") and v.dtype == jnp.float32
                else v
                for k, v in pvals.items()
            }
        fenv.update(pvals)
        ctx.amp_region = amp  # f32 deny-list active inside the region
        try:
            run_ops(ctx, fwd_ops, fenv)
        finally:
            ctx.amp_region = False
        loss = fenv[loss_name].astype(jnp.float32)
        return loss, fenv

    primal_params = {p: env[p] for p in dense_param_names}
    for p, outs in sparse_sites.items():
        w = base_env[p]
        for o in outs:
            ids = base_env[
                next(
                    op.inputs["Ids"][0]
                    for op in fwd_ops
                    if op.type == "lookup_table"
                    and op.outputs["Out"] == [o]
                )
            ]
            n = int(np.prod(ids.shape))
            primal_params[site_delta[o]] = jnp.zeros(
                (n, w.shape[1]), dtype=w.dtype
            )
    if bool(getattr(block.program, "remat", False)):
        # memory_optimize(): rematerialize the forward region during the
        # cotangent pass instead of keeping every activation live — the
        # TPU-native form of the reference's liveness-based buffer reuse
        # (memory_optimization_transpiler.py:270), trading FLOPs for HBM
        fwd = jax.checkpoint(fwd)
    loss_val, pullback, fenv = jax.vjp(fwd, primal_params, has_aux=True)
    (grads,) = pullback(jnp.ones_like(loss_val))

    # forward-region env entries win, but persistables that the forward did
    # NOT touch (optimizer state, master copies) keep their f32 originals
    saved = {
        k: v
        for k, v in env.items()
        if k not in fenv or (amp and k in param_names)
    }
    env.clear()
    env.update(fenv)
    env.update(saved)
    for p in dense_param_names:
        g = grads[p]
        env[grad_names[p]] = g.astype(jnp.float32) if amp else g
    for p, outs in sparse_sites.items():
        rows = jnp.concatenate(
            [fenv[o + "@sparse_rows"].reshape(-1) for o in outs]
        )
        vals = jnp.concatenate(
            [
                grads[site_delta[o]].reshape(
                    -1, grads[site_delta[o]].shape[-1]
                )
                for o in outs
            ]
        )
        if amp:
            vals = vals.astype(jnp.float32)
        env[grad_names[p]] = SelectedRows(rows, vals, env[p].shape[0])

    run_ops(ctx, tail_ops, env)
    return env


def profile_ops(
    program,
    env: Dict[str, Any],
    fetch_names: Sequence[str],
    persist_names: Sequence[str],
    collector,
    base_key=None,
    is_test: bool = False,
    seq_maxlen=None,
    seq_buckets=None,
):
    """Interpret-mode timed execution: each forward op runs EAGERLY on the
    device, synchronised and wall-clock-timed into `collector` — the
    per-op cost attribution the reference's profiler table gives
    (platform/profiler.cc:198 ParseEvents), which the fused XLA step
    cannot provide. When the program trains, the backward+update runs
    once more through the normal fused path (timed as one row) so the
    parameter update is applied exactly once with training semantics
    intact; the eager forward pass is the measurement overhead.

    Returns (fetches, new_persist_dict)."""
    import time as _time

    block = program.global_block()
    pruned_ops = _backward_slice(block, list(fetch_names), set(persist_names))
    ctx = LoweringContext(
        block, base_key, is_test=is_test, seq_maxlen=seq_maxlen,
        seq_buckets=seq_buckets,
    )
    fwd_ops, ad_op, _tail = _split_at_autodiff(pruned_ops)

    fwd_env = dict(env)
    if ad_op is not None and bool(getattr(program, "amp", False)):
        # the timed forward must run in the SAME precision as the fused
        # production step: _lower_ops applies the amp bf16 cast only on
        # the training (autodiff) path, so mirror exactly that
        fwd_inputs = set()
        for op in fwd_ops:
            fwd_inputs |= set(op.input_arg_names)
        for k in fwd_inputs:
            v = fwd_env.get(k)
            if v is not None and hasattr(v, "dtype") and v.dtype == jnp.float32:
                fwd_env[k] = jnp.asarray(v).astype(jnp.bfloat16)
    for op in fwd_ops:
        if op.type in _SKIP_OPS:
            continue
        t0 = _time.time()
        run_op(ctx, op, fwd_env)
        for n in op.output_arg_names:
            v = fwd_env.get(n)
            if isinstance(v, jax.Array):
                jax.block_until_ready(v)
        collector.record(op.type, _time.time() - t0)

    if ad_op is None:
        final_env = fwd_env
    else:
        final_env = dict(env)
        t0 = _time.time()
        final_env = _lower_ops(
            block, pruned_ops, final_env, base_key=base_key, is_test=is_test,
            seq_maxlen=seq_maxlen, seq_buckets=seq_buckets,
        )
        for n in list(fetch_names) + [
            p for p in persist_names if p in final_env
        ]:
            v = final_env.get(n)
            if isinstance(v, jax.Array):
                jax.block_until_ready(v)
        collector.record("backward+update (fused)", _time.time() - t0)

    fetches = [as_dense(final_env[n]) for n in fetch_names]
    new_persist = {}
    for n in persist_names:
        if n not in final_env:
            continue
        v = final_env[n]
        # keep the scope dtype stable (same restore as build_step_fn):
        # an amp forward must not persist bf16 state over f32 originals
        orig = env.get(n)
        if (
            orig is not None
            and hasattr(v, "dtype")
            and hasattr(orig, "dtype")
            and v.dtype != orig.dtype
        ):
            v = jnp.asarray(v).astype(orig.dtype)
        new_persist[n] = v
    return fetches, new_persist


def build_step_fn(
    program,
    feed_names: Sequence[str],
    fetch_names: Sequence[str],
    persist_names: Sequence[str],
    is_test: bool = False,
    persist_in: Optional[Sequence[str]] = None,
    seq_maxlen: Optional[int] = None,
    seq_buckets=None,
):
    """Build the pure step function over (persistables, feeds, rng-key).

    Returns (fn, persist_out) where
      fn: (persist: dict, feeds: dict, key) -> (fetches: list, new_persist)
    and persist_out is the static key list of new_persist. Pure and
    jittable; the Executor wraps it in jax.jit with the persist dict
    donated.
    """
    block = program.global_block()
    persist_names = list(persist_names)
    fetch_names = list(fetch_names)
    persist_in = list(persist_in or [])
    pruned_ops = _backward_slice(block, fetch_names, set(persist_names))

    # static set of persistables the step returns: those passed in plus
    # those produced by a kept op (startup programs create params fresh)
    produced = set()
    for op in pruned_ops:
        produced |= set(op.output_arg_names)
    persist_out = sorted(set(persist_in) | (produced & set(persist_names)))

    def step(persist: Dict[str, Any], feeds: Dict[str, Any], key):
        env: Dict[str, Any] = {}
        env.update(persist)
        env.update(feeds)
        env = _lower_ops(
            block, pruned_ops, env, base_key=key, is_test=is_test,
            seq_maxlen=seq_maxlen, seq_buckets=seq_buckets,
            fetch_names=fetch_names,
        )
        # a fetched sparse gradient is observed as its dense equivalent
        fetches = [as_dense(env[n]) for n in fetch_names]
        new_persist = {}
        for n in persist_out:
            v = env[n]
            # under AMP the forward may have produced bf16 values (e.g. BN
            # running stats); persisted state keeps its original dtype so
            # scope dtypes are stable across steps (no recompiles)
            if n in persist and hasattr(v, "dtype") and v.dtype != persist[n].dtype:
                v = v.astype(persist[n].dtype)
            new_persist[n] = v
        return fetches, new_persist

    return step, persist_out


def build_multi_step_fn(
    program,
    feed_names: Sequence[str],
    fetch_names: Sequence[str],
    persist_names: Sequence[str],
    steps: int,
    is_test: bool = False,
    persist_in: Optional[Sequence[str]] = None,
    scanned_feeds: Optional[Sequence[str]] = None,
    seq_maxlen: Optional[int] = None,
    seq_buckets=None,
):
    """K training steps inside ONE compiled computation via lax.scan.

    The reference pays an interpreter pass + kernel launches per batch
    (executor.cc hot loop); on TPU the host should not sit in the step
    loop at all — especially through a remote runtime where every buffer
    handle costs a round trip. Feeds named in `scanned_feeds` must carry a
    leading [steps] dim and are sliced per iteration; other feeds are
    reused each step. Fetches come back stacked [steps, ...].
    """
    from jax import lax

    step, persist_out = build_step_fn(
        program,
        feed_names,
        fetch_names,
        persist_names,
        is_test=is_test,
        persist_in=persist_in,
        seq_maxlen=seq_maxlen,
        seq_buckets=seq_buckets,
    )
    if set(persist_out) != set(persist_in or []):
        raise ValueError(
            "multi-step execution requires the program to update (not "
            "create) persistables; missing from scope: %r"
            % sorted(set(persist_out) - set(persist_in or []))
        )
    scanned = set(scanned_feeds or [])

    def multi(persist, feeds, key):
        bcast = {n: v for n, v in feeds.items() if n not in scanned}
        xs_feeds = {n: v for n, v in feeds.items() if n in scanned}

        def body(carry, xs):
            i, per_step = xs
            f = dict(bcast)
            f.update(per_step)
            fetches, newp = step(carry, f, jax.random.fold_in(key, i))
            return newp, fetches

        idx = jnp.arange(steps)
        new_persist, fetch_stack = lax.scan(body, dict(persist), (idx, xs_feeds))
        return fetch_stack, new_persist

    return multi, persist_out


def build_accum_step_fn(
    program,
    feed_names: Sequence[str],
    fetch_names: Sequence[str],
    persist_names: Sequence[str],
    micro_batches: int,
    persist_in: Optional[Sequence[str]] = None,
):
    """ONE optimizer step over `micro_batches` forward/backward passes
    (gradient accumulation): the feed batch splits into equal chunks
    along axis 0, a lax.scan runs forward+vjp per chunk accumulating
    the MEAN of chunk gradients (exact for mean-reduced losses), and
    the tail ops (regularizer/clip/optimizer) run ONCE on the
    accumulated gradients. The HBM lever the reference never needed:
    activations live for one micro-batch at a time, so the effective
    batch is bounded by steps, not memory.

    Forward-written persistables (BN running stats, counters) update
    per chunk — the same semantics as K small batches. Restrictions
    (v1): training programs only, dense gradients (sparse lookup sites
    fall back dense), no LoD side-band feeds, no AMP/remat flags, and
    fetches must be the loss (returned as the mean over chunks) or
    tail-op outputs.
    """
    if int(micro_batches) < 1:
        raise ValueError("micro_batches must be >= 1")
    if bool(getattr(program, "amp", False)) or bool(
        getattr(program, "remat", False)
    ):
        raise NotImplementedError(
            "gradient accumulation does not compose with program.amp/"
            "remat yet"
        )
    block = program.global_block()
    persist_names = list(persist_names)
    fetch_names = list(fetch_names)
    persist_in = list(persist_in or [])
    pruned = _backward_slice(block, fetch_names, set(persist_names))
    fwd_ops, ad_op, tail_ops = _split_at_autodiff(pruned)
    if ad_op is None:
        raise ValueError(
            "gradient accumulation requires a training program "
            "(optimizer.minimize before run)"
        )
    loss_name = ad_op.attrs["loss_name"]
    # chunk gradients are averaged, which is exact ONLY for mean-reduced
    # losses; a sum-reduced loss would silently train with gradients
    # scaled by 1/micro_batches (ADVICE r4) — detect the loss producer
    # and warn on a definite sum reduction
    producers = {}
    for op in fwd_ops:
        for nm in op.output_arg_names:
            producers[nm] = op  # last write wins
    # walk back through shape-only wrappers so `reshape(reduce_sum(x))`
    # is still recognised as a sum reduction
    _PASSTHROUGH = ("reshape", "reshape2", "squeeze", "unsqueeze", "cast")
    loss_producer = producers.get(loss_name)
    seen = 0
    while (
        loss_producer is not None
        and loss_producer.type in _PASSTHROUGH
        and seen < 8
    ):
        src = loss_producer.input_arg_names
        loss_producer = producers.get(src[0]) if src else None
        seen += 1
    # NOTE: op type "sum" is elementwise N-tensor addition here (linear,
    # so accumulation stays exact) — only a batch-axis reduce_sum is a
    # real mismatch
    _is_batch_sum = False
    if loss_producer is not None and loss_producer.type == "reduce_sum":
        if loss_producer.attrs.get("reduce_all", False):
            _is_batch_sum = True
        else:
            _d = loss_producer.attrs.get("dim", 0)
            _dims = list(_d) if isinstance(_d, (list, tuple)) else [_d]
            # negative dims can address the row axis; rank unknown here,
            # so treat them conservatively (same rule as _share_lod)
            _is_batch_sum = 0 in _dims or any(d < 0 for d in _dims)
    if _is_batch_sum:
        import warnings

        warnings.warn(
            "gradient accumulation averages chunk gradients (exact for "
            "mean-reduced losses) but the loss %r is produced by %r — a "
            "SUM reduction trains with gradients scaled by 1/"
            "micro_batches; reduce the loss with mean() instead"
            % (loss_name, loss_producer.type),
            stacklevel=3,
        )
    grad_names = dict(
        zip(ad_op.attrs["param_names"], ad_op.attrs["grad_names"])
    )
    produced = set()
    for op in pruned:
        produced |= set(op.output_arg_names)
    persist_out = sorted(set(persist_in) | (produced & set(persist_names)))
    missing = set(persist_out) - set(persist_in)
    if missing:
        raise ValueError(
            "gradient accumulation requires the program to update (not "
            "create) persistables; missing from scope: %r" % sorted(missing)
        )
    k = int(micro_batches)

    def step(persist: Dict[str, Any], feeds: Dict[str, Any], key):
        param_names = [
            p for p in ad_op.attrs["param_names"] if p in persist
        ]
        chunks = {}
        for n, v in feeds.items():
            if "@" in n:
                raise NotImplementedError(
                    "gradient accumulation with ragged (LoD) feeds is "
                    "not supported"
                )
            if v.shape[0] % k:
                raise ValueError(
                    "batch dim %d of feed %r is not divisible by "
                    "micro_batches=%d" % (v.shape[0], n, k)
                )
            chunks[n] = v.reshape((k, v.shape[0] // k) + v.shape[1:])

        def body(carry, xs):
            pstate, gsum, i = carry
            ctx = LoweringContext(block, jax.random.fold_in(key, i))
            base_env = dict(pstate)
            base_env.update(xs)

            def fwd(pvals):
                fenv = dict(base_env)
                fenv.update(pvals)
                run_ops(ctx, fwd_ops, fenv)
                return fenv[loss_name].astype(jnp.float32), fenv

            primal = {p: pstate[p] for p in param_names}
            loss, pullback, fenv = jax.vjp(fwd, primal, has_aux=True)
            (g,) = pullback(jnp.ones_like(loss))
            gsum = {p: gsum[p] + g[p] for p in param_names}
            newp = dict(pstate)
            for n2 in pstate:
                if n2 in fenv:
                    v = fenv[n2]
                    if hasattr(v, "dtype") and v.dtype != pstate[n2].dtype:
                        v = v.astype(pstate[n2].dtype)
                    newp[n2] = v
            return (newp, gsum, i + 1), loss

        gzero = {
            p: jnp.zeros(persist[p].shape, jnp.float32)
            for p in param_names
        }
        (pstate, gsum, _), losses = jax.lax.scan(
            body, (dict(persist), gzero, 0), chunks
        )
        env = dict(pstate)
        for p in param_names:
            env[grad_names[p]] = gsum[p] / float(k)
        ctx = LoweringContext(block, key)
        run_ops(ctx, tail_ops, env)
        fetches = []
        for n in fetch_names:
            if n == loss_name:
                # mean over the chunk axis only: keeps the mean op's
                # documented [1] fetch shape (kernels_math.py)
                fetches.append(jnp.mean(losses, axis=0))
            elif n in env:
                fetches.append(as_dense(env[n]))
            else:
                raise KeyError(
                    "fetch %r is neither the loss nor a tail-op output; "
                    "per-chunk intermediates are not retained under "
                    "gradient accumulation" % n
                )
        new_persist = {}
        for n in persist_out:
            v = env[n]
            # scope dtypes stay stable across steps (same restore as
            # build_step_fn): the f32 grad arithmetic must not widen a
            # low-precision param in the scope
            if (
                n in persist
                and hasattr(v, "dtype")
                and v.dtype != persist[n].dtype
            ):
                v = v.astype(persist[n].dtype)
            new_persist[n] = v
        return fetches, new_persist

    return step, persist_out
