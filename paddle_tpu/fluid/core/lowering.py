"""Block -> XLA lowering.

This replaces the reference's op-at-a-time C++ interpreter
(paddle/fluid/framework/executor.cc:80-141, which re-creates every op on
every `Executor::Run`) with whole-block tracing: the op list of a Block is
executed once symbolically under `jax.jit`, producing ONE fused XLA
computation per (program-version, feed-signature). Subsequent steps replay
the compiled artifact; parameters are donated so updates are in-place in
HBM.

Backward: `append_backward` (fluid/backward.py) inserts a single `autodiff`
marker op recording the loss and the (param -> grad-var) map. At lowering
time the ops *before* the marker become the primal function of one
`jax.vjp` call — the vjp primal pass IS the forward pass (no recompute),
its cotangent pass materialises every `X@GRAD` value, and the ops after the
marker (regularizers, clip, optimizer updates) consume those gradients
inside the same traced computation. This is the TPU-native equivalent of
the reference's desc-level `AppendBackward` (framework/backward.cc:523)
without per-op grad kernels.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .registry import LoweringContext, get_kernel

AUTODIFF_OP = "autodiff"
# ops handled by the executor itself, not kernels
_SKIP_OPS = frozenset(["feed", "fetch"])


def run_op(ctx: LoweringContext, op, env: Dict[str, Any]):
    """Execute one op symbolically: gather named inputs from env, call the
    kernel, bind named outputs back into env."""
    kernel = get_kernel(op.type)
    ins = {}
    for slot, names in op.inputs.items():
        ins[slot] = [env[n] for n in names]
    # sequence kernels read LoD offsets / write output LoD via ctx.env
    ctx.op = op
    ctx.env = env
    outs = kernel(ctx, ins, op.attrs)
    for slot, names in op.outputs.items():
        if slot not in outs:
            continue
        vals = outs[slot]
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        for name, val in zip(names, vals):
            env[name] = val
    _share_lod(op, env)


# ops whose outputs are dense even when inputs are ragged
_LOD_BARRIER_OPS = frozenset(
    [
        "sequence_pool",
        "mean",
        "accuracy",
        "auc",
        "top_k",
        "reduce_sum",
        "reduce_mean",
        "reduce_max",
        "reduce_min",
        "reduce_prod",
        "fill_constant_batch_size_like",
        "shape",
        "isfinite",
        "squared_l2_norm",
    ]
)


def _share_lod(op, env):
    """Default LoD propagation (reference: ShareLoD in each op's InferShape):
    row-wise ops keep their input's raggedness, so any output that hasn't
    set its own @LOD0 inherits the first input's. Sequence kernels that
    compute a new LoD set it explicitly before this runs; reductions that
    collapse the ragged axis are barriers."""
    from .kernels_sequence import lod_key

    if op.type in _LOD_BARRIER_OPS:
        return
    src = None
    for names in op.inputs.values():
        for n in names:
            if lod_key(n) in env:
                src = env[lod_key(n)]
                break
        if src is not None:
            break
    if src is None:
        return
    for names in op.outputs.values():
        for n in names:
            key = lod_key(n)
            if key not in env:
                env[key] = src


def run_ops(ctx: LoweringContext, ops, env: Dict[str, Any]):
    for op in ops:
        if op.type in _SKIP_OPS:
            continue
        if op.type == AUTODIFF_OP:
            _run_autodiff(ctx, op, env)
        else:
            run_op(ctx, op, env)


def _run_autodiff(ctx, op, env):
    """Fallback path when an autodiff op is executed mid-stream (eager-style
    startup runs). The fast path in `build_step_fn` splits at the marker so
    the vjp wraps the whole forward region instead."""
    raise RuntimeError(
        "autodiff op reached sequential execution; programs with "
        "append_backward must run through build_step_fn"
    )


def _split_at_autodiff(ops) -> Tuple[list, Optional[Any], list]:
    for i, op in enumerate(ops):
        if op.type == AUTODIFF_OP:
            return list(ops[:i]), op, list(ops[i + 1:])
    return list(ops), None, []


def _backward_slice(block, fetch_names, persist_names):
    """Keep only ops that (transitively) contribute to a fetch or write a
    persistable. This is the executor-side equivalent of the reference's
    Prune pass (framework/prune.cc) and means e.g. a for_test clone fetched
    only for predictions never traces its label-dependent loss ops."""
    needed = set(fetch_names)
    kept = []
    for op in reversed(block.ops):
        out_names = set(op.output_arg_names)
        if op.type == AUTODIFF_OP:
            out_names |= set(op.attrs.get("grad_names", []))
        if out_names & needed or out_names & persist_names:
            kept.append(op)
            needed |= set(op.input_arg_names)
            if op.type == AUTODIFF_OP:
                needed.add(op.attrs["loss_name"])
                needed |= set(op.attrs.get("param_names", []))
    return list(reversed(kept))


def lower_block(
    block,
    env: Dict[str, Any],
    base_key=None,
    is_test: bool = False,
) -> Dict[str, Any]:
    """Symbolically execute a whole block (including an autodiff marker if
    present) over `env` and return the final environment."""
    return _lower_ops(block, block.ops, env, base_key=base_key, is_test=is_test)


def _lower_ops(
    block,
    ops,
    env: Dict[str, Any],
    base_key=None,
    is_test: bool = False,
) -> Dict[str, Any]:
    ctx = LoweringContext(block, base_key, is_test=is_test)
    fwd_ops, ad_op, tail_ops = _split_at_autodiff(ops)

    if ad_op is None:
        run_ops(ctx, fwd_ops, env)
        return env

    loss_name = ad_op.attrs["loss_name"]
    param_names = [p for p in ad_op.attrs["param_names"] if p in env]
    grad_names = dict(zip(ad_op.attrs["param_names"], ad_op.attrs["grad_names"]))

    base_env = dict(env)

    def fwd(pvals: Dict[str, Any]):
        fenv = dict(base_env)
        fenv.update(pvals)
        run_ops(ctx, fwd_ops, fenv)
        loss = fenv[loss_name]
        return loss, fenv

    primal_params = {p: env[p] for p in param_names}
    loss_val, pullback, fenv = jax.vjp(fwd, primal_params, has_aux=True)
    (grads,) = pullback(jnp.ones_like(loss_val))

    env.clear()
    env.update(fenv)
    for p in param_names:
        env[grad_names[p]] = grads[p]

    run_ops(ctx, tail_ops, env)
    return env


def build_step_fn(
    program,
    feed_names: Sequence[str],
    fetch_names: Sequence[str],
    persist_names: Sequence[str],
    is_test: bool = False,
):
    """Build the pure step function over (persistables, feeds, rng-key).

    Returned fn: (persist: dict, feeds: dict, key) ->
                 (fetches: list, new_persist: dict)
    Pure and jittable; the Executor wraps it in jax.jit with the persist
    dict donated.
    """
    block = program.global_block()
    persist_names = list(persist_names)
    fetch_names = list(fetch_names)
    pruned_ops = _backward_slice(block, fetch_names, set(persist_names))

    def step(persist: Dict[str, Any], feeds: Dict[str, Any], key):
        env: Dict[str, Any] = {}
        env.update(persist)
        env.update(feeds)
        env = _lower_ops(block, pruned_ops, env, base_key=key, is_test=is_test)
        fetches = [env[n] for n in fetch_names]
        new_persist = {n: env[n] for n in persist_names if n in env}
        return fetches, new_persist

    return step
