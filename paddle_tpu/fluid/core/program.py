"""Program IR: Program / Block / Operator / Variable / Parameter.

TPU-native re-design of the reference's ProgramDesc object model
(reference: paddle/fluid/framework/framework.proto:34-152 and
python/paddle/v2/fluid/framework.py — Variable:127, Operator:362, Block:633,
Program:830, Parameter:991). Unlike the reference, the IR here is a plain
Python object graph (no protobuf round-trip needed for execution): the
executor lowers a whole Block into a single traced JAX function compiled by
XLA, so the IR only has to be a faithful, introspectable description of the
computation, not a wire format. A proto export lives in `serialization.py`
for save/load_inference_model parity.
"""

from __future__ import annotations

import contextlib
import itertools
import re
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

__all__ = [
    "Variable",
    "Parameter",
    "Operator",
    "Block",
    "Program",
    "default_main_program",
    "default_startup_program",
    "program_guard",
    "switch_main_program",
    "switch_startup_program",
    "unique_name",
    "grad_var_name",
    "convert_np_dtype",
]

_unique_counters: Dict[str, int] = {}


def unique_name(prefix: str) -> str:
    _unique_counters[prefix] = _unique_counters.get(prefix, 0) + 1
    return "%s_%d" % (prefix, _unique_counters[prefix] - 1)


GRAD_VAR_SUFFIX = "@GRAD"


def grad_var_name(name: str) -> str:
    return name + GRAD_VAR_SUFFIX


_DTYPE_ALIASES = {
    "float32": "float32",
    "float64": "float64",
    "float16": "float16",
    "bfloat16": "bfloat16",
    "int8": "int8",
    "int16": "int16",
    "int32": "int32",
    "int64": "int64",
    "uint8": "uint8",
    "bool": "bool",
}


def convert_np_dtype(dtype) -> str:
    """Normalise any dtype spelling (np.dtype, str, jnp dtype) to a str key."""
    if isinstance(dtype, str):
        if dtype not in _DTYPE_ALIASES:
            raise ValueError("unsupported dtype %r" % (dtype,))
        return dtype
    name = np.dtype(dtype).name
    if name not in _DTYPE_ALIASES:
        raise ValueError("unsupported dtype %r" % (dtype,))
    return name


class Variable(object):
    """A named tensor slot in a Block.

    Mirrors reference fluid.framework.Variable (framework.py:127): shape /
    dtype / lod_level / persistable metadata plus convenience numpy-style
    accessors. `shape` may contain -1 for the batch dimension.
    """

    def __init__(
        self,
        block: "Block",
        name: Optional[str] = None,
        shape: Optional[Sequence[int]] = None,
        dtype: Any = None,
        lod_level: int = 0,
        persistable: bool = False,
        stop_gradient: bool = False,
        initializer: Any = None,
        is_data: bool = False,
        **kwargs,
    ):
        self.block = block
        if name is None:
            name = unique_name("_generated_var")
        self.name = name
        self.shape = tuple(int(s) for s in shape) if shape is not None else None
        self.dtype = convert_np_dtype(dtype) if dtype is not None else "float32"
        self.lod_level = lod_level
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.op: Optional[Operator] = None  # generating op, set by append_op
        if initializer is not None:
            initializer(self, block)

    # --- operator sugar (reference: layers/math_op_patch.py) -------------
    def _binary(self, other, op):
        from ..layers import math_op_patch

        return math_op_patch.binary(self, other, op)

    def __add__(self, other):
        return self._binary(other, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binary(other, "elementwise_sub")

    def __rsub__(self, other):
        from ..layers import math_op_patch

        return math_op_patch.binary(self, other, "elementwise_sub", reverse=True)

    def __mul__(self, other):
        return self._binary(other, "elementwise_mul")

    __rmul__ = __mul__

    def __div__(self, other):
        return self._binary(other, "elementwise_div")

    __truediv__ = __div__

    def __lt__(self, other):
        return self._binary(other, "less_than")

    def __le__(self, other):
        return self._binary(other, "less_equal")

    def __gt__(self, other):
        return self._binary(other, "greater_than")

    def __ge__(self, other):
        return self._binary(other, "greater_equal")

    def __repr__(self):
        return "Variable(name=%r, shape=%r, dtype=%s, lod=%d%s)" % (
            self.name,
            self.shape,
            self.dtype,
            self.lod_level,
            ", persistable" if self.persistable else "",
        )

    __str__ = __repr__

    def to_string(self, throw_on_error=False):
        return repr(self)


class Parameter(Variable):
    """A trainable, persistable Variable (reference framework.py:991)."""

    def __init__(self, block, shape, dtype, **kwargs):
        if shape is None or dtype is None:
            raise ValueError("Parameter must have shape and dtype")
        for s in shape:
            if s <= 0:
                raise ValueError("each dimension of Parameter must be > 0")
        kwargs.setdefault("persistable", True)
        super().__init__(block, shape=shape, dtype=dtype, **kwargs)
        self.trainable = kwargs.get("trainable", True)
        self.optimize_attr = kwargs.get("optimize_attr", {"learning_rate": 1.0})
        self.regularizer = kwargs.get("regularizer", None)
        self.gradient_clip_attr = kwargs.get("gradient_clip_attr", None)
        self.do_model_average = kwargs.get("do_model_average", None)
        self.update_hook = kwargs.get("update_hook", None)


class Operator(object):
    """One op node: type, named input/output variable lists, attrs.

    Mirrors reference OpDesc (framework.proto:34) / framework.py:362.
    Inputs/outputs map slot name -> list of variable names (multi-var slots
    are how `sum`, `concat`, `while` etc. take variadic inputs).
    """

    def __init__(
        self,
        block: "Block",
        type: str,
        inputs: Optional[Dict[str, Any]] = None,
        outputs: Optional[Dict[str, Any]] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ):
        self.block = block
        self.type = type
        self.inputs: Dict[str, List[str]] = {}
        self.outputs: Dict[str, List[str]] = {}
        self.attrs: Dict[str, Any] = dict(attrs) if attrs else {}

        def _names(v):
            if v is None:
                return []
            if isinstance(v, (list, tuple)):
                return [x.name if isinstance(x, Variable) else str(x) for x in v]
            return [v.name if isinstance(v, Variable) else str(v)]

        if inputs:
            for slot, v in inputs.items():
                self.inputs[slot] = _names(v)
        if outputs:
            for slot, v in outputs.items():
                names = _names(v)
                self.outputs[slot] = names
                if isinstance(v, (list, tuple)):
                    for x in v:
                        if isinstance(x, Variable):
                            x.op = self
                elif isinstance(v, Variable):
                    v.op = self

    def input(self, slot: str) -> List[str]:
        return self.inputs.get(slot, [])

    def output(self, slot: str) -> List[str]:
        return self.outputs.get(slot, [])

    @property
    def input_arg_names(self):
        return [n for ns in self.inputs.values() for n in ns]

    @property
    def output_arg_names(self):
        return [n for ns in self.outputs.values() for n in ns]

    def attr(self, name: str):
        return self.attrs[name]

    def has_attr(self, name: str) -> bool:
        return name in self.attrs

    def set_attr(self, name: str, val):
        self.attrs[name] = val
        self.block.program._bump_version()

    def __repr__(self):
        ins = {k: v for k, v in self.inputs.items()}
        outs = {k: v for k, v in self.outputs.items()}
        return "{%s: inputs=%r outputs=%r attrs=%r}" % (self.type, ins, outs, self.attrs)


class Block(object):
    """An ordered op list + var symbol table (reference framework.py:633)."""

    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = {}
        self.ops: List[Operator] = []

    @property
    def parent_block(self) -> Optional["Block"]:
        if self.parent_idx < 0:
            return None
        return self.program.block(self.parent_idx)

    def create_var(self, **kwargs) -> Variable:
        var = Variable(self, **kwargs)
        self.vars[var.name] = var
        self.program._bump_version()
        return var

    def create_parameter(self, **kwargs) -> Parameter:
        global_block = self.program.global_block()
        param = Parameter(global_block, **kwargs)
        global_block.vars[param.name] = param
        self.program._bump_version()
        return param

    def var(self, name: str) -> Variable:
        v = self._find_var_recursive(name)
        if v is None:
            raise ValueError("variable %r not found in block %d" % (name, self.idx))
        return v

    def has_var(self, name: str) -> bool:
        return self._find_var_recursive(name) is not None

    def _find_var_recursive(self, name: str) -> Optional[Variable]:
        blk: Optional[Block] = self
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = blk.parent_block
        return None

    def all_parameters(self) -> List[Parameter]:
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    def append_op(self, type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type=type, inputs=inputs, outputs=outputs, attrs=attrs)
        self.ops.append(op)
        from . import infer_shape as _infer

        _infer.infer_op_shapes(op, self)
        self.program._bump_version()
        return op

    def prepend_op(self, type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type=type, inputs=inputs, outputs=outputs, attrs=attrs)
        self.ops.insert(0, op)
        self.program._bump_version()
        return op

    def insert_op(self, index: int, type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type=type, inputs=inputs, outputs=outputs, attrs=attrs)
        self.ops.insert(index, op)
        self.program._bump_version()
        return op

    def remove_op(self, index: int):
        del self.ops[index]
        self.program._bump_version()

    def __repr__(self):
        lines = ["Block(idx=%d, parent=%d)" % (self.idx, self.parent_idx)]
        for v in self.vars.values():
            lines.append("  " + repr(v))
        for op in self.ops:
            lines.append("  " + repr(op))
        return "\n".join(lines)


_program_uid_counter = itertools.count()


class Program(object):
    """A list of Blocks; block 0 is the global block (framework.py:830).

    `version` is bumped on every mutation; the executor uses
    (program.uid, version) as part of its compilation-cache key so that
    appending ops after a run correctly invalidates the cached XLA step.
    `uid` is monotonic across the process — unlike id(), it can never be
    recycled by a later allocation, so a dead Program's cache entries can
    never be replayed for a new one.
    """

    def __init__(self):
        self.uid = next(_program_uid_counter)
        self.blocks: List[Block] = [Block(self, 0)]
        self.current_block_idx = 0
        self.version = 0
        self._seed = 0
        # name -> sharding spec (set by the distributed transpiler / pjit glue)
        self.shardings: Dict[str, Any] = {}
        # mixed precision: forward/backward in bf16, f32 master params
        self.amp = False
        # memory_optimize(): rematerialize the forward region in backward
        self.remat = False

    def _bump_version(self):
        self.version += 1

    # --- blocks ---------------------------------------------------------
    def global_block(self) -> Block:
        return self.blocks[0]

    def block(self, idx: int) -> Block:
        return self.blocks[idx]

    def current_block(self) -> Block:
        return self.blocks[self.current_block_idx]

    def create_block(self, parent_idx: Optional[int] = None) -> Block:
        parent = self.current_block_idx if parent_idx is None else parent_idx
        blk = Block(self, len(self.blocks), parent)
        self.blocks.append(blk)
        self.current_block_idx = blk.idx
        self._bump_version()
        return blk

    def rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    # --- random seed (reference framework.py Program.random_seed) -------
    @property
    def random_seed(self):
        return self._seed

    @random_seed.setter
    def random_seed(self, seed):
        self._seed = int(seed)

    # --- convenience ----------------------------------------------------
    def list_vars(self):
        for blk in self.blocks:
            for v in blk.vars.values():
                yield v

    def clone(self, for_test: bool = False) -> "Program":
        """Deep-copy the program. With for_test=True, ops flip to inference
        behaviour (dropout/batch_norm read `is_test`)."""
        import copy

        p = Program.__new__(Program)
        p.uid = next(_program_uid_counter)
        p.blocks = []
        p.current_block_idx = self.current_block_idx
        p.version = self.version
        p._seed = self._seed
        p.shardings = dict(self.shardings)
        p.amp = self.amp
        p.remat = self.remat
        for blk in self.blocks:
            nb = Block(p, blk.idx, blk.parent_idx)
            for name, v in blk.vars.items():
                nv = copy.copy(v)
                nv.block = nb
                nv.op = None
                nb.vars[name] = nv
            p.blocks.append(nb)
        for blk, nb in zip(self.blocks, p.blocks):
            for op in blk.ops:
                nop = Operator(nb, op.type)
                nop.inputs = {k: list(v) for k, v in op.inputs.items()}
                nop.outputs = {k: list(v) for k, v in op.outputs.items()}
                nop.attrs = copy.deepcopy(
                    {k: v for k, v in op.attrs.items() if not k.startswith("_py_")}
                )
                # non-copyable python attrs (e.g. callables) are shared
                for k, v in op.attrs.items():
                    if k.startswith("_py_"):
                        nop.attrs[k] = v
                if for_test and "is_test" in nop.attrs:
                    nop.attrs["is_test"] = True
                nb.ops.append(nop)
        return p

    def _sub_block_outer_reads(self, op) -> set:
        """Names an op's sub-block (recursively) reads from OUTSIDE it.
        Control-flow ops list most reads explicitly (While builds its
        X list from the sub-block), but a sub-block op may also reference
        an outer name directly — pruning must treat those as inputs too
        (reference prune.cc walks sub-blocks the same way)."""
        idx = op.attrs.get("sub_block")
        if idx is None:
            return set()
        sub = self.block(idx)
        produced, reads = set(), set()
        for sop in sub.ops:
            # order-aware: a name read BEFORE the sub-block produces it is
            # an outer dependency (matches While.block()'s reads list and
            # reference prune.cc)
            reads |= (set(sop.input_arg_names) - produced)
            reads |= (self._sub_block_outer_reads(sop) - produced)
            produced |= set(sop.output_arg_names)
        return reads

    def prune(self, targets) -> "Program":
        """Return a clone containing only ops needed to compute `targets`
        (reference: framework/prune.cc via Program.prune). Dependency
        tracing descends through `sub_block` attrs (while, dynamic_rnn),
        so e.g. a beam-search decoder program prunes correctly."""
        if not isinstance(targets, (list, tuple)):
            targets = [targets]
        target_names = set(
            t.name if isinstance(t, Variable) else str(t) for t in targets
        )
        p = self.clone()
        blk = p.global_block()
        needed = set(target_names)
        kept = []
        for op in reversed(blk.ops):
            if set(op.output_arg_names) & needed or op.type in ("feed",):
                kept.append(op)
                needed |= set(op.input_arg_names)
                needed |= p._sub_block_outer_reads(op)
        blk.ops = list(reversed(kept))
        p._bump_version()
        return p

    def __repr__(self):
        return "\n".join(repr(b) for b in self.blocks)

    to_string = lambda self, throw_on_error=False: repr(self)


_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


def switch_main_program(program: Program) -> Program:
    global _main_program
    prev, _main_program = _main_program, program
    return prev


def switch_startup_program(program: Program) -> Program:
    global _startup_program
    prev, _startup_program = _startup_program, program
    return prev


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    prev_main = switch_main_program(main_program)
    prev_startup = None
    if startup_program is not None:
        prev_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(prev_main)
        if prev_startup is not None:
            switch_startup_program(prev_startup)
